"""Fused SPMD train step.

ref: the reference's training loop is CachedOp::Forward +
Imperative::Backward + kvstore push/pull + optimizer_op updates, each a
separate engine-scheduled stage (SURVEY.md §3.2/§3.3).  TPU-native, ALL of it
— forward, loss, backward, cross-device gradient reduction, optimizer update
— is one jitted XLA program over a sharded mesh: XLA inserts the ICI
collectives where the `dp` axis demands them (the KVStore allreduce), overlaps
them with compute, and fuses the whole optimizer (the reference's
`multi_sgd_update`/`multi_lamb` multi-tensor fusion, taken to 100%).

Usage:
    mesh = parallel.make_mesh(dp=8)
    step = parallel.TrainStep(net, loss_fn, optimizer, mesh=mesh)
    for data, label in loader:
        loss = step(data, label)          # sharded, async
    step.sync_params_to_net()             # reflect into Gluon Parameters
"""
from __future__ import annotations

import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from .. import random as _random
from .. import autograd as _autograd
from ..fault import fire as _fire
from ..elastic import NonFiniteAbortError
from .. import profiler as _profiler
from .. import telemetry as _telemetry
from ..profiler import scope as _pscope
from ..ndarray import NDArray
from ..gluon.block import Block, _flatten_nd, _unflatten_nd
from .mesh import MeshScope, default_mesh
from .sharding import ShardingRules, batch_spec, param_sharding
from .functional import (FunctionalState, functional_call,
                         param_names_and_values, trainable_split)
from .functional_opt import pure_update, state_template
from . import quantize as _quantize
from .mesh import shard_map as _shard_map

__all__ = ["TrainStep", "EvalStep", "all_finite_rows", "add_transfer_hook",
           "remove_transfer_hook"]


def all_finite_rows(arrays):
    """Per-example all-finite verdict over batch-major arrays.

    The serving-side counterpart of ``TrainStep(skip_nonfinite=True)``'s
    fused guard: the same isfinite/and reduction, taken per ROW instead
    of over the whole update, so the InferenceServer can fail exactly the
    poisoned request in a batch while its neighbours (and the server)
    carry on.  ``arrays`` is one array or a list whose leading axis is
    the batch; returns a host bool mask of shape ``(batch,)`` — True
    where every element of that example's outputs is finite."""
    mask = None
    for a in arrays if isinstance(arrays, (list, tuple)) else (arrays,):
        x = a._data if isinstance(a, NDArray) else a
        if isinstance(x, np.ndarray):
            # already on host (the serving path lands here after its
            # outputs were pulled for splitting): a host reduction —
            # round-tripping through the device would ADD two transfers
            # plus a sync per batch
            m = np.isfinite(x.reshape((x.shape[0], -1))).all(axis=1)
        else:
            # still on device: reduce there, ship back one bool per row
            m = np.asarray(jnp.all(
                jnp.isfinite(jnp.reshape(x, (x.shape[0], -1))), axis=1))
        mask = m if mask is None else np.logical_and(mask, m)
    return np.asarray(mask)

# Observers of actual host→device batch transfers (called as fn(leaf,
# sharding) right before each real device_put in _put_batch — NOT for
# pre-placed batches, which skip the put).  Tests and the profiler use this
# to assert/see that the async feed does exactly one transfer per leaf.
_TRANSFER_HOOKS = []


def add_transfer_hook(fn):
    """Register ``fn(leaf, sharding)`` to run on every real batch H2D put."""
    _TRANSFER_HOOKS.append(fn)
    return fn


def remove_transfer_hook(fn):
    _TRANSFER_HOOKS.remove(fn)


def _leaves(args):
    nds, tree = _flatten_nd(args)
    return [a._data for a in nds], tree


# decorrelates the gradient-quantizer rounding stream from the forward
# pass's dropout stream (both fold from the step's one PRNG key)
_GRADQ_SALT = 0x6A5D


def _coerce_arrays(v):
    """Accept raw numpy / jax arrays as batch leaves (wrap into NDArray so
    they flatten as data, not as static tree structure).  numpy stays in
    host memory — placement happens once, in ``_put_batch``."""
    if isinstance(v, (tuple, list)):
        return tuple(_coerce_arrays(x) for x in v)
    if isinstance(v, (np.ndarray, jax.Array)):
        return NDArray(v)
    return v


def _put_batch(leaf, sharding):
    """Place one batch leaf on the mesh.

    Single-process: plain device_put (the leaf is the full global batch).
    Multi-process (``jax.distributed``): the leaf is this worker's LOCAL
    shard — the reference's per-worker data partition (each worker reads its
    own slice of the dataset; SURVEY §3.3) — so the global batch is assembled
    from per-process shards without any cross-host copy.  A leaf that is
    already a global (not fully addressable) jax.Array is already placed;
    hand it to device_put for a sharding-to-sharding transfer instead.

    A leaf that ALREADY carries the target sharding (a DevicePrefetcher
    placed it while the previous step ran) is returned as-is: no second
    device_put, no transfer-hook callback — the async feed's steady state
    costs zero extra HBM traffic."""
    if isinstance(leaf, jax.Array) \
            and getattr(leaf, "sharding", None) == sharding:
        return leaf
    if jax.process_count() > 1:
        if not (isinstance(leaf, jax.Array) and not leaf.is_fully_addressable):
            for fn in _TRANSFER_HOOKS:
                fn(leaf, sharding)
            return jax.make_array_from_process_local_data(
                sharding, np.asarray(leaf))
    for fn in _TRANSFER_HOOKS:
        fn(leaf, sharding)
    return jax.device_put(leaf, sharding)


class TrainStep:
    """Compiled (params, states, batch) → (params', states', loss) on a mesh."""

    def __init__(self, net, loss_fn, optimizer, mesh=None, rules=None,
                 data_spec=None, loss_reduce="mean", donate_batch=False,
                 skip_nonfinite=False, nonfinite_budget=10,
                 grad_reduce="f32", heartbeat=None):
        self.net = net
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh if mesh is not None else default_mesh()
        self.rules = rules or ShardingRules()
        self._data_pspec = data_spec if data_spec is not None \
            else batch_spec(self.mesh)
        self._loss_reduce = loss_reduce
        # donate_batch=True donates the batch buffers to the XLA program so
        # a prefetched feed costs no steady-state HBM beyond the in-flight
        # batches.  Only safe when every batch is consumed exactly once
        # (DevicePrefetcher feed) — NOT when the caller re-steps the same
        # arrays (bench-style loops).
        self._donate_batch = bool(donate_batch)
        # skip_nonfinite=True guards the update with a fused all-finite
        # check over loss+grads INSIDE the compiled program: a NaN/Inf
        # batch leaves params, optimizer state, aux state and the step
        # counter untouched (the update is a select, not a branch — no
        # retrace, no host round-trip beyond the verdict scalar).  After
        # ``nonfinite_budget`` CONSECUTIVE skips the step aborts with a
        # diagnostic instead of silently treading water while the run
        # diverges; ``nonfinite_budget=None`` disables the abort.
        self._skip_nonfinite = bool(skip_nonfinite)
        self._nonfinite_budget = nonfinite_budget
        # grad_reduce selects the cross-device gradient wire format:
        # "f32" keeps the implicit sharding-inserted full-precision
        # collective; "bf16"/"int8" route the backward pass through an
        # explicit shard_map reduction stage over the dp axis
        # (parallel.quantize.reduce_gradients) — same jitted program,
        # compressed collective payloads, stochastic rounding driven by
        # the step's PRNG key.  Quantized modes need a pure
        # data-parallel mesh: the explicit stage replicates params per
        # device, which a tp/fsdp-sharded layout would contradict.
        if grad_reduce not in _quantize.GRAD_REDUCE_MODES:
            raise ValueError(
                f"TrainStep: grad_reduce={grad_reduce!r} not in "
                f"{_quantize.GRAD_REDUCE_MODES}")
        if grad_reduce != "f32":
            if "dp" not in self.mesh.shape:
                raise ValueError(
                    f"TrainStep: grad_reduce={grad_reduce!r} needs a "
                    f"'dp' mesh axis to reduce over (mesh axes: "
                    f"{dict(self.mesh.shape)})")
            extra = {a: s for a, s in self.mesh.shape.items()
                     if a != "dp" and s > 1}
            if extra:
                raise ValueError(
                    f"TrainStep: grad_reduce={grad_reduce!r} supports "
                    f"pure data-parallel meshes only; model-parallel "
                    f"axes {extra} shard the params the explicit "
                    f"reduction stage would replicate")
        self._grad_reduce = grad_reduce
        # heartbeat: an elastic.Heartbeat stamped after every completed
        # step (host side, post-dispatch) — the supervised-training
        # liveness wire (docs/api.md "Elastic training")
        self._heartbeat = heartbeat
        self.skipped_steps = 0
        self.consecutive_skips = 0
        self._skip_counter = _profiler.Counter(
            None, "TrainStep::nonfinite_skips")
        self._built = False
        self._jit = None
        self._num_update = optimizer.begin_num_update
        # feed-wait attribution for the per-step span (ISSUE 15): the
        # cumulative DevicePrefetcher consumer-wait reading at the last
        # traced step, so each step span carries the wait accrued since
        self._feed_wait_seen = None

    @property
    def data_sharding(self):
        """The NamedSharding batch leaves are placed with — what a
        DevicePrefetcher needs to pre-place batches this step will accept
        without a second transfer."""
        return NamedSharding(self.mesh, self._data_pspec)

    # --------------------------------------------------------------- build --
    def _batch_axis(self):
        """Index of the dp-sharded (batch) axis in the data pspec."""
        for i, el in enumerate(self._data_pspec):
            names = el if isinstance(el, tuple) else (el,)
            if "dp" in names:
                return i
        return 0

    def _build(self, sample_args):
        net = self.net
        if any(p._deferred_init is not None
               for p in net.collect_params().values()):
            # shape-inference dry run on a batch-1 slice: deferred init only
            # needs feature dims, and a full-batch eager forward would both
            # waste a step of compute and OOM at large batch sizes
            ax = self._batch_axis()
            nds, tree = _flatten_nd(sample_args)
            small = _unflatten_nd(tree, tuple(
                NDArray(jax.lax.slice_in_dim(jnp.asarray(a._data), 0, 1, axis=ax))
                for a in nds))
            with _autograd.pause(), MeshScope(self.mesh):
                Block.__call__(net, *small)
        names, plist, arrays = param_names_and_values(net)
        self._names, self._plist = names, plist
        self._train_idx, self._aux_idx = trainable_split(plist)
        shardings = param_sharding(names, [a.shape for a in arrays],
                                   self.mesh, self.rules)
        self._param_shardings = shardings
        arrays = [jax.device_put(a, s) for a, s in zip(arrays, shardings)]
        self._train_arrays = [arrays[i] for i in self._train_idx]
        self._aux_arrays = [arrays[i] for i in self._aux_idx]
        self._states = tuple(
            tuple(jax.device_put(s, shardings[i])
                  for s in state_template(self.optimizer, arrays[i]))
            for i in self._train_idx)
        # static per-param lr/wd multipliers (ref: Optimizer._get_lr/_get_wd)
        self._lr_mults = [plist[i].lr_mult for i in self._train_idx]
        self._wd_mults = [plist[i].wd_mult for i in self._train_idx]
        self._repl = NamedSharding(self.mesh, PartitionSpec())
        # device_put so t's aval carries the mesh like the jit outputs do —
        # otherwise step 2 retraces (t: i32[]({}) vs i32[]({Auto: (dp,)}))
        self._t = jax.device_put(jnp.zeros((), jnp.int32) + self._num_update,
                                 self._repl)
        self._built = True

    def _base_lr(self):
        # evaluated at the post-increment count, matching the eager path
        # (Optimizer._update_count runs before _get_lr)
        opt = self.optimizer
        if opt.lr_scheduler is not None:
            return float(opt.lr_scheduler(self._num_update + 1))
        return float(opt.lr)

    def _compile(self, data_tree, label_tree, n_data):
        net, opt = self.net, self.optimizer
        plist = self._plist
        train_idx, aux_idx = self._train_idx, self._aux_idx
        lr_mults, wd_mults = self._lr_mults, self._wd_mults
        loss_fn, reduce = self.loss_fn, self._loss_reduce
        state_holder = FunctionalState()

        def fn(train_arrays, aux_arrays, states, t, key, lr, *batch):
            data_leaves = list(batch[:n_data])
            label_leaves = list(batch[n_data:])

            def value_grad(ta_in, aux_in, key_in, dl, ll):
                def loss_of(ta):
                    pa = [None] * len(plist)
                    for i, a in zip(train_idx, ta):
                        pa[i] = a
                    for i, a in zip(aux_idx, aux_in):
                        pa[i] = a
                    # mesh visible to mesh-aware ops (ring/ulysses attn)
                    with MeshScope(self.mesh):
                        outs = functional_call(net, plist, pa, data_tree,
                                               dl, key_in, True,
                                               state_holder)
                    out_nd = _unflatten_nd(state_holder.out_tree,
                                           tuple(NDArray(o) for o in outs))
                    lab_nd = _unflatten_nd(label_tree,
                                           tuple(NDArray(l) for l in ll))
                    if isinstance(lab_nd, tuple) and len(lab_nd) == 1:
                        lab_nd = lab_nd[0]
                    loss = loss_fn(out_nd, lab_nd)
                    lv = loss._data if isinstance(loss, NDArray) else loss
                    lv = jnp.mean(lv) if reduce == "mean" else jnp.sum(lv)
                    mut = [m for _, m in state_holder.mutated]
                    return lv.astype(jnp.float32), mut

                return jax.value_and_grad(loss_of, has_aux=True)(ta_in)

            if self._grad_reduce == "f32":
                # implicit path: grads of the sharded-batch loss — the
                # SPMD partitioner inserts the full-precision all-reduce
                (loss, mut), grads = value_grad(
                    train_arrays, aux_arrays, key, data_leaves,
                    label_leaves)
            else:
                # explicit path: per-device local grads inside shard_map,
                # reduced by parallel.quantize with a compressed wire
                # format.  The local loss is the mean/sum over the LOCAL
                # shard; pmean/psum restores the global reduction (equal
                # shard sizes — sharding already guarantees that).
                dp = self.mesh.shape["dp"]
                mode = self._grad_reduce

                def local_step(ta, aux, k, *leaves):
                    # per-device key: forward RNG (dropout) and the
                    # rounding streams decorrelate across replicas
                    dk = jax.random.fold_in(k, jax.lax.axis_index("dp"))
                    (lv, mu), gr = value_grad(ta, aux, dk,
                                              list(leaves[:n_data]),
                                              list(leaves[n_data:]))
                    gr = _quantize.reduce_gradients(
                        gr, "dp", dp, mode=mode,
                        key=jax.random.fold_in(dk, _GRADQ_SALT),
                        reduce=reduce)
                    lv = (jax.lax.pmean if reduce == "mean"
                          else jax.lax.psum)(lv, "dp")
                    # aux updates (BN running stats) are per-shard here:
                    # average the float ones; anything non-float is
                    # assumed replica-identical
                    # mxlint: disable=spmd-collective-in-loop -- deliberate
                    # per-leaf comprehension over the short aux-state
                    # list (BN running stats): leaves have heterogeneous
                    # shapes and only float ones reduce
                    mu = [jax.lax.pmean(m, "dp")
                          if jnp.issubdtype(m.dtype, jnp.floating) else m
                          for m in mu]
                    return lv, mu, gr

                repl = PartitionSpec()
                loss, mut, grads = _shard_map(
                    local_step, mesh=self.mesh,
                    in_specs=(repl, repl, repl)
                    + tuple([self._data_pspec] * len(batch)),
                    out_specs=(repl, repl, repl),
                    check_vma=False)(train_arrays, aux_arrays, key, *batch)
            t1 = t + 1
            new_train, new_states = [], []
            for k, (w, g, s) in enumerate(zip(train_arrays, grads, states)):
                lr_k = lr * lr_mults[k]
                wd_k = opt.wd * wd_mults[k]
                nw, ns = pure_update(opt, w, g, s, t1, lr_k, wd_k)
                new_train.append(nw)
                new_states.append(ns)
            # aux-state writeback (BatchNorm running stats — the reference's
            # aux_states path in cached_op.cc)
            mut_map = {i: v for (i, _), v in zip(state_holder.mutated, mut)}
            new_aux = [mut_map.get(i, a) for i, a in zip(aux_idx, aux_arrays)]
            if not self._skip_nonfinite:
                return new_train, new_aux, tuple(new_states), t1, loss
            # fused all-finite guard: one reduction over loss+grads, then
            # every state transition becomes a select against it.  XLA
            # fuses the isfinite/and tree into the backward pass; a bad
            # batch costs the same step wall-clock as a good one.
            finite = jnp.all(jnp.isfinite(loss))   # scalar even when
            for g in grads:                        # loss_reduce="none"
                finite = jnp.logical_and(finite,
                                         jnp.all(jnp.isfinite(g)))
            keep = lambda new, old: jnp.where(finite, new, old)  # noqa: E731
            new_train = [keep(n, o) for n, o in zip(new_train, train_arrays)]
            new_states = [tuple(keep(n, o) for n, o in zip(ns, os))
                          for ns, os in zip(new_states, states)]
            new_aux = [keep(n, o) for n, o in zip(new_aux, aux_arrays)]
            t1 = jnp.where(finite, t1, t)
            return new_train, new_aux, tuple(new_states), t1, loss, finite

        train_sh = [self._param_shardings[i] for i in train_idx]
        aux_sh = [self._param_shardings[i] for i in aux_idx]
        state_sh = tuple(tuple(train_sh[k] for _ in s)
                         for k, s in enumerate(self._states))
        dat_sh = NamedSharding(self.mesh, self._data_pspec)
        in_sh = (train_sh, aux_sh, state_sh, self._repl, self._repl,
                 self._repl)
        out_sh = (train_sh, aux_sh, state_sh, self._repl, self._repl)
        if self._skip_nonfinite:
            out_sh = out_sh + (self._repl,)
        donate = (0, 1, 2)
        if self._donate_batch:
            # batch leaves sit after (train, aux, states, t, key, lr)
            donate += tuple(range(6, 6 + n_data + self._n_label))
        return jax.jit(
            fn,
            in_shardings=in_sh + tuple([dat_sh] * (n_data + self._n_label)),
            out_shardings=out_sh,
            donate_argnums=donate)

    # ---------------------------------------------------------------- call --
    def __call__(self, data, label):
        return self.step(data, label)

    def step(self, data, label):
        with _pscope("TrainStep.step", cat="step"):
            return self._step(data, label)

    def _prepare(self, data, label):
        """Everything a step needs short of touching the device: coerce
        the batch args, run the deferred-init build on first use, and
        (re)compile the jit program when the signature changed.  Shared
        by ``_step`` (which then places the batch and executes) and the
        AOT costing path (``lower``/``cost_analysis``, which never
        executes).  Returns the flattened (data_leaves, label_leaves)."""
        data, label = _coerce_arrays(data), _coerce_arrays(label)
        data_args = data if isinstance(data, (tuple, list)) else (data,)
        data_args = tuple(data_args)
        if not self._built:
            self._build(data_args)
        data_leaves, data_tree = _leaves(data_args)
        label_args = label if isinstance(label, (tuple, list)) else (label,)
        label_leaves, label_tree = _leaves(tuple(label_args))
        sig = (data_tree, label_tree,
               tuple((l.shape, str(l.dtype)) for l in data_leaves),
               tuple((l.shape, str(l.dtype)) for l in label_leaves))
        if self._jit is None or sig != getattr(self, "_sig", None):
            self._n_label = len(label_leaves)
            self._jit = self._compile(data_tree, label_tree, len(data_leaves))
            self._sig = sig
            self._last_avals = None  # refresh lazily on the next step
            self._cost_cache = None
            self._compiled_cache = None
            self._fresh_jit = True
        return data_leaves, label_leaves

    def _invoke(self, args):
        """The one jit dispatch of a step (the donated first call
        suppresses XLA's expected "donated buffers were not usable"
        notice — for that compile only, not process-wide)."""
        if self._donate_batch and getattr(self, "_fresh_jit", False):
            import warnings
            with warnings.catch_warnings():
                warnings.filterwarnings(
                    "ignore", message="Some donated buffers were not usable")
                out = self._jit(*args)
            self._fresh_jit = False
            return out
        return self._jit(*args)

    def _run_guarded(self, args):
        """``_invoke`` through the compile-event chokepoint."""
        with _telemetry.compile_guard("TrainStep", self._jit, key="step"):
            return self._invoke(args)

    @staticmethod
    def _finish_step_trace(tr, error=None):
        """Export a step trace on a FAILING path: the flight-recorder
        bundle dumped at abort time must contain the spans of the very
        step that died, not every step except it.  ``finish()`` closes
        the still-open spans itself; never raises."""
        if tr is None:
            return
        try:
            if error is not None:
                cls = error if isinstance(error, type) else type(error)
                tr.root.attrs["error"] = cls.__name__
            tr.root.end()
            tr.finish()
        except Exception:   # noqa: BLE001 — tracing never worsens a death
            pass

    def _step(self, data, label):
        _fire("step")
        t_wall = time.perf_counter()
        data_leaves, label_leaves = self._prepare(data, label)
        # does this signature still owe its compile?  Stamped on the
        # heartbeat BEFORE the compiling call so the supervisor's
        # watchdog can tell a long first compile from a hung step
        # (ISSUE 15 — startup grace stops being a blind timer)
        if self._heartbeat is not None and self._jit._cache_size() == 0:
            self._heartbeat.beat(self._num_update, phase="train",
                                 compile_in_progress=True)
        tr = _telemetry.maybe_trace("step", server="TrainStep") \
            if _telemetry.ACTIVE else None
        key = _random.next_key()
        lr = jnp.float32(self._base_lr())
        dat_sh = NamedSharding(self.mesh, self._data_pspec)
        sp_h2d = None if tr is None else tr.open("h2d", parent=tr.root)
        data_leaves = [_put_batch(l, dat_sh) for l in data_leaves]
        label_leaves = [_put_batch(l, dat_sh) for l in label_leaves]
        if sp_h2d is not None:
            sp_h2d.end()
        args = (self._train_arrays, self._aux_arrays, self._states,
                self._t, key, lr, *data_leaves, *label_leaves)
        if getattr(self, "_last_avals", None) is None:
            # once per signature: the aval snapshot cost_analysis() lowers
            # with (shapes are fixed until sig changes)
            self._last_avals = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), args)
        sp_compute = None if tr is None else tr.open("compute",
                                                     parent=tr.root)
        try:
            out = self._run_guarded(args)
        except BaseException as exc:
            self._finish_step_trace(tr, error=exc)
            raise
        if self._skip_nonfinite:
            (self._train_arrays, self._aux_arrays, self._states, self._t,
             loss, finite) = out
            # the verdict is the one host round-trip the guard costs; the
            # arrays themselves stay async on the mesh
            if bool(finite):
                self._num_update += 1
                self.consecutive_skips = 0
            else:
                self.skipped_steps += 1
                self.consecutive_skips += 1
                self._skip_counter.increment()
                budget = self._nonfinite_budget
                if budget is not None and self.consecutive_skips >= budget:
                    try:
                        lv = float(np.asarray(loss))
                    except Exception:
                        lv = float("nan")
                    # the numeric-abort flight trigger (ISSUE 15): the
                    # dying step's trace exports FIRST (into the ring),
                    # then the post-mortem bundle lands, then the raise
                    # unwinds
                    self._finish_step_trace(tr, error=NonFiniteAbortError)
                    tr = None          # the except/finish below must not
                    #                    double-handle an exported trace
                    _telemetry.flight_trip(
                        "nonfinite-abort", step=int(self._num_update),
                        consecutive_skips=self.consecutive_skips)
                    try:
                        # queued async snapshots commit before the abort
                        # unwinds (ISSUE 17): the last GOOD state must be
                        # on disk when the supervisor inspects the wreck
                        from .checkpoint import flush_pending
                        flush_pending(timeout=60.0)
                    except Exception:  # noqa: BLE001 — the abort verdict
                        pass           # must not be masked by a flush
                    raise NonFiniteAbortError(
                        f"TrainStep: {self.consecutive_skips} consecutive "
                        f"non-finite updates (budget {budget}) at "
                        f"num_update={self._num_update}; last loss={lv}. "
                        f"Params and optimizer state are unchanged since the "
                        f"last finite step — check the input pipeline for "
                        f"corrupt batches or lower the learning rate "
                        f"(skipped {self.skipped_steps} steps total this "
                        f"run)")
        else:
            (self._train_arrays, self._aux_arrays, self._states, self._t,
             loss) = out
            self._num_update += 1
        self.optimizer.num_update = self._num_update
        step_ms = (time.perf_counter() - t_wall) * 1e3
        if sp_compute is not None:
            sp_compute.end()
        if tr is not None:
            # feed-wait attribution: the DevicePrefetcher consumer-wait
            # accrued since the last traced step rides the root span
            # (the wait happened before this step's window opened, so
            # it is an attribute + histogram, not a child span)
            try:
                w = _profiler.counter_value(
                    "DevicePrefetcher::consumer_wait_ms")
                if w is not None:
                    seen = self._feed_wait_seen
                    delta = 0.0 if seen is None else max(0.0, w - seen)
                    self._feed_wait_seen = w
                    tr.root.attrs["feed_wait_ms"] = round(delta, 3)
                    _telemetry.registry().histogram(
                        "TrainStep::feed_wait_ms",
                        _telemetry.SPAN_MS_BUCKETS).observe(delta)
                tr.root.attrs["num_update"] = int(self._num_update)
                tr.root.end()
                tr.finish()
            except Exception:   # noqa: BLE001 — tracing never fails a step
                pass
        if self._heartbeat is not None:
            self._heartbeat.beat(self._num_update, last_step_ms=step_ms)
        return NDArray(loss)

    # ------------------------------------------------------------- costing --
    def _synth_avals(self, data_leaves, label_leaves):
        """Abstract argument shapes for AOT lowering, built WITHOUT
        running a step: params/states exist after ``_build``; batch
        leaves are canonicalized the way ``device_put`` would (x64 off:
        int64→int32, float64→float32); the PRNG-key aval comes from a
        constant key so the costing path never consumes RNG state (a
        budget audit must not perturb a seeded training run)."""
        key_aval = jax.ShapeDtypeStruct((), jax.random.key(0).dtype)
        lr_aval = jax.ShapeDtypeStruct((), jnp.float32)

        def leaf_aval(l):
            return jax.ShapeDtypeStruct(
                l.shape, jax.dtypes.canonicalize_dtype(l.dtype))

        args = (self._train_arrays, self._aux_arrays, self._states,
                self._t, key_aval, lr_aval,
                *[leaf_aval(l) for l in data_leaves],
                *[leaf_aval(l) for l in label_leaves])
        return jax.tree.map(
            lambda a: a if isinstance(a, jax.ShapeDtypeStruct)
            else jax.ShapeDtypeStruct(a.shape, a.dtype), args)

    def lower(self, data=None, label=None):
        """AOT-lower the compiled step program WITHOUT executing a step.

        After a step has run, no arguments are needed (the live
        signature is reused).  Before any step, pass one sample
        ``(data, label)`` batch — host numpy zeros are enough; only
        shapes/dtypes matter — and the program is built and lowered from
        abstract values: nothing is placed on the device and no update
        runs (tools/costguard's budget audits drive this path in tier-1
        under ``JAX_PLATFORMS=cpu``)."""
        if data is not None:
            dl, ll = self._prepare(data, label)
            if getattr(self, "_last_avals", None) is None:
                self._last_avals = self._synth_avals(dl, ll)
        if self._jit is None or getattr(self, "_last_avals", None) is None:
            raise RuntimeError(
                "lower() needs one completed step, or a sample (data, "
                "label) batch to lower against")
        return self._jit.lower(*self._last_avals)

    def compiled(self, data=None, label=None):
        """The AOT-compiled step executable (cached per jit signature:
        the lower+compile is a second full XLA compile, not worth
        repeating through a flaky tunnel).  Accepts the same optional
        sample batch as ``lower`` — a sample with a NEW signature
        recompiles rather than serving the previous program's cache."""
        if data is not None:
            # _prepare resets _compiled_cache/_cost_cache on a signature
            # change, so the cache check below is always against the
            # sample's own program, never a stale one
            dl, ll = self._prepare(data, label)
            if getattr(self, "_last_avals", None) is None:
                self._last_avals = self._synth_avals(dl, ll)
        if getattr(self, "_compiled_cache", None) is None:
            self._compiled_cache = self.lower().compile()
        return self._compiled_cache

    def _require_program(self, what, data):
        if data is None and (self._jit is None
                             or getattr(self, "_last_avals", None) is None):
            raise RuntimeError(
                f"{what} needs one completed step or a sample "
                f"(data, label) batch")

    def cost_analysis(self, data=None, label=None):
        """XLA's cost model of the compiled step program: {'flops': ...,
        'bytes accessed': ...} — the profiler substitute that works through
        the axon tunnel (PERF.md methodology; device traces do not).
        Works after one completed step, or — the lower-only path — from a
        sample ``(data, label)`` batch without ever executing."""
        self._require_program("cost_analysis()", data)
        compiled = self.compiled(data, label)
        if getattr(self, "_cost_cache", None) is None:
            costs = compiled.cost_analysis()
            self._cost_cache = costs[0] if isinstance(costs, list) else costs
        return self._cost_cache

    def memory_analysis(self, data=None, label=None):
        """XLA's compiled-buffer accounting (argument/output/temp/alias
        bytes) of the step program — ``cost_analysis``'s memory-side
        sibling, same lower-only contract."""
        self._require_program("memory_analysis()", data)
        return self.compiled(data, label).memory_analysis()

    # ---------------------------------------------------------------- sync --
    def sync_params_to_net(self):
        """Write the step-owned arrays back into the Gluon Parameters.

        Arrays are gathered to the default device: eager Gluon execution is
        single-logical-device (placement-by-sharding belongs to the step), and
        mesh-committed params would collide with device-0 inputs in eager ops."""
        dev = jax.local_devices()[0]
        if not hasattr(self, "_gather"):
            # one jitted identity reused across params and calls (a fresh
            # lambda per param would retrace/recompile every sync)
            self._gather = jax.jit(lambda x: x, out_shardings=self._repl)

        def host(a):
            # Multi-process: a may be sharded over non-addressable devices;
            # all-gather to fully-replicated first (XLA collective), then the
            # local copy is readable on every rank.
            if jax.process_count() > 1:
                if not a.is_fully_replicated:
                    a = self._gather(a)
                return np.asarray(a)
            return a

        for i, a in zip(self._train_idx, self._train_arrays):
            self._plist[i].data()._data = jax.device_put(host(a), dev)
        for i, a in zip(self._aux_idx, self._aux_arrays):
            self._plist[i].data()._data = jax.device_put(host(a), dev)

    @property
    def params(self):
        full = [None] * len(self._plist)
        for i, a in zip(self._train_idx, self._train_arrays):
            full[i] = a
        for i, a in zip(self._aux_idx, self._aux_arrays):
            full[i] = a
        return dict(zip(self._names, full))


class EvalStep:
    """Compiled sharded inference: (params, batch) → outputs."""

    def __init__(self, net, mesh=None, rules=None, data_spec=None):
        self.net = net
        self.mesh = mesh if mesh is not None else default_mesh()
        self.rules = rules or ShardingRules()
        self._data_pspec = data_spec if data_spec is not None \
            else batch_spec(self.mesh)
        self._jit = None
        self._built = False

    @property
    def data_sharding(self):
        """See TrainStep.data_sharding."""
        return NamedSharding(self.mesh, self._data_pspec)

    def _build(self, sample_args):
        if any(p._deferred_init is not None
               for p in self.net.collect_params().values()):
            with _autograd.pause(), MeshScope(self.mesh):
                Block.__call__(self.net, *sample_args)
        names, plist, arrays = param_names_and_values(self.net)
        self._names, self._plist = names, plist
        sh = param_sharding(names, [a.shape for a in arrays], self.mesh,
                            self.rules)
        self._arrays = [jax.device_put(a, s) for a, s in zip(arrays, sh)]
        self._shardings = sh
        self._built = True

    def __call__(self, *data):
        data = tuple(_coerce_arrays(d) for d in data)
        if not self._built:
            self._build(data)
        data_leaves, data_tree = _leaves(tuple(data))
        sig = (data_tree, tuple((l.shape, str(l.dtype)) for l in data_leaves))
        if self._jit is None or sig != getattr(self, "_sig", None):
            net, plist = self.net, self._plist
            holder = FunctionalState()

            def fn(arrays, key, *leaves):
                with MeshScope(self.mesh):
                    outs = functional_call(net, plist, list(arrays), data_tree,
                                           list(leaves), key, False, holder)
                return tuple(outs)

            dat_sh = NamedSharding(self.mesh, self._data_pspec)
            self._jit = jax.jit(
                fn,
                in_shardings=(self._shardings,
                              NamedSharding(self.mesh, PartitionSpec()))
                + tuple([dat_sh] * len(data_leaves)))
            self._holder = holder
            self._sig = sig
        key = _random.next_key()
        dat_sh = NamedSharding(self.mesh, self._data_pspec)
        data_leaves = [_put_batch(l, dat_sh) for l in data_leaves]
        with _telemetry.compile_guard("EvalStep", self._jit, key="eval"):
            outs = self._jit(self._arrays, key, *data_leaves)
        res = _unflatten_nd(self._holder.out_tree,
                            tuple(NDArray(o) for o in outs))
        if isinstance(res, tuple) and len(res) == 1:
            return res[0]
        return res
