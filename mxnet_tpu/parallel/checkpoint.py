"""Checkpoint / resume for the fused sharded TrainStep.

ref: the reference checkpoints via save_checkpoint/load_checkpoint
(python/mxnet/model.py) + Trainer.save_states/load_states — params and
optimizer state as separate files keyed by name (SURVEY §5.4).  The fused
TrainStep owns its arrays (params, per-param optimizer state tuples, aux
state, step counter) on the mesh, so it gets its own save/restore that:

- v1 (portable): gathers every array to host and writes ONE ``.npz``
  (same container as ``nd.save``) with a manifest — param names, optimizer
  class, state layout, step count.  Restores into any mesh/sharding layout
  (re-``device_put`` against the step's shardings), so a checkpoint taken
  on dp=8 restores onto dp×tp or a different device count.
- v1.1 (ISSUE 17): the manifest additionally carries a format version
  plus a per-array crc32 digest and byte size for every ``p.*`` /
  ``s.*`` / ``a.*`` entry, computed at write time BEFORE the bytes hit
  the container.  ``verify_checkpoint`` deep-checks a snapshot without
  constructing a TrainStep; every load path verifies digests before
  staging anything, so a bit-flipped array is *damage* (skipped by
  ``resume_latest``, rejected by the serving ``WeightUpdater``), never
  silently-loaded poison.  Pre-v1.1 snapshots (no digests) still load —
  the digest check is skipped and logged.
- durability: the payload file AND its directory entry are fsynced
  before+after the atomic ``os.replace`` commit, so "committed" survives
  power loss, not just process death.
- ``AsyncSnapshotter`` / ``CheckpointManager(async_save=True)``: the
  step loop pays only the device→host fetch at the step boundary; a
  background writer thread serializes, fsyncs, and commits.  Bounded
  queue with skip-if-busy, ``wait_until_finished()``, and a process-wide
  ``flush_pending()`` hook the SIGTERM / nonfinite-abort exits call so
  a snapshot training believed committed is never lost in the queue.
- multi-process: every rank gathers (all-gather for sharded arrays rides
  the fabric) and rank 0 writes; restore reads on every rank and re-shards
  via the step's own placement path.

A kill-and-resume must reproduce the loss trajectory exactly — that is the
test's contract (tests/test_checkpoint.py).
"""
from __future__ import annotations

import json
import logging
import os
import queue as _queue
import threading
import time as _time
import weakref
import zlib

import numpy as np
import jax

from ..fault import fire as _fire
from .. import elastic as _elastic
from .. import telemetry as _telemetry

__all__ = ["save_train_step", "load_train_step",
           "save_train_step_sharded", "load_train_step_sharded",
           "CheckpointManager", "CheckpointMismatchError",
           "CheckpointCorruptError", "BitFlipInjection",
           "verify_checkpoint", "AsyncSnapshotter", "flush_pending",
           "resume_latest", "list_checkpoints", "latest_checkpoint",
           "latest_step", "wait_for_new", "load_snapshot_params"]

_MANIFEST = "__manifest__"
FORMAT_VERSION = "1.1"
_logger = logging.getLogger(__name__)


class CheckpointMismatchError(ValueError):
    """A checkpoint that READ fine does not MATCH the model (param
    name/shape, aux, or optimizer disagreement).  Distinct from unreadable
    (truncated/corrupt) files so recovery paths like ``resume_latest`` can
    skip damage but refuse to paper over a user error."""


class CheckpointCorruptError(ValueError):
    """A snapshot whose BYTES are wrong: missing/truncated payload entry,
    byte-size drift, or a crc32 digest mismatch against the v1.1
    manifest.  Always *damage* (never user error): ``resume_latest``
    skips it for an older intact sibling, and the serving
    ``WeightUpdater`` rejects it before any replica swap."""


class BitFlipInjection(Exception):
    """Fault-armed corruption injector (ISSUE 17).  Armed on the
    ``checkpoint.serialize`` point via ``fault.inject``, the writer
    CATCHES it (instead of propagating) and flips one bit in one payload
    entry AFTER the manifest digests were computed — the committed
    snapshot is then silently corrupt at the container level (the zip
    CRCs are consistent with the flipped bytes), exactly the damage only
    the v1.1 digest check can catch::

        with fault.inject("checkpoint.serialize",
                          checkpoint.BitFlipInjection(), times=1):
            mgr.save()                    # commits a poisoned snapshot

    ``key`` picks the payload entry (default: the largest ``p.*``),
    ``byte`` the offset (default: the middle), ``bit`` the bit (0-7)."""

    def __init__(self, key=None, byte=None, bit=0):
        super().__init__(f"bit-flip injection (key={key}, byte={byte}, "
                         f"bit={bit})")
        self.key = key
        self.byte = byte
        self.bit = int(bit) & 7


def _norm_name(n):
    """Strip gluon's process-global instance counters: dense3_weight →
    dense_weight (structure is checked by sequence position + shape)."""
    import re
    return re.sub(r"(\D)\d+", r"\1", n)


def _natural_order(names):
    """Indices ordering ``names`` with numeric counters compared as numbers
    (dense9 < dense10).  The save/load pairing runs in this order on both
    sides: the plain lexicographic order param_names_and_values uses is NOT
    stable across processes (counters are process-global, and 'dense10' <
    'dense9' lexicographically), so positional restore needs it."""
    import re

    def key(i):
        return [int(t) if t.isdigit() else t
                for t in re.split(r"(\d+)", names[i])]

    return sorted(range(len(names)), key=key)


def _to_host(step, a):
    """Fetch one (possibly mesh-sharded) array to host memory."""
    if jax.process_count() > 1 and hasattr(a, "is_fully_replicated") \
            and not a.is_fully_replicated:
        if not hasattr(step, "_gather"):
            step._gather = jax.jit(lambda x: x, out_shardings=step._repl)
        a = step._gather(a)
    return np.asarray(a)


def _collect_payload(step):
    """Fetch the step's arrays to host; ``(payload, manifest)`` where
    ``payload`` maps ``p.*``/``s.*``/``a.*`` entry names to host arrays.
    This is the ONLY part of a snapshot the step loop must block on —
    the async writer pays everything downstream of it."""
    if not step._built:
        raise ValueError("TrainStep has not run yet — nothing to checkpoint")
    _fire("checkpoint.write")
    payload = {}
    for k, a in enumerate(step._train_arrays):
        payload[f"p.{k}"] = _to_host(step, a)
    for k, states in enumerate(step._states):
        for j, s in enumerate(states):
            payload[f"s.{k}.{j}"] = _to_host(step, s)
    for k, a in enumerate(step._aux_arrays):
        payload[f"a.{k}"] = _to_host(step, a)
    manifest = {
        "train_names": [step._names[i] for i in step._train_idx],
        "aux_names": [step._names[i] for i in step._aux_idx],
        "optimizer": type(step.optimizer).__name__,
        "num_update": int(step._num_update),
        "state_counts": [len(s) for s in step._states],
    }
    return payload, manifest


def _entry_bytes(a):
    """The canonical byte view a digest is computed over (and verified
    against): C-contiguous raw array bytes."""
    return np.ascontiguousarray(a).tobytes()


def _apply_bitflip(payload, flip):
    """Honour an armed ``BitFlipInjection``: flip one bit in one entry's
    bytes (digests were already computed, so the corruption is silent to
    the container and visible only to the v1.1 digest check)."""
    key = flip.key
    if key is None:
        params = [k for k in payload if k.startswith("p.")]
        key = max(params or sorted(payload),
                  key=lambda k: payload[k].nbytes)
    a = payload[key]
    buf = bytearray(_entry_bytes(a))
    i = (len(buf) // 2 if flip.byte is None else int(flip.byte)) \
        % max(1, len(buf))
    buf[i] ^= 1 << flip.bit
    payload = dict(payload)
    payload[key] = np.frombuffer(bytes(buf), dtype=a.dtype).reshape(a.shape)
    _logger.warning("checkpoint.serialize: injected bit-flip in %r "
                    "(byte %d, bit %d)", key, i, flip.bit)
    return payload


def _fsync_dir(directory):
    """fsync the directory entry so a committed rename survives power
    loss, not just process death.  Platforms that refuse to fsync a
    directory fd (some network filesystems) are skipped."""
    try:
        fd = os.open(directory or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _write_payload(payload, manifest, fname, trace=None):
    """Serialize + fsync + atomically commit one snapshot (the writer
    half of ``save_train_step``, shared with the async writer thread).

    The v1.1 integrity manifest (format version, per-entry crc32 digest
    and byte size) is stamped here, BEFORE serialization, so anything
    that corrupts the bytes downstream — including the fault-armed
    ``BitFlipInjection`` — is caught by the digest check at load time.
    Durability: payload fsync before the ``os.replace`` commit, directory
    fsync after it.  Returns the payload byte total."""
    man = dict(manifest)
    digests, sizes = {}, {}
    total = 0
    for k, a in payload.items():
        b = _entry_bytes(a)
        digests[k] = zlib.crc32(b) & 0xFFFFFFFF
        sizes[k] = len(b)
        total += len(b)
    man["format"] = FORMAT_VERSION
    man["digests"] = digests
    man["sizes"] = sizes
    sp = None if trace is None else trace.open("serialize",
                                               parent=trace.root)
    try:
        _fire("checkpoint.serialize")
    except BitFlipInjection as flip:
        payload = _apply_bitflip(payload, flip)
    blob = dict(payload)
    blob[_MANIFEST] = np.frombuffer(
        json.dumps(man).encode(), dtype=np.uint8)
    tmp = fname + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **blob)
        f.flush()
        _fire("checkpoint.fsync")
        os.fsync(f.fileno())
    if sp is not None:
        sp.end()
    sp = None if trace is None else trace.open("commit", parent=trace.root)
    _fire("checkpoint.replace")
    os.replace(tmp, fname)
    _fsync_dir(os.path.dirname(os.path.abspath(fname)))
    if sp is not None:
        sp.end()
    _telemetry.registry().gauge("ckpt_bytes").set(total)
    return total


def save_train_step(step, fname):
    """Write params + optimizer state + aux + step count to ``fname``.

    Layout: ``p.<i>`` trainable param i (in ``step._train_idx`` order),
    ``s.<i>.<j>`` its j-th optimizer state array, ``a.<i>`` aux array i,
    plus a JSON manifest with the param names for name-checked restore
    and the v1.1 integrity section (format version + per-entry crc32
    digest and byte size).

    Preemption-safe: the ``.npz`` payload lands in ``fname + '.tmp'`` and
    is committed with ``os.replace`` (atomic on POSIX), so a crash at ANY
    point leaves either the previous complete checkpoint or the new one —
    never a truncated payload under the final name.  Manifest and payload
    live in the one file, so they can never disagree.  Durable: payload
    and directory entry are fsynced around the commit, so a committed
    snapshot survives power loss too."""
    t0 = _time.perf_counter()
    tr = _telemetry.maybe_trace("snapshot", server="save_train_step") \
        if _telemetry.ACTIVE else None
    sp = None if tr is None else tr.open("fetch", parent=tr.root)
    payload, manifest = _collect_payload(step)
    if sp is not None:
        sp.end()
    try:
        if jax.process_index() == 0:
            _write_payload(payload, manifest, fname, trace=tr)
        _telemetry.registry().gauge("ckpt_last_snapshot_ms").set(
            round((_time.perf_counter() - t0) * 1e3, 3))
    finally:
        if tr is not None:
            tr.root.end()
            tr.finish()
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("ckpt_save")


def load_train_step(step, fname):
    """Restore a checkpoint into a built TrainStep (any mesh layout).

    The step must have been built (one step run, or call it once on a
    sample batch first) so shardings exist; arrays are re-placed with the
    step's own shardings, so restoring onto a different mesh works."""
    if not step._built:
        raise ValueError("build the TrainStep (run one step) before restore")
    try:
        z = np.load(fname)
    except FileNotFoundError:
        raise
    except Exception as exc:          # torn zip container = damage
        _corrupt(fname, f"unreadable container: {exc}")
    try:
        manifest = json.loads(bytes(z[_MANIFEST]).decode())
    except Exception as exc:
        _corrupt(fname, f"manifest missing or unreadable: {exc}")
    # integrity FIRST: a bit-flipped/truncated entry must surface as
    # CheckpointCorruptError (damage) before any model-match verdict or
    # staging — never as a spurious mismatch, never as loaded poison
    _verify_entries(z, manifest, fname)
    names = [step._names[i] for i in step._train_idx]
    saved_names = manifest["train_names"]
    if len(saved_names) != len(names):
        raise CheckpointMismatchError(
            f"checkpoint/model mismatch: file has {len(saved_names)} "
            f"trainable params, model expects {len(names)}")
    # pair by natural order on both sides; counter-normalised names and
    # shapes must then agree pointwise (gluon counters are process-global,
    # so the plain lexicographic storage order is NOT reproducible)
    pairs = list(zip(_natural_order(saved_names), _natural_order(names)))
    for sk, wk in pairs:
        if _norm_name(saved_names[sk]) != _norm_name(names[wk]) or \
                tuple(z[f"p.{sk}"].shape) != \
                tuple(step._train_arrays[wk].shape):
            raise CheckpointMismatchError(
                f"checkpoint/model mismatch: saved param "
                f"{saved_names[sk]!r} {z[f'p.{sk}'].shape} does not match "
                f"model param {names[wk]!r} "
                f"{tuple(step._train_arrays[wk].shape)}")
    if manifest["optimizer"] != type(step.optimizer).__name__:
        raise CheckpointMismatchError(
            f"optimizer mismatch: checkpoint={manifest['optimizer']} "
            f"step={type(step.optimizer).__name__}")

    # stage + validate the ENTIRE payload before touching the step: a
    # raise from a truncated later section (aux, a state slot) must leave
    # the step exactly as it was, so resume_latest can fall back to an
    # older file — a half-restored step is worse than a failed load
    shard = [step._param_shardings[i] for i in step._train_idx]
    aux_shard = [step._param_shardings[i] for i in step._aux_idx]
    new_train = list(step._train_arrays)
    new_states = list(step._states)
    for sk, wk in pairs:
        new_train[wk] = jax.device_put(z[f"p.{sk}"], shard[wk])
        new_states[wk] = tuple(
            jax.device_put(z[f"s.{sk}.{j}"], shard[wk])
            for j in range(manifest["state_counts"][sk]))
    aux_names = [step._names[i] for i in step._aux_idx]
    saved_aux = manifest["aux_names"]
    if len(saved_aux) != len(aux_names):
        raise CheckpointMismatchError(
            f"checkpoint/model mismatch: file has {len(saved_aux)} aux "
            f"arrays, model expects {len(aux_names)}")
    new_aux = list(step._aux_arrays)
    for sk, wk in zip(_natural_order(saved_aux), _natural_order(aux_names)):
        if _norm_name(saved_aux[sk]) != _norm_name(aux_names[wk]) or \
                tuple(z[f"a.{sk}"].shape) != \
                tuple(step._aux_arrays[wk].shape):
            raise CheckpointMismatchError(
                f"checkpoint/model mismatch: saved aux {saved_aux[sk]!r} "
                f"{z[f'a.{sk}'].shape} does not match model aux "
                f"{aux_names[wk]!r} {tuple(step._aux_arrays[wk].shape)}")
        new_aux[wk] = jax.device_put(z[f"a.{sk}"], aux_shard[wk])
    num_update = int(manifest["num_update"])

    step._train_arrays = new_train
    step._states = tuple(new_states)
    step._aux_arrays = new_aux
    step._num_update = num_update
    step.optimizer.num_update = num_update
    import jax.numpy as jnp
    step._t = jax.device_put(jnp.zeros((), jnp.int32) + num_update,
                             step._repl)


# -------------------------------------------------- integrity (v1.1) ------

def _corrupt(path, msg):
    """Record one integrity failure (gauge + flight-recorder dump) and
    raise ``CheckpointCorruptError`` — the single chokepoint every
    verification failure funnels through."""
    _telemetry.registry().gauge("ckpt_verify_failures").add(1)
    _telemetry.flight_trip("ckpt-verify-failure", path=str(path),
                           error=str(msg))
    raise CheckpointCorruptError(f"{path}: {msg}")


def _verify_entries(z, manifest, path, entries=None):
    """Digest-check payload entries against the v1.1 manifest BEFORE any
    bytes are staged.  ``entries`` restricts the check (the params-only
    reader verifies only ``p.*``); None checks every digest-covered entry
    plus flags uncovered strays.  Returns True when digests were checked,
    False for a pre-v1.1 snapshot (no digest section — skipped, logged).
    Raises ``CheckpointCorruptError`` on any missing entry, byte-size
    drift, or crc32 mismatch."""
    _fire("checkpoint.verify")
    digests = manifest.get("digests")
    if digests is None:
        _logger.info("checkpoint %s: pre-v1.1 snapshot (no digest "
                     "section) — integrity check skipped", path)
        return False
    sizes = manifest.get("sizes") or {}
    files = set(getattr(z, "files", ()))
    if entries is None:
        keys = list(digests)
        strays = files - set(digests) - {_MANIFEST}
        if strays:
            _corrupt(path, f"payload entries {sorted(strays)} are not "
                           f"covered by the v1.1 digest section")
    else:
        keys = list(entries)
    for k in keys:
        if k not in digests:
            _corrupt(path, f"entry {k!r} has no digest in the manifest")
        if k not in files:
            _corrupt(path, f"payload entry {k!r} missing from container")
        try:
            b = _entry_bytes(z[k])
        except Exception as exc:
            _corrupt(path, f"payload entry {k!r} unreadable: {exc}")
        if k in sizes and len(b) != int(sizes[k]):
            _corrupt(path, f"payload entry {k!r} is {len(b)} bytes, "
                           f"manifest says {sizes[k]}")
        if (zlib.crc32(b) & 0xFFFFFFFF) != int(digests[k]):
            _corrupt(path, f"crc32 mismatch on payload entry {k!r} "
                           f"(bytes corrupted after write, or flipped "
                           f"between digest and serialize)")
    return True


def verify_checkpoint(path):
    """Deep-check one committed snapshot WITHOUT constructing a
    TrainStep: container readability, manifest parse, and (v1.1) every
    entry's byte size + crc32 digest.  Returns the parsed manifest dict
    on success; raises ``CheckpointCorruptError`` on any damage
    (``FileNotFoundError`` passes through untouched — a pruned path is
    *stale*, not corrupt).  Pre-v1.1 snapshots verify container
    readability only (every entry decompressed), logged as such.

    This is the operator / CI spelling: ``verify_checkpoint(p)`` over a
    retention directory proves the snapshot stream intact end to end."""
    try:
        z = np.load(path)
    except FileNotFoundError:
        raise
    except Exception as exc:
        _corrupt(path, f"unreadable container: {exc}")
    try:
        files = set(z.files)
        if _MANIFEST not in files:
            _corrupt(path, "no __manifest__ entry — not a v1 snapshot")
        try:
            manifest = json.loads(bytes(z[_MANIFEST]).decode())
        except Exception as exc:
            _corrupt(path, f"manifest unreadable: {exc}")
        if not _verify_entries(z, manifest, path):
            # pre-v1.1: no digests to check, but still decompress every
            # entry so zip-level truncation cannot hide
            for k in sorted(files - {_MANIFEST}):
                try:
                    z[k]
                except Exception as exc:
                    _corrupt(path, f"payload entry {k!r} unreadable: "
                                   f"{exc}")
        return manifest
    finally:
        z.close()


# ---------------------------------------------------------------- v2 ------
# Sharded/async checkpointing via orbax: each host writes only ITS shards
# (no gather traffic), and the async form lets training continue while
# the write completes.  The reference has neither (SURVEY §5.4 "No
# sharded/async checkpoint") — this is a TPU-native exceed, with v1 above
# remaining the portable single-file format.

def _sharded_tree(step):
    # zero-padded positional keys: lexicographic order == positional order
    # (6 digits for params, 2 for per-param optimizer-state slots)
    names = [step._names[i] for i in step._train_idx]
    aux_names = [step._names[i] for i in step._aux_idx]
    params = {f"{k:06d}.{_norm_name(n)}": a
              for k, (n, a) in enumerate(zip(names, step._train_arrays))}
    states = {f"{k:06d}.{j:02d}": s
              for k, st in enumerate(step._states)
              for j, s in enumerate(st)}
    aux = {f"{k:06d}.{_norm_name(n)}": a
           for k, (n, a) in enumerate(zip(aux_names, step._aux_arrays))}
    return {"params": params, "states": states, "aux": aux,
            "num_update": int(step._num_update)}


def _v2_manifest(step):
    return {
        "train_names": [step._names[i] for i in step._train_idx],
        "aux_names": [step._names[i] for i in step._aux_idx],
        "optimizer": type(step.optimizer).__name__,
        "shapes": [list(a.shape) for a in step._train_arrays],
        "aux_shapes": [list(a.shape) for a in step._aux_arrays],
        "state_counts": [len(s) for s in step._states],
    }


def _orbax():
    try:
        import orbax.checkpoint as ocp
        return ocp
    except Exception as exc:  # pragma: no cover
        raise ImportError(
            f"sharded checkpointing needs orbax ({exc}); use "
            f"save_train_step/load_train_step (v1 single-file) instead")


def save_train_step_sharded(step, directory, async_save=True):
    """v2: write the TrainStep's state as an orbax sharded checkpoint.

    Every process writes only its own shards.  With ``async_save`` the
    call returns immediately; call ``.wait_until_finished()`` on the
    returned checkpointer (or just save again later — orbax serialises).
    """
    import os
    if not step._built:
        raise ValueError("TrainStep has not run yet — nothing to checkpoint")
    ocp = _orbax()
    path = os.path.abspath(str(directory))
    if async_save:
        ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    else:
        ckptr = ocp.Checkpointer(ocp.StandardCheckpointHandler())
    ckptr.save(path, args=ocp.args.StandardSave(_sharded_tree(step)),
               force=True)
    # the manifest is what restore VALIDATES and REMAPS against (the
    # orbax target alone cannot catch model/checkpoint mismatches, and
    # positional order is not stable across processes — gluon name
    # counters are process-global).  Written temp-then-rename so a crash
    # mid-write never leaves a truncated json next to a valid orbax dir.
    # NOTE: like the orbax directory itself, the manifest lives on a
    # filesystem that must be SHARED across processes on multi-host runs
    # (process 0 writes it; every process reads it at restore).
    if jax.process_index() == 0:
        mpath = path + ".manifest.json"
        tmp = mpath + ".tmp"
        with open(tmp, "w") as f:
            json.dump(_v2_manifest(step), f)
        os.replace(tmp, mpath)
    return ckptr


def load_train_step_sharded(step, directory):
    """Restore a v2 sharded checkpoint into a BUILT TrainStep.

    The abstract target is derived from the step's own arrays, so every
    restored shard lands directly on its device with the step's sharding
    (no host gather, no resharding traffic beyond what the layouts need).
    """
    import os
    if not step._built:
        raise ValueError("build the TrainStep (run one step) before restore")
    ocp = _orbax()
    path = os.path.abspath(str(directory))

    # The manifest drives BOTH validation and slot remapping: positional
    # order is not stable across processes (gluon name counters are
    # process-global, and param_names_and_values sorts lexicographically,
    # so dense9/dense10 order flips) — pair saved↔model slots by natural
    # order exactly like v1's load_train_step.
    mpath = path + ".manifest.json"
    if not os.path.exists(mpath):
        raise ValueError(
            f"missing {mpath}: v2 checkpoints are written with a manifest "
            f"(save_train_step_sharded); cannot validate or remap without it")
    with open(mpath) as f:
        man = json.load(f)
    names = [step._names[i] for i in step._train_idx]
    saved_names = man["train_names"]
    if len(saved_names) != len(names):
        raise ValueError(
            f"checkpoint/model mismatch: file has {len(saved_names)} "
            f"trainable params, model expects {len(names)}")
    pairs = list(zip(_natural_order(saved_names), _natural_order(names)))
    for sk, wk in pairs:
        if _norm_name(saved_names[sk]) != _norm_name(names[wk]) \
                or tuple(man["shapes"][sk]) != \
                tuple(step._train_arrays[wk].shape):
            raise ValueError(
                f"checkpoint/model mismatch: saved {saved_names[sk]!r} "
                f"{man['shapes'][sk]} vs model {names[wk]!r} "
                f"{tuple(step._train_arrays[wk].shape)}")
    if man["optimizer"] != type(step.optimizer).__name__:
        raise ValueError(
            f"optimizer mismatch: checkpoint={man['optimizer']} "
            f"step={type(step.optimizer).__name__}")
    aux_names = [step._names[i] for i in step._aux_idx]
    saved_aux = man["aux_names"]
    if len(saved_aux) != len(aux_names):
        raise ValueError(
            f"checkpoint/model mismatch: file has {len(saved_aux)} aux "
            f"arrays, model expects {len(aux_names)}")
    aux_pairs = list(zip(_natural_order(saved_aux),
                         _natural_order(aux_names)))
    # manifests written before aux_shapes existed: fall back to the
    # model's own shapes (name check still applies)
    aux_shapes = man.get("aux_shapes") or \
        {sk: list(step._aux_arrays[wk].shape) for sk, wk in aux_pairs}
    for sk, wk in aux_pairs:
        if _norm_name(saved_aux[sk]) != _norm_name(aux_names[wk]) \
                or tuple(aux_shapes[sk]) != \
                tuple(step._aux_arrays[wk].shape):
            raise ValueError(
                f"checkpoint/model mismatch: saved aux {saved_aux[sk]!r} "
                f"{aux_shapes[sk]} vs model {aux_names[wk]!r} "
                f"{tuple(step._aux_arrays[wk].shape)}")

    # Build the restore target with the FILE's keys (saved names/order),
    # each slot shaped+sharded for the model array it will land in.
    def _sds(a):
        return jax.ShapeDtypeStruct(a.shape, a.dtype,
                                    sharding=getattr(a, "sharding", None))

    tgt_params, tgt_states, tgt_aux = {}, {}, {}
    for sk, wk in pairs:
        if man["state_counts"][sk] != len(step._states[wk]):
            raise ValueError(
                f"checkpoint/model mismatch: param {saved_names[sk]!r} has "
                f"{man['state_counts'][sk]} optimizer state slots in the "
                f"checkpoint, {len(step._states[wk])} in the model (same "
                f"optimizer class configured differently?)")
        key = f"{sk:06d}.{_norm_name(saved_names[sk])}"
        tgt_params[key] = _sds(step._train_arrays[wk])
        for j in range(man["state_counts"][sk]):
            tgt_states[f"{sk:06d}.{j:02d}"] = _sds(step._states[wk][j])
    for sk, wk in aux_pairs:
        key = f"{sk:06d}.{_norm_name(saved_aux[sk])}"
        tgt_aux[key] = _sds(step._aux_arrays[wk])
    target = {"params": tgt_params, "states": tgt_states, "aux": tgt_aux,
              "num_update": 0}

    ckptr = ocp.Checkpointer(ocp.StandardCheckpointHandler())
    restored = ckptr.restore(path, args=ocp.args.StandardRestore(target))

    new_train = list(step._train_arrays)
    new_states = list(step._states)
    for sk, wk in pairs:
        key = f"{sk:06d}.{_norm_name(saved_names[sk])}"
        new_train[wk] = restored["params"][key]
        new_states[wk] = tuple(restored["states"][f"{sk:06d}.{j:02d}"]
                               for j in range(man["state_counts"][sk]))
    step._train_arrays = new_train
    step._states = tuple(new_states)
    new_aux = list(step._aux_arrays)
    for sk, wk in aux_pairs:
        key = f"{sk:06d}.{_norm_name(saved_aux[sk])}"
        new_aux[wk] = restored["aux"][key]
    step._aux_arrays = new_aux
    step._num_update = int(restored["num_update"])
    step.optimizer.num_update = step._num_update
    import jax.numpy as jnp
    step._t = jax.device_put(jnp.zeros((), jnp.int32) + step._num_update,
                             step._repl)


# ------------------------------------------------- retention / discovery --
# Preemption-safe training needs more than one atomic write: periodic
# snapshots (save_every_n_steps), bounded disk (keep-last-K), and a resume
# path that discovers the newest LOADABLE checkpoint by itself — a
# preempted VM restarts with nothing but the directory name.

def list_checkpoints(directory, prefix="ckpt"):
    """``(num_update, path)`` pairs for every ``<prefix>-<n>.npz`` in
    ``directory``, ascending by step.  Orphan ``.tmp`` files (a crash
    mid-write) are ignored — they were never committed.  Delegates to
    ``elastic.scan_checkpoints`` — the one committed-name parser, shared
    with the (jax-free) supervisor's progress accounting."""
    return _elastic.scan_checkpoints(directory, prefix)


def latest_checkpoint(directory, prefix="ckpt"):
    """Newest committed ``(num_update, path)``, or None when empty."""
    return _elastic.latest_checkpoint(directory, prefix)


def latest_step(directory, prefix="ckpt"):
    """The newest committed snapshot's step count, or None when the
    directory holds none — the progress probe the elastic supervisor's
    restart-budget accounting reads (``elastic.latest_committed_step``
    is the stdlib spelling the supervisor process itself uses)."""
    return _elastic.latest_committed_step(directory, prefix)


def wait_for_new(directory, last_seen=None, timeout=None, prefix="ckpt",
                 poll=0.1):
    """Block until ``directory`` holds a checkpoint NEWER than
    ``last_seen`` (a ``num_update``; ``None`` accepts any); returns the
    newest ``(num_update, path)`` pair, or ``None`` on timeout.

    This is the serving side of the training→serving snapshot stream: a
    ``WeightUpdater`` parks here between rolling updates.  Pure polling
    over the committed-name namespace (``list_checkpoints``), so it only
    ever sees atomically-committed snapshots — a mid-write ``.tmp`` is
    invisible by construction, and the returned path is complete the
    moment it is returned."""
    t_end = None if timeout is None else _time.monotonic() + float(timeout)
    while True:
        ck = latest_checkpoint(directory, prefix)
        if ck is not None:
            num_update, path = ck
            if last_seen is None or num_update > last_seen:
                return num_update, path
        if t_end is not None:
            remaining = t_end - _time.monotonic()
            if remaining <= 0:
                return None
            _time.sleep(min(float(poll), remaining))
        else:
            _time.sleep(float(poll))


def load_snapshot_params(fname):
    """Read ONLY the trainable params out of a v1 snapshot, without a
    TrainStep: ``(params, names)`` where ``params`` is a list of host
    arrays in saved (``p.<k>``) order and ``names`` the matching
    manifest names.  This is the weight-update reader — a serving
    process streams training snapshots into its replicas without ever
    constructing the training step they came from.

    Integrity: the ``p.*`` entries are digest-verified (v1.1) before
    anything is returned — a corrupt snapshot raises
    ``CheckpointCorruptError`` so the updater can reject it WITHOUT a
    replica swap.  ``FileNotFoundError`` propagates untouched: a path
    pruned between discovery and read is *stale* (re-poll), not bad."""
    try:
        z = np.load(fname)
    except FileNotFoundError:
        raise
    except Exception as exc:          # torn zip container = damage
        _corrupt(fname, f"unreadable container: {exc}")
    try:
        try:
            manifest = json.loads(bytes(z[_MANIFEST]).decode())
        except Exception as exc:
            _corrupt(fname, f"manifest missing or unreadable: {exc}")
        names = list(manifest["train_names"])
        keys = [f"p.{k}" for k in range(len(names))]
        _verify_entries(z, manifest, fname, entries=keys)
        params = []
        for k in keys:
            try:
                params.append(z[k])
            except Exception as exc:
                _corrupt(fname, f"payload entry {k!r} unreadable: {exc}")
        return params, names
    finally:
        z.close()


def resume_latest(step, directory, prefix="ckpt"):
    """Restore the newest loadable checkpoint in ``directory`` into a
    BUILT TrainStep; returns its ``num_update``, or None when the
    directory holds no usable checkpoint (fresh start).

    A checkpoint that cannot be READ (truncated zip, corrupt json,
    truncated inner array — e.g. the process died while an external copy
    was happening) is skipped with a warning and the next-older one is
    tried: preemption recovery must not be wedged by one bad file.  The
    same damage-vs-user-error split applies to VALIDATION failures: a
    committed file (the marker name exists) whose payload fails the
    model match is only a user error when the mismatch is *systematic* —
    if an older snapshot of the same run restores cleanly, the
    mismatching file was damaged in place (partial overwrite, botched
    external restore) and is skipped as damage, not reported as user
    error.  Only when NO candidate matches does the newest file's
    ``CheckpointMismatchError`` raise — a genuinely wrong model must
    never silently resume."""
    if not step._built:
        raise ValueError("build the TrainStep (run one step) before "
                         "resume_latest")
    mismatch = None
    skipped = []
    for num_update, path in reversed(list_checkpoints(directory, prefix)):
        try:
            load_train_step(step, path)
        except CheckpointMismatchError as exc:
            # deferred verdict: user error only if every candidate agrees
            if mismatch is None:
                mismatch = exc
            skipped.append((path, exc))
            continue
        except Exception as exc:   # truncated/corrupt in ANY layer (zip,
            # manifest json, inner .npy header): damage, not user error
            _logger.warning("resume_latest: skipping unreadable checkpoint "
                            "%s (%s)", path, exc)
            continue
        for bad_path, exc in skipped:    # an older file restored: the
            # newer mismatches were per-file damage after all
            _logger.warning(
                "resume_latest: skipped damaged checkpoint %s — its "
                "payload fails validation (%s) but %s restores cleanly, "
                "so this is file damage, not a model mismatch",
                bad_path, exc, path)
        return num_update
    if mismatch is not None:
        raise mismatch
    return None


# ------------------------------------------------------ async pipeline ----

_LIVE_LOCK = threading.Lock()
_LIVE_SNAPSHOTTERS = weakref.WeakSet()


def flush_pending(timeout=None):
    """Drain every live ``AsyncSnapshotter`` in the process: returns True
    when all queued snapshot writes have committed (or none exist), False
    on timeout.  The SIGTERM / nonfinite-abort exit paths call this so a
    snapshot training believed saved is never lost in the queue — the
    elastic supervisor's progress accounting reads the directory, not the
    queue."""
    with _LIVE_LOCK:
        snaps = list(_LIVE_SNAPSHOTTERS)
    ok = True
    for s in snaps:
        ok = s.wait_until_finished(timeout=timeout) and ok
    return ok


class AsyncSnapshotter:
    """Non-blocking snapshot writes: the step loop pays ONLY the
    device→host fetch; a background writer thread serializes, fsyncs,
    and atomically commits through the same ``_write_payload`` as the
    synchronous path (identical v1.1 format, identical durability).

    The queue is bounded (``max_pending``, default 1 → double buffer:
    one snapshot being written while the next is fetched).  When the
    writer is still busy at the next save point the snapshot is SKIPPED
    — counted in ``snapshots_skipped`` and the ``ckpt_snapshots_skipped``
    gauge — rather than stalling training: a slow disk degrades snapshot
    *frequency*, never step time.  ``wait_until_finished()`` drains;
    every live instance is registered so the process-wide
    ``flush_pending()`` (SIGTERM / nonfinite-abort paths) can drain them
    all.  Writer-thread failures are latched in ``errors`` and logged —
    the step loop is never interrupted by a failed background write.

    Multi-process: every rank pays the fetch (sharded-array all-gathers
    ride the fabric), rank 0 enqueues; there is deliberately no global
    device sync per save — the fetch itself is the only coupling."""

    def __init__(self, max_pending=1, on_commit=None):
        self.max_pending = max(1, int(max_pending))
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._q = _queue.Queue()
        self._pending = 0
        self._skipped = 0
        self._written = 0
        self._errors = []
        self._closed = False
        self._on_commit = on_commit
        self._thread = threading.Thread(target=self._run,
                                        name="ckpt-writer", daemon=True)
        self._thread.start()
        with _LIVE_LOCK:
            _LIVE_SNAPSHOTTERS.add(self)

    # -- step-loop side ----------------------------------------------------
    def save(self, step, fname):
        """Snapshot ``step`` toward ``fname``.  Blocks only for the
        device→host fetch; returns True when the write was enqueued,
        False when it was skipped because ``max_pending`` writes are
        already in flight.  The ``ckpt_last_snapshot_ms`` gauge records
        the stall THIS call cost the step loop (fetch only)."""
        t0 = _time.perf_counter()
        with self._lock:
            if self._closed:
                raise RuntimeError("AsyncSnapshotter is closed")
            if self._pending >= self.max_pending:
                self._skipped += 1
                _telemetry.registry().gauge(
                    "ckpt_snapshots_skipped").set(self._skipped)
                _logger.warning(
                    "AsyncSnapshotter: skipping snapshot %s — %d write(s) "
                    "still in flight (slow disk? raise max_pending or "
                    "lower the snapshot rate)", fname, self._pending)
                return False
            self._pending += 1
            _telemetry.registry().gauge(
                "ckpt_pending_writes").set(self._pending)
        try:
            tr = _telemetry.maybe_trace("snapshot", server="async") \
                if _telemetry.ACTIVE else None
            sp = None if tr is None else tr.open("fetch", parent=tr.root)
            payload, manifest = _collect_payload(step)
            if sp is not None:
                sp.end()
            if tr is not None:
                tr.root.end()
                tr.finish()
        except BaseException:
            with self._idle:
                self._pending -= 1
                self._idle.notify_all()
            raise
        if jax.process_index() == 0:
            self._q.put((payload, manifest, fname))
        else:                                  # non-writer rank: fetch was
            with self._idle:                   # the whole job
                self._pending -= 1
                self._idle.notify_all()
        _telemetry.registry().gauge("ckpt_last_snapshot_ms").set(
            round((_time.perf_counter() - t0) * 1e3, 3))
        return True

    def wait_until_finished(self, timeout=None):
        """Block until every enqueued snapshot has committed; True when
        drained, False on timeout."""
        deadline = None if timeout is None \
            else _time.monotonic() + float(timeout)
        with self._idle:
            while self._pending > 0:
                remaining = None if deadline is None \
                    else deadline - _time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._idle.wait(remaining)
            return True

    def close(self, timeout=None):
        """Drain, stop the writer thread, deregister (idempotent)."""
        with self._lock:
            already = self._closed
            self._closed = True
        if already:
            return
        self.wait_until_finished(timeout=timeout)
        self._q.put(None)
        self._thread.join(timeout=10.0 if timeout is None else timeout)
        with _LIVE_LOCK:
            _LIVE_SNAPSHOTTERS.discard(self)

    # -- introspection (locked: written by the writer thread) --------------
    @property
    def pending_writes(self):
        with self._lock:
            return self._pending

    @property
    def snapshots_skipped(self):
        with self._lock:
            return self._skipped

    @property
    def snapshots_written(self):
        with self._lock:
            return self._written

    @property
    def errors(self):
        """``(fname, exception)`` pairs from failed background writes."""
        with self._lock:
            return list(self._errors)

    # -- writer thread -----------------------------------------------------
    def _run(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            payload, manifest, fname = item
            tr = _telemetry.maybe_trace("snapshot", server="ckpt-writer") \
                if _telemetry.ACTIVE else None
            try:
                _write_payload(payload, manifest, fname, trace=tr)
                with self._lock:
                    self._written += 1
                cb = self._on_commit
                if cb is not None:
                    try:
                        cb(fname)
                    except Exception:
                        _logger.exception(
                            "AsyncSnapshotter: on_commit hook failed "
                            "for %s", fname)
            except Exception as exc:
                _logger.error("AsyncSnapshotter: background write of %s "
                              "failed: %s", fname, exc)
                with self._lock:
                    self._errors.append((fname, exc))
            finally:
                if tr is not None:
                    tr.root.end()
                    tr.finish()
                with self._idle:
                    self._pending -= 1
                    _telemetry.registry().gauge(
                        "ckpt_pending_writes").set(self._pending)
                    self._idle.notify_all()


class CheckpointManager:
    """Periodic, retained, preemption-safe checkpoints for a TrainStep.

    ``every_n_steps`` drives ``maybe_save()`` (call it after each step, or
    hand ``callback.do_step_checkpoint(manager)`` to ``fit`` as a
    batch-end callback); ``keep_last`` bounds disk by deleting the oldest
    snapshots after each successful save.  Writes go through
    ``save_train_step`` so every snapshot is atomic; stale ``.tmp`` orphans
    from crashed writes are cleaned opportunistically.  Multi-process:
    rank 0 writes and prunes, every rank synchronises inside
    ``save_train_step``.

    ``async_save=True`` routes writes through an ``AsyncSnapshotter``:
    ``save()``/``maybe_save()`` block only for the device→host fetch and
    the commit + retention pruning happen on the writer thread.  A save
    landing while ``max_pending`` writes are still in flight is skipped
    (see ``snapshots_skipped``).  Call ``wait_until_finished()`` before
    reading the directory, and ``close()`` when done (the module-level
    ``flush_pending()`` drains every live snapshotter on SIGTERM /
    nonfinite-abort exits).
    """

    def __init__(self, step, directory, every_n_steps=0, keep_last=3,
                 prefix="ckpt", async_save=False, max_pending=1):
        self.step = step
        self.directory = str(directory)
        self.every_n_steps = int(every_n_steps)
        self.keep_last = max(1, int(keep_last))
        self.prefix = prefix
        self._last_saved = None
        # retention runs on the caller thread (sync) or the writer thread
        # (async on_commit) — one lock so concurrent prunes never race
        self._retain_lock = threading.Lock()
        self._snapshotter = AsyncSnapshotter(
            max_pending=max_pending,
            on_commit=lambda _fname: self._retain()) if async_save else None
        if jax.process_index() == 0:
            os.makedirs(self.directory, exist_ok=True)

    def _fname(self, num_update):
        return os.path.join(self.directory,
                            f"{self.prefix}-{num_update:08d}.npz")

    def save(self):
        """Snapshot now; returns the committed path — or, async, the
        DESTINED path (committed once the writer lands it; None when the
        bounded queue skipped this save)."""
        n = int(self.step._num_update)
        fname = self._fname(n)
        if self._snapshotter is not None:
            if not self._snapshotter.save(self.step, fname):
                return None
            self._last_saved = n
            return fname
        save_train_step(self.step, fname)
        self._last_saved = n
        self._retain()
        return fname

    def maybe_save(self):
        """Snapshot iff ``every_n_steps`` divides the step count (and this
        step was not already saved); returns the path or None."""
        n = int(self.step._num_update)
        if self.every_n_steps <= 0 or n == 0 or n % self.every_n_steps:
            return None
        if self._last_saved == n:
            return None
        return self.save()

    def checkpoints(self):
        return list_checkpoints(self.directory, self.prefix)

    def latest_step(self):
        """Newest committed snapshot's step, or None when empty — the
        one-call progress probe (the supervisor-side twin is
        ``elastic.latest_committed_step`` on the same directory)."""
        return latest_step(self.directory, self.prefix)

    def resume_latest(self):
        """``resume_latest(step, directory)`` with this manager's step."""
        return resume_latest(self.step, self.directory, self.prefix)

    def wait_for_new(self, last_seen=None, timeout=None, poll=0.1):
        """``wait_for_new`` against this manager's directory/prefix —
        the polling hook a ``serving.WeightUpdater`` watches."""
        return wait_for_new(self.directory, last_seen=last_seen,
                            timeout=timeout, prefix=self.prefix, poll=poll)

    def wait_until_finished(self, timeout=None):
        """Drain pending async writes (no-op when sync); True when the
        directory reflects every accepted ``save()``."""
        if self._snapshotter is None:
            return True
        return self._snapshotter.wait_until_finished(timeout=timeout)

    def close(self, timeout=None):
        """Drain and stop the async writer (no-op when sync)."""
        if self._snapshotter is not None:
            self._snapshotter.close(timeout=timeout)

    @property
    def snapshots_skipped(self):
        """Saves dropped by the async bounded queue (0 when sync)."""
        if self._snapshotter is None:
            return 0
        return self._snapshotter.snapshots_skipped

    @property
    def write_errors(self):
        """``(fname, exception)`` pairs from failed async writes."""
        if self._snapshotter is None:
            return []
        return self._snapshotter.errors

    def _retain(self):
        if jax.process_index() != 0:
            return
        with self._retain_lock:
            cks = self.checkpoints()
            newest = cks[-1][1] if cks else None
            for _, path in cks[:-self.keep_last]:
                if path == newest:
                    # never prune the newest committed snapshot — it is
                    # the one a wait_for_new watcher was just handed and
                    # the one resume must always find
                    continue
                try:
                    os.remove(path)
                except OSError:
                    pass
            for name in os.listdir(self.directory):
                if name.startswith(self.prefix + "-") and \
                        name.endswith(".tmp"):
                    try:
                        os.remove(os.path.join(self.directory, name))
                    except OSError:
                        pass
