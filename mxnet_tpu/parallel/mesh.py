"""Device mesh management.

ref: the reference scales via KVStore device lists (src/kvstore/comm.h —
CommDevice over ctx lists) and `group2ctx` device groups
(src/executor/graph_executor.cc — AssignContext).  TPU-native, placement is a
`jax.sharding.Mesh` with named axes; every parallelism strategy is an axis:

    dp    data parallel (batch sharded; grads all-reduced by XLA over ICI)
    fsdp  ZeRO-style parameter sharding on top of dp traffic
    tp    tensor parallel (megatron-style sharded matmuls)
    pp    pipeline parallel (stage-sharded layer stacks, microbatch schedule)
    sp    sequence/context parallel (ring attention / Ulysses)
    ep    expert parallel (MoE dispatch)

The reference has only dp + limited model parallel (SURVEY.md §2.3); the rest
are first-class here.
"""
from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["AXES", "make_mesh", "current_mesh", "default_mesh", "MeshScope",
           "replicated", "named_sharding", "shard_map", "validate_specs"]


def _compat_shard_map():
    """jax.shard_map across versions: older jax exposes it only under
    jax.experimental with the replication-check kwarg named ``check_rep``
    (renamed ``check_vma`` when promoted to the top level)."""
    try:
        from jax import shard_map as sm
        return sm
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm
        import functools

        @functools.wraps(_sm)
        def sm(f=None, **kw):
            if "check_vma" in kw:
                kw["check_rep"] = kw.pop("check_vma")
            if f is None:
                return lambda g: _sm(g, **kw)
            return _sm(f, **kw)
        return sm


_jax_shard_map = _compat_shard_map()


def _spec_axis_names(specs):
    """Every axis name appearing in a specs pytree (PartitionSpec
    leaves; entries may be names or tuples of names)."""
    out = []
    for spec in jax.tree_util.tree_leaves(
            specs, is_leaf=lambda s: isinstance(s, PartitionSpec)):
        if not isinstance(spec, PartitionSpec):
            continue
        for entry in spec:
            names = entry if isinstance(entry, tuple) else (entry,)
            for name in names:
                if isinstance(name, str):
                    out.append(name)
    return out


def validate_specs(mesh, in_specs=None, out_specs=None):
    """Raise ``ValueError`` naming the axis when an in/out spec names a
    mesh axis that does not exist — the runtime twin of mxlint's
    ``spmd-axis-unknown``.  Without this a typo'd axis surfaces as a
    deep jax internal error far from the call site."""
    axes = set(getattr(mesh, "axis_names", ()) or ())
    if not axes:
        return
    for role, specs in (("in_specs", in_specs), ("out_specs", out_specs)):
        for name in _spec_axis_names(specs):
            if name not in axes:
                raise ValueError(
                    f"shard_map {role} names axis {name!r}, which is "
                    f"not one of the mesh axes {tuple(sorted(axes))} — "
                    f"a typo'd axis would otherwise fail deep inside "
                    f"jax (or silently change the partitioning)")


def shard_map(f=None, *, mesh=None, in_specs=None, out_specs=None, **kw):
    """``jax.shard_map`` with call-time axis validation: every axis
    named in ``in_specs``/``out_specs`` must exist in
    ``mesh.axis_names`` (``validate_specs``).  Currying (``f=None``)
    and the ``check_vma``/``check_rep`` compat of older jax are
    preserved."""
    if mesh is not None:
        validate_specs(mesh, in_specs, out_specs)
    inner = {}
    if mesh is not None:
        inner["mesh"] = mesh
    if in_specs is not None:
        inner["in_specs"] = in_specs
    if out_specs is not None:
        inner["out_specs"] = out_specs
    inner.update(kw)
    if f is None:
        return lambda g: _jax_shard_map(g, **inner)
    return _jax_shard_map(f, **inner)

# Canonical axis order: collectives that ride adjacent devices (tp, sp) go
# last so they land on the fastest ICI neighbours in the device enumeration.
AXES = ("pp", "dp", "fsdp", "ep", "sp", "tp")

_tls = threading.local()


def make_mesh(axes=None, devices=None, **axis_sizes):
    """Build a named-axis mesh, e.g. ``make_mesh(dp=2, tp=4)``.

    Axis sizes must multiply to the device count; any remainder axis may be
    given as -1 (inferred).  With no args, all devices go onto one ``dp`` axis
    — the TPU-native equivalent of KVStore "device" over all local GPUs.
    """
    if axes:
        axis_sizes = dict(axes, **axis_sizes)
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if not axis_sizes:
        axis_sizes = {"dp": n}
    ordered = OrderedDict()
    for name in AXES:
        if name in axis_sizes:
            ordered[name] = axis_sizes.pop(name)
    for name, size in axis_sizes.items():  # user-defined extra axes
        ordered[name] = size
    infer = [k for k, v in ordered.items() if v == -1]
    if len(infer) > 1:
        raise ValueError("at most one axis size may be -1")
    known = int(np.prod([v for v in ordered.values() if v != -1]))
    if infer:
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        ordered[infer[0]] = n // known
        known = n
    if known != n:
        raise ValueError(f"mesh axes {dict(ordered)} need {known} devices, "
                         f"have {n}")
    arr = np.asarray(devices).reshape(tuple(ordered.values()))
    return Mesh(arr, tuple(ordered.keys()))


class MeshScope:
    """``with MeshScope(mesh):`` makes it the framework-current mesh."""

    def __init__(self, mesh):
        self.mesh = mesh

    def __enter__(self):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self.mesh)
        return self.mesh

    def __exit__(self, *exc):
        _tls.stack.pop()


def current_mesh():
    stack = getattr(_tls, "stack", None)
    if stack:
        return stack[-1]
    return None


def default_mesh():
    """Current mesh, or an all-``dp`` mesh over every device."""
    m = current_mesh()
    if m is None:
        m = make_mesh()
    return m


def replicated(mesh):
    return NamedSharding(mesh, PartitionSpec())


def named_sharding(mesh, *spec):
    return NamedSharding(mesh, PartitionSpec(*spec))
