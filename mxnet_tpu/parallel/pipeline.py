"""Pipeline parallelism (GPipe-style microbatch schedule over a ``pp`` axis).

The reference has NO pipeline parallelism (SURVEY.md §2.3: only symbolic
``group2ctx`` device groups with executor-inserted copies).  TPU-native
design: stage parameters are STACKED along a leading dim sharded over the
``pp`` mesh axis (stage i's slice lives on pp-rank i), and the schedule is a
``lax.scan`` over ticks inside shard_map — each tick every device applies its
stage to its current microbatch and ``ppermute``s the activation to the next
rank.  Warmup/cooldown bubbles are masked compute, the canonical GPipe cost
of (P-1)/(M+P-1).

Constraints (v1): every stage must map activations of one fixed shape to the
same shape (the transformer-block case); the incoming batch splits into
``microbatches`` equal microbatches.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from .mesh import current_mesh, shard_map

__all__ = ["gpipe", "PipelineStack"]


def gpipe(stage_apply, stacked_params, x, mesh=None, axis="pp",
          batch_axis="dp", microbatches=None):
    """Run ``x`` through P pipelined stages.

    stage_apply(params_slice, act) -> act', shape-preserving.
    stacked_params: pytree whose leaves have leading dim P (sharded on axis).
    x: (B, ...) global batch; split into M microbatches along dim 0.
    Returns (B, ...).
    """
    mesh = mesh if mesh is not None else current_mesh()
    if mesh is None:
        raise ValueError("gpipe needs a mesh: pass mesh= or enter a MeshScope")
    P = mesh.shape[axis]
    for path, leaf in jax.tree_util.tree_flatten_with_path(stacked_params)[0]:
        if leaf.ndim < 1 or leaf.shape[0] != P:
            raise ValueError(
                f"gpipe: stacked param {jax.tree_util.keystr(path)} has "
                f"leading dim {leaf.shape[:1]} but mesh axis {axis!r} has "
                f"size {P}; every stacked leaf must have leading dim == "
                f"number of pipeline stages == mesh.shape[{axis!r}]")
    M = microbatches if microbatches is not None else P
    B = x.shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible into {M} microbatches")
    bm = B // M
    xm = x.reshape((M, bm) + x.shape[1:])

    p_spec = jax.tree_util.tree_map(lambda _: PartitionSpec(axis), stacked_params)
    bdim = batch_axis if batch_axis in mesh.shape else None
    x_spec = PartitionSpec(None, bdim)
    out_spec = PartitionSpec(None, bdim)  # stays (M, bm, ...); flatten outside

    import inspect
    takes_rng = len(inspect.signature(stage_apply).parameters) >= 3
    base_key = None
    if takes_rng:
        from .. import random as _random
        base_key = _random.next_key()

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(p_spec, x_spec, PartitionSpec()), out_specs=out_spec,
        check_vma=False)
    def _run(params_loc, xm_loc, key):
        # params_loc leaves: (1, ...) -> (...)
        params_me = jax.tree_util.tree_map(
            lambda a: jnp.squeeze(a, axis=0), params_loc)
        rank = jax.lax.axis_index(axis)
        T = M + P - 1
        act0 = jnp.zeros(xm_loc.shape[1:], xm_loc.dtype)
        out0 = jnp.zeros(xm_loc.shape, xm_loc.dtype)
        send = [(p, p + 1) for p in range(P - 1)]
        # distinct RNG stream per stage/dp-shard/tick (stacked dropout masks
        # must be independent across stages and microbatches)
        key_me = jax.random.fold_in(key, rank) if takes_rng else None
        if takes_rng and batch_axis in mesh.shape:
            key_me = jax.random.fold_in(
                key_me, jax.lax.axis_index(batch_axis))

        def tick(carry, t):
            recv, out = carry
            x_t = jax.lax.dynamic_index_in_dim(
                xm_loc, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            my_in = jnp.where(rank == 0, x_t, recv)
            if takes_rng:
                y = stage_apply(params_me, my_in,
                                jax.random.fold_in(key_me, t))
            else:
                y = stage_apply(params_me, my_in)
            y_next = jax.lax.ppermute(y, axis, send) if P > 1 else y
            widx = t - (P - 1)
            write = (widx >= 0) & (rank == P - 1)
            upd = jax.lax.dynamic_update_index_in_dim(
                out, y, jnp.clip(widx, 0, M - 1), 0)
            out = jnp.where(write, upd, out)
            return (y_next, out), None

        (_, out), _ = jax.lax.scan(tick, (act0, out0), jnp.arange(T))
        # only the last rank holds real outputs (others are zero) -> replicate
        mine = jnp.where(rank == P - 1, out, jnp.zeros_like(out))
        return jax.lax.psum(mine, axis)   # (M, bm_local, ...)

    x_sh = NamedSharding(mesh, x_spec)
    p_sh = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), p_spec)
    eager = not any(isinstance(l, jax.core.Tracer)
                    for l in jax.tree_util.tree_leaves((stacked_params, x)))
    if eager:
        stacked_params = jax.tree_util.tree_map(
            jax.device_put, stacked_params, p_sh)
        xm = jax.device_put(xm, x_sh)
    if base_key is None:
        base_key = jax.random.key(0)  # unused by 2-arg stage fns
    out = _run(stacked_params, xm, base_key)
    out = out.reshape((B,) + out.shape[2:])
    if eager:
        out = jax.device_put(out, jax.local_devices()[0])
    return out


def _make_pipeline_stack():
    """Deferred import cycle breaker: gluon imports parallel pieces lazily."""
    from ..gluon.block import Block
    from ..ndarray import NDArray
    from .functional import FunctionalState, functional_call
    from .sharding import ShardingRules
    from .. import initializer as init_mod
    from .. import random as _random
    from .. import autograd as _autograd
    import re

    class PipelineStack(Block):
        """Gluon pipeline of N identical-structure stages (GPipe over ``pp``).

        Built from a factory producing one HybridBlock stage; owns STACKED
        parameters (leading dim = num_stages) so each stage's slice lands on
        its pp rank — pass ``stack.sharding_rules()`` to TrainStep.
        """

        def __init__(self, stage_factory, num_stages, microbatches=None,
                     axis="pp", prefix=None, params=None):
            super().__init__(prefix=prefix, params=params)
            self.num_stages = num_stages
            self.microbatches = microbatches
            self.axis = axis
            with self.name_scope():
                self.template = stage_factory()
            self.template.initialize()
            stacked_names = []
            for name, p in sorted(self.template.collect_params().items()):
                if p._deferred_init is not None:
                    raise ValueError(
                        f"pipeline stages need fully-specified shapes; "
                        f"parameter '{name}' has deferred init "
                        f"(pass in_units/in_channels)")
                draws = [p.data()._data]
                initializer = init_mod.create(
                    p.init if p.init is not None else "uniform")
                for _ in range(num_stages - 1):
                    draws.append(jnp.asarray(
                        initializer(p.name, p.shape, p.dtype)))
                arr = jnp.stack(draws)
                p._data = NDArray(arr)
                p.shape = tuple(arr.shape)
                if p._grad_req != "null":
                    p._data.attach_grad(p._grad_req)
                stacked_names.append(name)
            self._stacked_names = stacked_names

        def sharding_rules(self):
            """Leading stage dim of every stacked param -> the pp axis."""
            return ShardingRules(
                rules=[(re.escape(n), (self.axis,))
                       for n in self._stacked_names])

        def forward(self, x):
            names = self._stacked_names
            plist = [self.template.collect_params()[n] for n in names]
            stacked = [p.data()._data for p in plist]
            template = self.template
            state = FunctionalState()

            def stage_apply(params_slice, act, rng_key):
                arrays = [params_slice[n] for n in names]
                outs = functional_call(
                    template, plist, arrays, ("*",), [act],
                    rng_key, _autograd.is_training(), state)
                return outs[0]

            params_tree = dict(zip(names, stacked))
            xv = x._data if isinstance(x, NDArray) else x
            out = gpipe(stage_apply, params_tree, xv, axis=self.axis,
                        microbatches=self.microbatches)
            return NDArray(out) if isinstance(x, NDArray) else out

    return PipelineStack


PipelineStack = _make_pipeline_stack()