"""Quantized gradient collectives.

ref: the reference's KVStore moves full-precision gradients between
devices (src/kvstore/comm.h — CommDevice reduces in the array dtype);
its only compression is 2-bit gradient compression on the PS path
(src/kvstore/gradient_compression.cc), which never made it to the dense
allreduce.  PERF.md establishes the hot paths here are bandwidth-bound,
not FLOP-bound — and MULTICHIP runs still move f32/bf16 gradients over
ICI.  *EQuARX* (arXiv:2506.17615, PAPERS.md) shows a quantized
AllReduce recovers most of that wire traffic at negligible quality
cost.  This module is that trade, jax-native:

- **Chunked symmetric quantization** (``quantize_chunked`` /
  ``dequantize_chunked``): int8 payloads with one f32 scale per
  ``chunk`` elements (amax / 127), so one outlier only poisons its own
  chunk, not the tensor.  Rounding is *stochastic* when a PRNG key is
  supplied — ``floor(x/scale + u)``, ``u ~ U[0,1)`` — which makes the
  quantizer unbiased: over steps the rounding error averages out
  instead of accumulating as a directional drift (the property the
  tier-1 unbiasedness test checks statistically).
- **Stochastic bf16 cast** (``cast_bf16``): the same unbiasedness for
  the bf16 wire format, via integer arithmetic on the f32 bit pattern
  (adding 16 random low bits carries into the kept mantissa with
  probability equal to the truncated remainder).
- **The reduction stage** (``reduce_gradients``): called INSIDE a
  ``shard_map`` over the data-parallel axis, it replaces the
  sharding-inserted full-precision all-reduce with a two-phase
  compressed exchange — quantize the local gradient, ``all_to_all``
  the int8 slices (a reduce-scatter whose wire payload is 1/4 the f32
  bytes), dequantize + sum the owned slice, re-quantize it, and
  ``all_gather`` the int8 result.  Every device dequantizes identical
  payloads, so the output is bit-identical fleet-wide and may be
  declared replicated.  ``bf16`` mode is simpler: one ``psum`` over the
  stochastically-cast payload (half the f32 bytes).

Non-finite gradients survive the round-trip as non-finite (an inf amax
poisons its chunk's scale), so ``TrainStep(skip_nonfinite=True)``'s
fused guard keeps working unchanged on the dequantized values.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["GRAD_REDUCE_MODES", "ACTIVATION_REDUCE_MODES",
           "quantize_chunked", "dequantize_chunked", "cast_bf16",
           "reduce_gradients", "all_reduce_activations"]

#: the TrainStep ``grad_reduce=`` vocabulary ("f32" = the implicit
#: sharding-inserted full-precision collective, the pre-ISSUE-8 path)
GRAD_REDUCE_MODES = ("f32", "bf16", "int8")

#: the GenerationServer ``tp_collectives=`` vocabulary — the wire
#: format of the per-layer activation all-reduce on the tensor-parallel
#: decode path (EQuARX, arXiv:2506.17615: decode is latency-bound on
#: collective bytes, so the activation exchange quantizes)
ACTIVATION_REDUCE_MODES = ("f32", "int8")

#: default elements per quantization chunk (one f32 scale each: 1.6%
#: overhead on the int8 payload)
DEFAULT_CHUNK = 256

# key decorrelation: phase-2 rounding must not reuse phase-1's stream
_PHASE2_SALT = 0x5EED


def _blocks(x, chunk):
    """``(..., L)`` → ``(..., nc, c)`` zero-padded chunk view,
    ``c = min(chunk, L)``."""
    L = x.shape[-1]
    c = max(1, min(int(chunk), L))
    nc = -(-L // c)
    pad = nc * c - L
    if pad:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    return x.reshape(x.shape[:-1] + (nc, c))


def quantize_chunked(x, chunk=DEFAULT_CHUNK, key=None):
    """Symmetric per-chunk int8 quantization over the last axis.

    Returns ``(q, scales)``: ``q`` int8 of shape ``(..., nc, c)`` (the
    last axis zero-padded up to a whole number of chunks) and
    ``scales`` f32 of shape ``(..., nc)``.  With ``key`` the rounding
    is stochastic (unbiased); without, round-to-nearest (deterministic
    — what post-training weight quantization wants)."""
    xb = _blocks(x.astype(jnp.float32), chunk)
    amax = jnp.max(jnp.abs(xb), axis=-1)
    # != 0, not > 0: a NaN amax (any NaN element) must KEEP its NaN
    # scale so the whole chunk dequantizes non-finite — `> 0` is False
    # for NaN and would silently launder the poison into finite zeros,
    # under the nose of TrainStep's skip_nonfinite guard
    scales = jnp.where(amax != 0, amax / 127.0, 1.0)
    y = xb / scales[..., None]
    if key is None:
        q = jnp.round(y)
    else:
        q = jnp.floor(y + jax.random.uniform(key, y.shape))
    return jnp.clip(q, -127.0, 127.0).astype(jnp.int8), scales


def dequantize_chunked(q, scales, length, dtype=jnp.float32):
    """Inverse of ``quantize_chunked``: ``(..., nc, c)`` int8 + scales
    → ``(..., length)`` in ``dtype`` (padding stripped)."""
    y = q.astype(jnp.float32) * scales[..., None]
    y = y.reshape(y.shape[:-2] + (-1,))
    return y[..., :length].astype(dtype)


def cast_bf16(x, key=None):
    """bf16 cast; stochastic (unbiased) when ``key`` is given.

    Works on the f32 bit pattern: adding 16 random low bits carries
    into the kept mantissa with probability equal to the truncated
    remainder, so ``E[cast_bf16(x, key)] == x`` for finite x.  Exactly
    representable values never move.  Non-finite inputs are not
    preserved bit-exactly (a carry out of the mantissa can walk an inf
    into NaN) — they stay non-finite, which is all the skip_nonfinite
    guard needs."""
    x32 = x.astype(jnp.float32)
    if key is None:
        return x32.astype(jnp.bfloat16)
    u = lax.bitcast_convert_type(x32, jnp.uint32)
    u = u + (jax.random.bits(key, x32.shape, jnp.uint32) & jnp.uint32(0xFFFF))
    return lax.bitcast_convert_type((u >> jnp.uint32(16)).astype(jnp.uint16),
                                    jnp.bfloat16)


def _reduce_leaf_int8(g, axis_name, n_dev, key, chunk, mean):
    """Two-phase int8 reduction of ONE gradient leaf (inside shard_map).

    Phase 1 (reduce-scatter shape): slice the local gradient ``n_dev``
    ways, quantize, ``all_to_all`` — int8 moves, each device ends up
    holding every peer's version of the slice it owns, dequantizes and
    sums.  Phase 2 (all-gather shape): the owner re-quantizes its
    reduced slice once; ``all_gather`` hands every device the same int8
    payloads, so the dequantized result is bit-identical everywhere
    (the replication the out_specs claim)."""
    shape, dtype, n = g.shape, g.dtype, g.size
    m = -(-n // n_dev)
    flat = g.astype(jnp.float32).reshape(-1)
    if n_dev * m != n:
        flat = jnp.pad(flat, (0, n_dev * m - n))
    x = flat.reshape(n_dev, m)
    q, s = quantize_chunked(x, chunk, key)
    q = lax.all_to_all(q, axis_name, 0, 0, tiled=True)
    s = lax.all_to_all(s, axis_name, 0, 0, tiled=True)
    owned = jnp.sum(dequantize_chunked(q, s, m), axis=0)        # (m,)
    if mean:
        owned = owned / n_dev
    key2 = None if key is None else jax.random.fold_in(key, _PHASE2_SALT)
    q2, s2 = quantize_chunked(owned, chunk, key2)
    gq = lax.all_gather(q2, axis_name, axis=0)                  # (n_dev, ...)
    gs = lax.all_gather(s2, axis_name, axis=0)
    out = dequantize_chunked(gq, gs, m)                         # (n_dev, m)
    return out.reshape(-1)[:n].reshape(shape).astype(dtype)


def all_reduce_activations(x, axis_name, n_dev, mode="int8", key=None,
                           chunk=DEFAULT_CHUNK):
    """Cross-device SUM of one activation tensor with a compressed wire
    format — the serving twin of ``reduce_gradients``, called INSIDE a
    ``shard_map`` over ``axis_name`` with ``x`` the device's local
    partial product (Megatron row-parallel matmul output).  Returns the
    summed activations in ``x``'s dtype, bit-identical on every device
    (the int8 path all-gathers ONE set of quantized payloads that every
    device dequantizes the same way — the replication an out_spec may
    honestly claim).

    ``mode``: ``"f32"`` = plain ``psum`` (uncompressed reference),
    ``"int8"`` = the two-phase chunked exchange (``all_to_all`` int8
    slices → dequant+sum the owned slice → requantize → ``all_gather``)
    at ~1/4 the f32 wire bytes.  ``key=None`` (the serving default)
    rounds to nearest: decode wants the same traffic to produce the
    same tokens on every replica, and the inference forward takes one
    bounded quantization error per layer rather than accumulating drift
    across steps — the unbiasedness stochastic rounding buys gradients
    has no equivalent payoff here."""
    if mode not in ACTIVATION_REDUCE_MODES:
        raise ValueError(f"all_reduce_activations: mode {mode!r} not in "
                         f"{ACTIVATION_REDUCE_MODES}")
    if mode == "f32":
        return lax.psum(x, axis_name)
    return _reduce_leaf_int8(x, axis_name, n_dev, key, chunk, mean=False)


def reduce_gradients(grads, axis_name, n_dev, mode="int8", key=None,
                     reduce="mean", chunk=DEFAULT_CHUNK):
    """Cross-device gradient reduction with a compressed wire format.

    Call INSIDE a ``shard_map`` over ``axis_name`` with ``grads`` the
    local (per-device, full-size) gradient leaves.  Returns the reduced
    leaves — the cross-device mean (``reduce="mean"``) or sum — in each
    leaf's original dtype, identical on every device.

    ``mode``: ``"f32"`` = plain ``psum`` (the uncompressed reference
    point), ``"bf16"`` = stochastic-cast payload + psum (2x fewer wire
    bytes vs f32), ``"int8"`` = two-phase chunked int8 exchange (4x).
    ``key`` drives the stochastic rounding (fold the device index in
    BEFORE calling, so replicas round independently); ``key=None``
    rounds to nearest — deterministic, but biased over many steps."""
    if mode not in GRAD_REDUCE_MODES:
        raise ValueError(f"reduce_gradients: mode {mode!r} not in "
                         f"{GRAD_REDUCE_MODES}")
    if reduce not in ("mean", "sum"):
        raise ValueError(f"reduce_gradients: reduce {reduce!r} not in "
                         f"('mean', 'sum')")
    mean = reduce == "mean"
    out = []
    for i, g in enumerate(grads):
        lkey = None if key is None else jax.random.fold_in(key, i)
        if mode == "f32":
            # mxlint: disable=spmd-collective-in-loop -- deliberate
            # per-leaf loop: gradient leaves have heterogeneous
            # shapes/dtypes, flattening them into one collective would
            # defeat the per-chunk scales (and XLA overlaps the
            # unrolled per-leaf collectives on ICI anyway)
            r = lax.psum(g, axis_name)
            r = (r / n_dev).astype(g.dtype) if mean else r
        elif mode == "bf16":
            h = cast_bf16(g.astype(jnp.float32) / n_dev if mean else g, lkey)
            # mxlint: disable=spmd-collective-in-loop -- same deliberate
            # per-leaf loop as the f32 branch (heterogeneous leaves)
            r = lax.psum(h, axis_name).astype(g.dtype)
        else:
            r = _reduce_leaf_int8(g, axis_name, n_dev, lkey, chunk, mean)
        out.append(r)
    return out
