"""Environment-variable configuration (the ``MXNET_*`` knob system).

ref: docs/static_site/src/pages/api/faq/env_var.md + the ``dmlc::GetEnv``
pattern used throughout src/ — every tunable behavior is controlled by an
``MXNET_*`` environment variable with a documented default.

This module is the single registry: each knob declares its type, default,
and what it drives.  Knobs whose reference meaning is subsumed by XLA/PJRT
(thread pools, GPU memory pools, cuDNN autotune) are registered as
``accepted`` so reference launch scripts run unchanged, but changing them
is a documented no-op here.  ``describe()`` prints the full table.
"""
from __future__ import annotations

import os

__all__ = ["get", "describe", "KNOBS"]


class Knob:
    __slots__ = ("name", "default", "type", "doc", "wired")

    def __init__(self, name, default, type_, doc, wired=True):
        self.name = name
        self.default = default
        self.type = type_
        self.doc = doc
        self.wired = wired


def _as_bool(v):
    return str(v).lower() in ("1", "true", "yes", "on")


KNOBS = {k.name: k for k in [
    # --- live knobs (change behavior in this build) ----------------------
    Knob("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice", str,
         "Execution engine. 'NaiveEngine' forces synchronous dispatch "
         "(every op blocks until complete) — the reference's race-bisect "
         "debugging mode (SURVEY §5.2)."),
    Knob("MXNET_CPU_WORKER_NTHREADS", 0, int,
         "Default DataLoader worker-process count when num_workers is not "
         "passed (0 = in-process loading)."),
    Knob("MXNET_PROFILER_AUTOSTART", 0, int,
         "1 = start the profiler at import; dump to MXNET_PROFILER_FILENAME "
         "at exit."),
    Knob("MXNET_PROFILER_FILENAME", "profile.json", str,
         "Trace output path for the autostarted profiler."),
    Knob("MXNET_SEED", None, int,
         "Global PRNG seed applied at import (mx.random.seed)."),
    # --- accepted for compatibility (no-ops under XLA/PJRT, documented) --
    Knob("MXNET_STORAGE_FALLBACK_LOG_VERBOSE", 1, int,
         "No silent sparse→dense fallback exists here: dense-only ops raise "
         "a storage-type error instead (mxnet_tpu/ndarray).", wired=False),
    Knob("MXNET_KVSTORE_BIGARRAY_BOUND", 1000000, int,
         "Server-side big-array sharding bound — no parameter servers here "
         "(collectives over ICI/DCN).", wired=False),
    Knob("MXNET_EXEC_BULK_EXEC_TRAIN", 1, int,
         "Engine bulking — subsumed by hybridize/jit whole-graph compile.",
         wired=False),
    Knob("MXNET_EXEC_BULK_EXEC_INFERENCE", 1, int,
         "Engine bulking — subsumed by jit.", wired=False),
    Knob("MXNET_GPU_MEM_POOL_RESERVE", 5, int,
         "Percent of MXNET_HOST_MEM_POOL_LIMIT_MB kept out of the host "
         "staging-buffer pool (device HBM itself is managed by PJRT; see "
         "mxnet_tpu/storage.py)."),
    Knob("MXNET_GPU_MEM_POOL_TYPE", "Naive", str,
         "Host staging-buffer pool strategy: Naive (exact-size buckets), "
         "Round (pow2 buckets below the linear cutoff), or Unpooled "
         "(ref: pooled_storage_manager.h; device HBM stays with PJRT)."),
    Knob("MXNET_GPU_MEM_POOL_ROUND_LINEAR_CUTOFF", 24, int,
         "Round-pool strategy: sizes below 2^cutoff round to a power of "
         "two; above, to a page multiple."),
    Knob("MXNET_HOST_MEM_POOL_LIMIT_MB", 256, int,
         "Upper bound on host staging buffers retained by the pool."),
    Knob("MXNET_ENGINE_TRACK_BYTES_MB", 64, int,
         "Byte budget for the waitall tracking ring: newest arrays are "
         "held (strongly) up to this budget so waitall stays a true "
         "barrier without pinning unbounded HBM."),
    Knob("MXNET_STORAGE_ACCOUNTING", 1, int,
         "1 = every NDArray registers its bytes with the storage manager "
         "(mx.storage.stats(), gpu_memory_info fallback); 0 disables."),
    Knob("MXNET_TPU_HBM_CAPACITY_MB", 16384, int,
         "Assumed per-chip HBM capacity when the PJRT plugin reports no "
         "memory_stats (v5e = 16 GB); used by gpu_memory_info."),
    Knob("MXNET_CUDNN_AUTOTUNE_DEFAULT", 1, int,
         "cuDNN algo search — XLA picks conv strategies at compile time.",
         wired=False),
    Knob("MXNET_ENFORCE_DETERMINISM", 0, int,
         "XLA TPU execution is deterministic by construction.", wired=False),
    Knob("MXNET_SAFE_ACCUMULATION", 1, int,
         "Wide-accumulator reductions — always on (norm ops accumulate in "
         "f32 regardless; see ops/nn.py _moments).", wired=False),
    Knob("MXNET_GPU_WORKER_NTHREADS", 2, int,
         "Per-GPU worker threads — PJRT streams replace them.", wired=False),
]}


def get(name, default=None):
    """Typed read of a knob (env var wins over registry default)."""
    knob = KNOBS.get(name)
    raw = os.environ.get(name)
    if knob is None:
        return raw if raw is not None else default
    if raw is None:
        return knob.default if default is None else default
    if knob.type is int:
        try:
            return int(raw)
        except ValueError:
            return knob.default
    if knob.type is bool:
        return _as_bool(raw)
    return raw


def describe():
    """Render the knob table (ref: env_var.md)."""
    out = [f"{'variable':<38s}{'default':<26s}{'wired':<7s}description"]
    for k in KNOBS.values():
        out.append(f"{k.name:<38s}{str(k.default):<26s}"
                   f"{'yes' if k.wired else 'n/a':<7s}{k.doc}")
    return "\n".join(out)


def _apply_startup():
    """Run once at package import: knobs that act at process start."""
    seed = get("MXNET_SEED")
    if seed is not None:
        from . import random as _random
        _random.seed(int(seed))
    if not get("MXNET_STORAGE_ACCOUNTING"):
        from . import storage
        storage.set_accounting(False)
    if get("MXNET_PROFILER_AUTOSTART"):
        import atexit

        from . import profiler
        profiler.set_config(filename=get("MXNET_PROFILER_FILENAME"))
        profiler.start()
        atexit.register(lambda: (profiler.stop(), profiler.dump()))


def naive_engine():
    """True when MXNET_ENGINE_TYPE=NaiveEngine (synchronous dispatch)."""
    return get("MXNET_ENGINE_TYPE") == "NaiveEngine"
