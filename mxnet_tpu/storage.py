"""Storage manager — memory accounting + pooled host staging buffers.

ref: src/storage/storage.cc — ``Storage::Get()->Alloc/Free``;
src/storage/pooled_storage_manager.h — ``GPUPooledStorageManager`` (naive
exact-size buckets) and ``GPUPooledRoundedStorageManager`` (power-of-two
buckets below ``MXNET_GPU_MEM_POOL_ROUND_LINEAR_CUTOFF``); knobs
``MXNET_GPU_MEM_POOL_TYPE`` / ``MXNET_GPU_MEM_POOL_RESERVE``.

TPU substitution: device (HBM) allocation inside compiled programs is
planned by XLA and owned by PJRT — a user-level HBM pool would fight the
runtime, so this build does NOT re-implement device pooling.  What stays
the framework's job, and what this module provides:

1. **Device-side accounting.**  Every live ``NDArray`` registers its
   buffer bytes here, so live / peak / alloc-count introspection
   (``storage.stats()``, ``mx.context.gpu_memory_info``) works even where
   the PJRT plugin reports no ``memory_stats`` (the axon tunnel reports
   none).  Counts are *logical tensor bytes held by the framework* — XLA
   scratch and executable temps are intentionally out of scope (they are
   visible via ``Context.memory_info`` where the plugin supports it).

2. **Pooled host staging buffers.**  The data pipeline's batchify/pin
   path and RecordIO readers reuse page-sized numpy buffers instead of
   malloc churn, with the reference's two pooling strategies selected by
   ``MXNET_GPU_MEM_POOL_TYPE``: ``Naive`` (exact-size free-lists) and
   ``Round`` (power-of-two buckets below the linear cutoff).
   ``MXNET_GPU_MEM_POOL_RESERVE`` caps the pool the same way the
   reference reserves a fraction of device memory: the pool holds at most
   ``(100 - reserve)%`` of ``MXNET_HOST_MEM_POOL_LIMIT_MB``.
"""
from __future__ import annotations

import threading
import weakref

import numpy as np
from jax import core as _jax_core

__all__ = ["Storage", "Handle", "stats", "reset_peak", "pool_info",
           "release_all", "on_create"]


# ---------------------------------------------------------------------------
# device-side accounting
# ---------------------------------------------------------------------------

class _DeviceStats:
    __slots__ = ("live_bytes", "peak_bytes", "num_allocs", "num_frees",
                 "live_arrays")

    def __init__(self):
        self.live_bytes = 0
        self.peak_bytes = 0
        self.num_allocs = 0
        self.num_frees = 0
        self.live_arrays = 0

    def as_dict(self):
        return {"live_bytes": self.live_bytes,
                "peak_bytes": self.peak_bytes,
                "num_allocs": self.num_allocs,
                "num_frees": self.num_frees,
                "live_arrays": self.live_arrays}


_lock = threading.Lock()
_by_device: dict[str, _DeviceStats] = {}
# Buffers currently accounted, by id().  The finalizer is attached to the
# BUFFER (jax/numpy array), not the NDArray wrapper: wrappers rebind
# ``_data`` freely (in-place ops, out=, jit write-back) and several
# wrappers can share one buffer (detach()) — tying lifetime to the buffer
# makes the count exact under both, and id() reuse is safe because an
# entry is removed at the instant its buffer is collected.
_registered: set[int] = set()
_enabled = True


def set_accounting(enabled: bool):
    """Toggle per-NDArray accounting (MXNET_STORAGE_ACCOUNTING knob)."""
    global _enabled
    _enabled = bool(enabled)


def _dec(devkey: str, nbytes: int, bufkey: int):
    with _lock:
        if bufkey not in _registered:
            return
        _registered.discard(bufkey)
        st = _by_device.get(devkey)
        if st is not None:
            st.live_bytes -= nbytes
            st.num_frees += 1
            st.live_arrays -= 1


def on_create(nd) -> None:
    """Register the buffer behind a freshly constructed NDArray.

    Called from ``NDArray.__init__``; must stay cheap.  Tracers (abstract
    values inside jit) and zero-size arrays are skipped; a buffer already
    seen (shared or re-wrapped) costs one set lookup.
    """
    if not _enabled:
        return
    data = nd._data
    if isinstance(data, _jax_core.Tracer):
        return  # abstract value inside jit/vjp tracing — no buffer exists
    nbytes = getattr(data, "nbytes", None)
    if not nbytes or not isinstance(nbytes, int):
        return
    devkey = str(nd._ctx)
    bufkey = id(data)
    with _lock:
        if bufkey in _registered:
            return
        _registered.add(bufkey)
        st = _by_device.get(devkey)
        if st is None:
            st = _by_device[devkey] = _DeviceStats()
        st.live_bytes += nbytes
        st.num_allocs += 1
        st.live_arrays += 1
        if st.live_bytes > st.peak_bytes:
            st.peak_bytes = st.live_bytes
    try:
        weakref.finalize(data, _dec, devkey, nbytes, bufkey)
    except TypeError:  # non-weakref-able buffer type: drop the entry
        _dec(devkey, nbytes, bufkey)


def stats(device=None):
    """Per-device accounting snapshot.

    ``stats()`` → ``{devkey: {live_bytes, peak_bytes, ...}}``;
    ``stats(ctx_or_key)`` → the one device's dict (zeros if unseen).
    """
    with _lock:
        if device is None:
            return {k: v.as_dict() for k, v in _by_device.items()}
        key = device if isinstance(device, str) else str(device)
        st = _by_device.get(key)
        return st.as_dict() if st is not None else _DeviceStats().as_dict()


def live_bytes(device=None) -> int:
    with _lock:
        if device is None:
            return sum(st.live_bytes for st in _by_device.values())
        key = device if isinstance(device, str) else str(device)
        st = _by_device.get(key)
        return st.live_bytes if st is not None else 0


def reset_peak():
    """Reset peak watermarks to current live bytes (profiler epoch reset)."""
    with _lock:
        for st in _by_device.values():
            st.peak_bytes = st.live_bytes


# ---------------------------------------------------------------------------
# pooled host staging buffers
# ---------------------------------------------------------------------------

class Handle:
    """An allocated host buffer (ref: ``Storage::Handle`` — dptr/size/ctx)."""

    __slots__ = ("dptr", "size", "ctx", "_bucket", "_ptr", "_fin",
                 "__weakref__")

    def __init__(self, dptr, size, ctx, bucket, ptr=None):
        self.dptr = dptr          # numpy uint8 view, length == size
        self.size = size
        self.ctx = ctx
        self._bucket = bucket     # rounded size the pool stores it under
        self._ptr = ptr           # native pool address (None: python pool)
        self._fin = None          # leak guard for native buffers


def _pool_config():
    """(strategy, round_cutoff, limit_bytes) from the MXNET_* knobs —
    shared by the python and native pools so the reserve formula lives
    in one place."""
    from . import config
    strategy = str(config.get("MXNET_GPU_MEM_POOL_TYPE") or "Naive")
    cutoff = int(config.get("MXNET_GPU_MEM_POOL_ROUND_LINEAR_CUTOFF") or 24)
    reserve = int(config.get("MXNET_GPU_MEM_POOL_RESERVE") or 5)
    limit_mb = int(config.get("MXNET_HOST_MEM_POOL_LIMIT_MB") or 256)
    limit = limit_mb * (1 << 20) * max(0, 100 - reserve) // 100
    return strategy, cutoff, limit


class _HostPool:
    """Free-list pool over page-sized numpy buffers.

    Strategies (MXNET_GPU_MEM_POOL_TYPE):
      - ``Naive``:  exact-size buckets (GPUPooledStorageManager);
      - ``Round``:  power-of-two buckets below ``2**cutoff``, linear
        (page-rounded) above (GPUPooledRoundedStorageManager);
      - ``Unpooled``: passthrough malloc/free.
    """

    PAGE = 4096

    def __init__(self):
        self._free: dict[int, list[np.ndarray]] = {}
        self._held = 0          # bytes sitting in free lists
        self._hits = 0
        self._misses = 0
        self._lock = threading.Lock()
        self._configured = False
        self._strategy = "Naive"
        self._cutoff = 24
        self._limit = 0

    def _configure(self):
        self._strategy, self._cutoff, self._limit = _pool_config()
        self._configured = True

    def _bucket_of(self, nbytes: int) -> int:
        if self._strategy == "Round":
            if nbytes <= 0:
                return self.PAGE
            if nbytes < (1 << self._cutoff):
                return 1 << max(nbytes - 1, 1).bit_length()
            # linear region: round up to page
        return -(-max(nbytes, 1) // self.PAGE) * self.PAGE

    def alloc(self, nbytes: int, ctx=None) -> Handle:
        if not self._configured:
            self._configure()
        if self._strategy == "Unpooled":
            buf = np.empty(max(nbytes, 1), dtype=np.uint8)
            return Handle(buf[:nbytes], nbytes, ctx, -1)
        bucket = self._bucket_of(nbytes)
        with self._lock:
            lst = self._free.get(bucket)
            if lst:
                buf = lst.pop()
                self._held -= bucket
                self._hits += 1
            else:
                buf = None
                self._misses += 1
        if buf is None:
            buf = np.empty(bucket, dtype=np.uint8)
        return Handle(buf[:nbytes], nbytes, ctx, bucket)

    def free(self, handle: Handle):
        with self._lock:
            if handle._bucket < 0:
                return
            buf = (handle.dptr.base if handle.dptr.base is not None
                   else handle.dptr)
            # guard fields flip under the lock so concurrent frees of one
            # handle cannot both pass
            bucket, handle._bucket = handle._bucket, -1
            handle.dptr = None  # view must not outlive the pooled buffer
            if self._held + bucket > self._limit:
                return  # over reserve cap — drop to the allocator
            self._free.setdefault(bucket, []).append(buf)
            self._held += bucket

    def direct_free(self, handle: Handle):
        with self._lock:
            handle._bucket = -1  # numpy buffer: the GC reclaims it

    def release_all(self):
        with self._lock:
            self._free.clear()
            self._held = 0

    def info(self):
        with self._lock:
            return {"strategy": self._strategy, "native": False,
                    "held_bytes": self._held,
                    "limit_bytes": self._limit,
                    "hits": self._hits,
                    "misses": self._misses,
                    "buckets": {k: len(v) for k, v in self._free.items()}}


class _NativePool:
    """ctypes binding over src/storage_pool.cc (the native free-list pool,
    parity with the reference's C++ pooled storage managers).  Same
    interface as ``_HostPool``; selected automatically when the shared
    object builds/loads, unless the strategy is Unpooled."""

    def __init__(self, lib):
        self._lib = lib
        self._pool = None
        self._strategy = "Naive"
        self._limit = 0
        self._lock = threading.Lock()

    def _configure(self):
        self._strategy, cutoff, limit = _pool_config()
        self._limit = limit
        self._pool = self._lib.sp_create(
            1 if self._strategy == "Round" else 0, limit, cutoff)

    def alloc(self, nbytes: int, ctx=None) -> Handle:
        import ctypes
        with self._lock:
            if self._pool is None:
                self._configure()
        if self._strategy == "Unpooled":
            buf = np.empty(max(nbytes, 1), dtype=np.uint8)
            return Handle(buf[:nbytes], nbytes, ctx, -1)
        bucket = ctypes.c_int64(0)
        ptr = self._lib.sp_alloc(self._pool, max(nbytes, 1),
                                 ctypes.byref(bucket))
        if not ptr:
            raise MemoryError(f"native pool: alloc({nbytes}) failed")
        cbuf = (ctypes.c_uint8 * bucket.value).from_address(ptr)
        arr = np.frombuffer(cbuf, dtype=np.uint8, count=bucket.value)
        handle = Handle(arr[:nbytes], nbytes, ctx, bucket.value, ptr)
        # A dropped handle must not leak the malloc'd block (the python
        # pool's numpy buffers are GC-owned; native ones are not).  The
        # finalizer rides the base VIEW, not the Handle: any escaped
        # dptr-derived view keeps `arr` alive through its .base chain, so
        # GC reclamation can never free memory a live view still sees.
        # Explicit free()/direct_free() detach it (the caller asserts no
        # views remain — the documented pool contract).
        handle._fin = weakref.finalize(arr, self._lib.sp_free,
                                       self._pool, ptr, bucket.value)
        return handle

    def _sever(self, handle: Handle):
        """Detach handle fields under the lock; returns (ptr, bucket) or
        (None, -1) if another thread already freed it."""
        with self._lock:
            # detach BEFORE dropping dptr: clearing the view may collect
            # the base array immediately (refcounting) and a still-armed
            # finalizer would return the buffer a second time
            fin, handle._fin = handle._fin, None
            if fin is not None:
                fin.detach()
            ptr, handle._ptr = handle._ptr, None
            bucket, handle._bucket = handle._bucket, -1
            handle.dptr = None
            return ptr, bucket

    def free(self, handle: Handle):
        ptr, bucket = self._sever(handle)
        if ptr is not None:
            self._lib.sp_free(self._pool, ptr, bucket)

    def direct_free(self, handle: Handle):
        ptr, _ = self._sever(handle)
        if ptr is not None:
            self._lib.sp_free(self._pool, ptr, -1)

    def release_all(self):
        if self._pool is not None:
            self._lib.sp_release_all(self._pool)

    def info(self):
        import ctypes
        held = ctypes.c_int64(0)
        hits = ctypes.c_int64(0)
        misses = ctypes.c_int64(0)
        if self._pool is not None:
            self._lib.sp_info(self._pool, ctypes.byref(held),
                              ctypes.byref(hits), ctypes.byref(misses))
        return {"strategy": self._strategy, "native": True,
                "held_bytes": held.value, "limit_bytes": self._limit,
                "hits": hits.value, "misses": misses.value,
                "buckets": {}}  # native pool does not expose per-bucket fill


def _load_native_pool():
    """dlopen src/storage_pool.cc's library (building if needed), or None."""
    import ctypes

    from .base import load_native_lib
    lib = load_native_lib("libstoragepool.so", "storage_pool.cc")
    if lib is None:
        return None
    lib.sp_create.restype = ctypes.c_void_p
    lib.sp_create.argtypes = [ctypes.c_int, ctypes.c_int64, ctypes.c_int]
    lib.sp_alloc.restype = ctypes.c_void_p
    lib.sp_alloc.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                             ctypes.POINTER(ctypes.c_int64)]
    lib.sp_free.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]
    lib.sp_release_all.argtypes = [ctypes.c_void_p]
    lib.sp_info.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
                            ctypes.POINTER(ctypes.c_int64),
                            ctypes.POINTER(ctypes.c_int64)]
    lib.sp_destroy.argtypes = [ctypes.c_void_p]
    return _NativePool(lib)


_pool = _load_native_pool() or _HostPool()


class Storage:
    """Singleton facade matching the reference's ``Storage::Get()`` API."""

    _instance = None

    @classmethod
    def get(cls) -> "Storage":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def alloc(self, size: int, ctx=None) -> Handle:
        return _pool.alloc(size, ctx)

    def free(self, handle: Handle):
        _pool.free(handle)

    def direct_free(self, handle: Handle):
        """Bypass the pool (ref: Storage::DirectFree)."""
        _pool.direct_free(handle)

    def release_all(self, ctx=None):
        _pool.release_all()

    def stats(self, device=None):
        return stats(device)

    def pool_info(self):
        return _pool.info()


def pool_info():
    return _pool.info()


def release_all():
    _pool.release_all()
