"""Async execution semantics.

The reference runs every op through a dependency engine with read/write vars
(ref: src/engine/threaded_engine.cc — ThreadedEngine::PushAsync,
include/mxnet/engine.h — Engine).  On TPU, XLA/PJRT already gives us an async
stream per device: op dispatch returns immediately with futures (jax.Array),
and data dependencies order execution.  This module therefore only supplies the
*semantics* the reference exposes to users:

- ``waitall()``  (ref: MXNDArrayWaitAll) — barrier on everything in flight.
- ``wait_to_read(x)`` (ref: NDArray::WaitToRead) — block on one array.
- a bulking knob kept for API compat (``set_bulk_size``) — a no-op, because
  trace+compile (hybridize) subsumes engine bulking.

A bounded ring of recently produced arrays backs ``waitall``; PJRT guarantees
program order per device so blocking on the newest arrays is a full barrier.
The ring holds weak references — tracking must not extend buffer lifetime
(256 pinned activations would hold real HBM).
"""
from __future__ import annotations

import collections
import threading
import weakref

import jax

__all__ = ["waitall", "wait_to_read", "track", "set_bulk_size", "bulk"]

_LOCK = threading.Lock()
_RECENT = collections.deque(maxlen=256)
_bulk_size = 0

# MXNET_ENGINE_TYPE=NaiveEngine → synchronous dispatch (every op blocks),
# the reference's race-bisect debug mode.  Read once at import, like the
# reference's engine singleton.
from . import config as _config  # noqa: E402

_NAIVE = _config.naive_engine()


def track(arr):
    """Record a freshly produced jax.Array for the waitall barrier."""
    if _NAIVE:
        try:
            jax.block_until_ready(arr)
        except Exception:
            pass
        return arr
    try:
        ref = weakref.ref(arr)
    except TypeError:
        return arr  # non-weakref-able (plain numpy on cpu ctx): nothing async
    with _LOCK:
        _RECENT.append(ref)
    return arr


def wait_to_read(arr):
    jax.block_until_ready(arr)


def waitall():
    """Block until all dispatched work has completed (ref: MXNDArrayWaitAll)."""
    with _LOCK:
        pending = list(_RECENT)
        _RECENT.clear()
    for ref in pending:
        a = ref()
        if a is None:
            continue  # collected — its work is done or unobservable
        try:
            jax.block_until_ready(a)
        except Exception:  # deleted/donated buffers are already "done"
            pass


def set_bulk_size(size: int) -> int:
    """API compat (ref: python/mxnet/engine.py — set_bulk_size).

    The reference bulks engine pushes to amortise dispatch; with XLA the
    equivalent is hybridize/jit which compiles the whole graph, so this is a
    recorded no-op returning the previous value.
    """
    global _bulk_size
    prev, _bulk_size = _bulk_size, int(size)
    return prev


class bulk:
    """Context manager compat shim for ``mx.engine.bulk(size)``."""

    def __init__(self, size: int):
        self.size = size

    def __enter__(self):
        self._prev = set_bulk_size(self.size)

    def __exit__(self, *exc):
        set_bulk_size(self._prev)
