"""Async execution semantics.

The reference runs every op through a dependency engine with read/write vars
(ref: src/engine/threaded_engine.cc — ThreadedEngine::PushAsync,
include/mxnet/engine.h — Engine).  On TPU, XLA/PJRT already gives us an async
stream per device: op dispatch returns immediately with futures (jax.Array),
and data dependencies order execution.  This module therefore only supplies the
*semantics* the reference exposes to users:

- ``waitall()``  (ref: MXNDArrayWaitAll) — barrier on everything in flight.
- ``wait_to_read(x)`` (ref: NDArray::WaitToRead) — block on one array.
- a bulking knob kept for API compat (``set_bulk_size``) — a no-op, because
  trace+compile (hybridize) subsumes engine bulking.

A bounded ring of recently produced arrays backs ``waitall``; PJRT guarantees
program order per device, so blocking on the NEWEST arrays barriers
everything dispatched before them.  That ordering is what lets the ring be
small: entries are evicted oldest-first once the ring exceeds a byte budget
(MXNET_ENGINE_TRACK_BYTES_MB) — an evicted (older) op is covered by any
newer entry — so tracking never pins more than the budget of HBM while
``waitall`` remains a true barrier even for outputs the user dropped.
"""
from __future__ import annotations

import collections
import threading

import jax
from jax import core as _jax_core

__all__ = ["waitall", "wait_to_read", "track", "set_bulk_size", "bulk"]

_LOCK = threading.Lock()
# Per-device rings: devkey → deque[(array, nbytes)], newest on the right.
# Per-device because PJRT's dispatch-order guarantee is per device — the
# "newest entry covers evicted older ones" eviction argument is only sound
# within one device's stream.
_RECENT: dict = {}
_RECENT_BYTES: dict = {}
_bulk_size = 0

# MXNET_ENGINE_TYPE=NaiveEngine → synchronous dispatch (every op blocks),
# the reference's race-bisect debug mode.  Read once at import, like the
# reference's engine singleton.
from . import config as _config  # noqa: E402

_NAIVE = _config.naive_engine()
_TRACK_BYTES = int(_config.get("MXNET_ENGINE_TRACK_BYTES_MB") or 64) << 20


def track(arr):
    """Record a freshly produced jax.Array for the waitall barrier."""
    if _NAIVE:
        try:
            jax.block_until_ready(arr)
        except Exception:
            pass
        return arr
    if not isinstance(arr, jax.Array) or isinstance(arr, _jax_core.Tracer):
        return arr  # numpy results / tracers: nothing asynchronous to track
    try:
        devs = arr.devices()
        devkey = next(iter(devs)) if len(devs) == 1 else frozenset(devs)
    except Exception:
        devkey = None
    nbytes = getattr(arr, "nbytes", 0) or 0
    with _LOCK:
        ring = _RECENT.get(devkey)
        if ring is None:
            ring = _RECENT[devkey] = collections.deque()
            _RECENT_BYTES[devkey] = 0
        ring.append((arr, nbytes))
        _RECENT_BYTES[devkey] += nbytes
        # evict oldest beyond the byte budget (and a generous count cap);
        # always keep the newest entry — within one device's stream it
        # alone barriers everything dispatched before it.
        while len(ring) > 1 and (_RECENT_BYTES[devkey] > _TRACK_BYTES
                                 or len(ring) > 256):
            _, old = ring.popleft()
            _RECENT_BYTES[devkey] -= old
    return arr


def wait_to_read(arr):
    jax.block_until_ready(arr)


def waitall():
    """Block until all dispatched work has completed (ref: MXNDArrayWaitAll)."""
    with _LOCK:
        pending = [a for ring in _RECENT.values() for a, _ in ring]
        _RECENT.clear()
        _RECENT_BYTES.clear()
    for a in pending:
        try:
            jax.block_until_ready(a)
        except Exception:  # deleted/donated buffers are already "done"
            pass


def set_bulk_size(size: int) -> int:
    """API compat (ref: python/mxnet/engine.py — set_bulk_size).

    The reference bulks engine pushes to amortise dispatch; with XLA the
    equivalent is hybridize/jit which compiles the whole graph, so this is a
    recorded no-op returning the previous value.
    """
    global _bulk_size
    prev, _bulk_size = _bulk_size, int(size)
    return prev


class bulk:
    """Context manager compat shim for ``mx.engine.bulk(size)``."""

    def __init__(self, size: int):
        self.size = size

    def __enter__(self):
        self._prev = set_bulk_size(self.size)

    def __exit__(self, *exc):
        set_bulk_size(self._prev)
