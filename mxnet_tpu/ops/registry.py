"""Op registry.

Replaces the reference's NNVM op registry (ref: 3rdparty/tvm/nnvm/include/nnvm
— NNVM_REGISTER_OP; src/operator pattern ``.set_attr<FCompute>``).  An op here
is a pure function ``fn(*jax_arrays, **static_params) -> array | tuple`` whose
shape/dtype inference, gradient, and fusion all come from XLA tracing, so the
FInferShape/FInferType/FGradient attribute machinery of the reference is not
needed.
"""
from __future__ import annotations

import functools
import inspect
from typing import Callable, Dict

import jax

OPS: Dict[str, Callable] = {}
# Per-op dispatch metadata: has_training (op behavior depends on train/predict
# mode — must be part of the jit cache key) and needs_rng (op draws random
# numbers — a fresh key must be a traced argument, never constant-folded).
OP_META: Dict[str, dict] = {}

# Bumped on every (re-)registration so signature caches (symbol builders)
# never serve a stale inspection after an op is replaced.
REGISTRATION_EPOCH = 0

# The contrib ops that ALSO get short names in the nd/sym `contrib`
# namespaces (one list, two frontends — see ndarray/__init__.py and
# symbol.py namespace generation).
CONTRIB_SHORT_NAMES = (
    "interleaved_matmul_selfatt_qk", "interleaved_matmul_selfatt_valatt",
    "box_nms", "box_iou", "MultiBoxPrior", "MultiBoxTarget",
    "MultiBoxDetection", "div_sqrt_dim", "multi_head_attention",
    "quantize_v2", "dequantize",
)


def register_op(name, fn: Callable = None, aliases=(), needs_rng: bool = False,
                mesh_aware: bool = False):
    """Register ``fn`` under ``name`` (+aliases). Usable as a decorator.

    ``mesh_aware`` ops contain shard_map over the ambient parallel mesh;
    eager dispatch calls them directly (no single-device jit wrapper, which
    would pin inputs to one device and fight the mesh)."""

    def _do(f):
        global REGISTRATION_EPOCH
        REGISTRATION_EPOCH += 1
        try:
            has_training = "training" in inspect.signature(f).parameters
        except (TypeError, ValueError):
            has_training = False
        meta = {"has_training": has_training, "needs_rng": needs_rng,
                "mesh_aware": mesh_aware,
                # Only optimizer update kernels take per-step scalar
                # hyperparams (lr schedules etc.) as traced args; everywhere
                # else scalars stay static so XLA constant-folds them.
                "dynamic": name.endswith("_update")}
        OPS[name] = f
        OP_META[name] = meta
        for a in aliases:
            OPS[a] = f
            OP_META[a] = meta
        return f

    if fn is None:
        return _do
    return _do(fn)


def alias_op(new_name: str, existing: str):
    global REGISTRATION_EPOCH
    REGISTRATION_EPOCH += 1
    OPS[new_name] = OPS[existing]
    OP_META[new_name] = OP_META[existing]


def get_op(name: str) -> Callable:
    try:
        return OPS[name]
    except KeyError:
        raise ValueError(f"unknown operator '{name}'") from None


# Scalar hyperparameters that change between calls (lr schedules, adam bias
# correction, ...).  They are passed as TRACED weak-typed scalars so the jit
# cache keys only on their NAMES — otherwise every new lr value would trigger
# a recompile (the reference passes these through dmlc::Parameter per call;
# kernels read them as runtime scalars, same idea).
DYNAMIC_SCALARS = frozenset({
    "lr", "wd", "momentum", "beta1", "beta2", "epsilon", "rho", "eta",
    "lamda1", "beta", "wd_lh", "rescale_grad", "t",
})


@functools.lru_cache(maxsize=8192)
def compiled(name: str, params_key: tuple, dyn_names: tuple = ()):
    """Cached jitted closure of an op at fixed static params.

    This is the eager fast path: dispatch cost is a dict lookup + jit cache
    hit, the TPU-native analogue of the reference's cached FCompute dispatch
    (ref: src/imperative/imperative_utils.h — PushFCompute).

    Static Python state must never be constant-folded into the cache:
    the training flag is part of ``params_key`` (invoke injects it), for
    ``needs_rng`` ops the PRNG key is a traced argument feeding a
    RandomScope, and DYNAMIC_SCALARS arrive as the traced ``dyn`` tuple.
    """
    fn = get_op(name)
    kwargs = dict(params_key)

    if OP_META.get(name, {}).get("needs_rng"):
        from .. import random as _random

        @jax.jit
        def _run_rng(key, dyn, *arrays):
            with _random.RandomScope(key):
                return fn(*arrays, **kwargs, **dict(zip(dyn_names, dyn)))

        return _run_rng

    @jax.jit
    def _run(dyn, *arrays):
        return fn(*arrays, **kwargs, **dict(zip(dyn_names, dyn)))

    return _run


def split_dynamic(kwargs: dict, enabled: bool = True):
    """Split op kwargs into (static, dyn_names, dyn_values), sorted by name
    so differing call-site kwarg order maps to one compile-cache entry."""
    if not enabled:
        return kwargs, (), ()
    static, dyn = {}, []
    for k, v in kwargs.items():
        if k in DYNAMIC_SCALARS and isinstance(v, (int, float)) \
                and not isinstance(v, bool):
            dyn.append((k, v))
        else:
            static[k] = v
    dyn.sort()
    return (static, tuple(k for k, _ in dyn), tuple(v for _, v in dyn))


def params_key(kwargs: dict) -> tuple:
    """Normalise static kwargs to a hashable cache key (lists -> tuples)."""
    items = []
    for k in sorted(kwargs):
        v = kwargs[k]
        if isinstance(v, list):
            v = tuple(v)
        items.append((k, v))
    return tuple(items)
