"""Op registry.

Replaces the reference's NNVM op registry (ref: 3rdparty/tvm/nnvm/include/nnvm
— NNVM_REGISTER_OP; src/operator pattern ``.set_attr<FCompute>``).  An op here
is a pure function ``fn(*jax_arrays, **static_params) -> array | tuple`` whose
shape/dtype inference, gradient, and fusion all come from XLA tracing, so the
FInferShape/FInferType/FGradient attribute machinery of the reference is not
needed.
"""
from __future__ import annotations

import functools
import inspect
from typing import Callable, Dict

import jax

OPS: Dict[str, Callable] = {}
# Per-op dispatch metadata: has_training (op behavior depends on train/predict
# mode — must be part of the jit cache key) and needs_rng (op draws random
# numbers — a fresh key must be a traced argument, never constant-folded).
OP_META: Dict[str, dict] = {}


def register_op(name, fn: Callable = None, aliases=(), needs_rng: bool = False):
    """Register ``fn`` under ``name`` (+aliases). Usable as a decorator."""

    def _do(f):
        try:
            has_training = "training" in inspect.signature(f).parameters
        except (TypeError, ValueError):
            has_training = False
        meta = {"has_training": has_training, "needs_rng": needs_rng}
        OPS[name] = f
        OP_META[name] = meta
        for a in aliases:
            OPS[a] = f
            OP_META[a] = meta
        return f

    if fn is None:
        return _do
    return _do(fn)


def alias_op(new_name: str, existing: str):
    OPS[new_name] = OPS[existing]
    OP_META[new_name] = OP_META[existing]


def get_op(name: str) -> Callable:
    try:
        return OPS[name]
    except KeyError:
        raise ValueError(f"unknown operator '{name}'") from None


@functools.lru_cache(maxsize=8192)
def compiled(name: str, params_key: tuple):
    """Cached jitted closure of an op at fixed static params.

    This is the eager fast path: dispatch cost is a dict lookup + jit cache
    hit, the TPU-native analogue of the reference's cached FCompute dispatch
    (ref: src/imperative/imperative_utils.h — PushFCompute).

    Static Python state must never be constant-folded into the cache:
    the training flag is part of ``params_key`` (invoke injects it), and for
    ``needs_rng`` ops the PRNG key is a traced leading argument feeding a
    RandomScope, so every call draws fresh randomness.
    """
    fn = get_op(name)
    kwargs = dict(params_key)

    if OP_META.get(name, {}).get("needs_rng"):
        from .. import random as _random

        @jax.jit
        def _run_rng(key, *arrays):
            with _random.RandomScope(key):
                return fn(*arrays, **kwargs)

        return _run_rng

    @jax.jit
    def _run(*arrays):
        return fn(*arrays, **kwargs)

    return _run


def params_key(kwargs: dict) -> tuple:
    """Normalise static kwargs to a hashable cache key (lists -> tuples)."""
    items = []
    for k in sorted(kwargs):
        v = kwargs[k]
        if isinstance(v, list):
            v = tuple(v)
        items.append((k, v))
    return tuple(items)
