"""Paged decode attention — single-query attention over a paged KV cache.

The serving decode loop (serving/generate.py) keeps every in-flight
sequence's K/V in fixed-size *pages* drawn from one shared pool
``[n_pages, page_size, heads, head_dim]`` per layer, addressed through a
per-slot page table.  This op computes, for each decode slot, attention
of its single query token over its own (ragged-length) cached context —
the PAPERS.md *Ragged Paged Attention* formulation (arXiv:2604.15464):
sequences of any mix of lengths share ONE compiled program, because the
pool/table/length shapes are configuration constants, never functions
of traffic.

Two execution paths, selected like ``ops/pallas/flash_attention.py``:

- **pure-jnp** (default off-TPU): gather pages by table, mask past each
  slot's length, softmax — runs under ``JAX_PLATFORMS=cpu`` so the whole
  serving stack (and tier-1) needs no accelerator.  The gather
  materialises a ``[slots, max_ctx, H, D]`` temp, which is fine on CPU:
  the *resident* state is still the paged pool.
- **Pallas ragged kernel** (``ops/pallas/paged_attention.py``) on TPU:
  pages stream HBM→VMEM through a scalar-prefetched page-table index
  map, with the online-softmax recurrence across a slot's pages and a
  skip for pages past the slot's length — no dense temp, no per-length
  recompile.

``dense_decode_attention`` is the max-length dense-cache reference the
paged path is budgeted against (the costguard ``llm_decode_step`` vs
``llm_decode_step_dense`` golden pair) and parity-tested with.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op

_NEG = -1e30


def _masked_softmax(scores, valid):
    """Softmax over the key axis with invalid keys masked.  A slot with
    ZERO valid keys (an inactive decode slot) degrades to uniform
    weights, not NaN: every score is the same ``_NEG`` constant, and
    softmax subtracts the max before exponentiating — callers ignore
    inactive rows, they must not poison the batch with NaN."""
    scores = jnp.where(valid, scores, jnp.asarray(_NEG, scores.dtype))
    return jax.nn.softmax(scores, axis=-1)


@register_op("paged_decode_attention")
def paged_decode_attention(q, k_pages, v_pages, page_tables, lengths,
                           impl=None):
    """Single-query attention over a paged KV cache.

    Args:
      q:           ``[slots, heads, head_dim]`` — one query token per
                   decode slot.
      k_pages:     ``[n_pages, page_size, heads, head_dim]`` shared pool.
      v_pages:     same shape as ``k_pages``.
      page_tables: ``[slots, pages_per_seq]`` int32 page ids per slot
                   (page 0 is the serving allocator's write sink; unused
                   table entries may be 0 — they are masked by length).
      lengths:     ``[slots]`` int32 — valid KV tokens per slot,
                   INCLUDING the just-written current token.  0 marks an
                   inactive slot (output row is garbage, never NaN).
      impl:        None (auto: Pallas on TPU, jnp elsewhere), "jnp", or
                   "pallas".

    Returns ``[slots, heads, head_dim]`` attention output.
    """
    if impl is None:
        impl = "pallas" if jax.default_backend() == "tpu" else "jnp"
    if impl == "pallas":
        from .pallas.paged_attention import paged_decode_attention_pallas
        return paged_decode_attention_pallas(q, k_pages, v_pages,
                                             page_tables, lengths)
    if impl != "jnp":
        raise ValueError(f"paged_decode_attention: impl={impl!r} "
                         f"(expected None, 'jnp', or 'pallas')")
    n_pages, page_size, heads, head_dim = k_pages.shape
    slots, pages_per_seq = page_tables.shape
    ctx = pages_per_seq * page_size
    # gather each slot's pages: [slots, pages_per_seq, page, H, D] and
    # flatten the (page-table, in-page) axes into one context axis
    k_ctx = k_pages[page_tables].reshape(slots, ctx, heads, head_dim)
    v_ctx = v_pages[page_tables].reshape(slots, ctx, heads, head_dim)
    scale = 1.0 / jnp.sqrt(jnp.asarray(head_dim, q.dtype))
    scores = jnp.einsum("shd,schd->shc", q * scale, k_ctx)
    pos = jnp.arange(ctx, dtype=lengths.dtype)
    valid = (pos[None, None, :] < lengths[:, None, None])
    w = _masked_softmax(scores, valid)
    return jnp.einsum("shc,schd->shd", w, v_ctx)


@register_op("dense_decode_attention")
def dense_decode_attention(q, k_cache, v_cache, lengths):
    """The dense max-length-cache reference: every slot owns a
    ``[max_ctx, H, D]`` stripe of a ``[slots, max_ctx, H, D]`` cache
    whether it uses it or not — the per-sequence HBM reservation the
    paged pool exists to reclaim.  Same masking/length semantics as
    ``paged_decode_attention``; the two are parity-tested token-exact
    (up to float assoc) in tests/test_generate.py."""
    slots, ctx, heads, head_dim = k_cache.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(head_dim, q.dtype))
    scores = jnp.einsum("shd,schd->shc", q * scale, k_cache)
    pos = jnp.arange(ctx, dtype=lengths.dtype)
    valid = (pos[None, None, :] < lengths[:, None, None])
    w = _masked_softmax(scores, valid)
    return jnp.einsum("shc,schd->shd", w, v_cache)
