"""Optimizer update ops.

Re-emission of (ref: src/operator/optimizer_op{.cc,.cu,-inl.h},
contrib/adamw*, contrib/multi_lamb*).  Functional form: each op returns the
updated weight (and updated state tensors); the Trainer writes them back —
the reference mutates in place through the engine.  XLA fuses each update into
a single elementwise kernel; the ``multi_*`` fused multi-tensor variants are
realised by jit-ing the whole Trainer step instead.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register_op


def _apply_wd(grad, weight, wd, rescale_grad, clip_gradient):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g + wd * weight


@register_op("sgd_update")
def _sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    return weight - lr * g


@register_op("sgd_mom_update")
def _sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * g
    return weight + new_mom, new_mom


@register_op("nag_mom_update")
def _nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    new_mom = momentum * mom + g
    return weight - lr * (g + momentum * new_mom), new_mom


@register_op("adam_update")
def _adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                 lazy_update=True):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    return weight - lr * new_mean / (jnp.sqrt(new_var) + epsilon), new_mean, new_var


@register_op("adamw_update")
def _adamw_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                  epsilon=1e-8, wd=0.0, eta=1.0, rescale_grad=1.0, clip_gradient=-1.0):
    """ref: src/operator/contrib/adamw.cc — decoupled weight decay."""
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    upd = new_mean / (jnp.sqrt(new_var) + epsilon) + wd * weight
    return weight - eta * lr * upd, new_mean, new_var


@register_op("rmsprop_update")
def _rmsprop_update(weight, grad, n, lr=0.001, rho=0.9, epsilon=1e-8, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    new_n = rho * n + (1 - rho) * jnp.square(g)
    out = weight - lr * g / (jnp.sqrt(new_n) + epsilon)
    if clip_weights is not None and clip_weights > 0:
        out = jnp.clip(out, -clip_weights, clip_weights)
    return out, new_n


@register_op("rmspropalex_update")
def _rmspropalex_update(weight, grad, n, g_state, delta, lr=0.001, rho=0.9,
                        momentum=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                        clip_gradient=-1.0, clip_weights=-1.0):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    new_n = rho * n + (1 - rho) * jnp.square(g)
    new_g = rho * g_state + (1 - rho) * g
    new_delta = momentum * delta - lr * g / jnp.sqrt(new_n - jnp.square(new_g) + epsilon)
    return weight + new_delta, new_n, new_g, new_delta


@register_op("ftrl_update")
def _ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                 rescale_grad=1.0, clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    new_w = jnp.where(
        jnp.abs(new_z) <= lamda1,
        jnp.zeros_like(weight),
        -(new_z - jnp.sign(new_z) * lamda1) / ((beta + jnp.sqrt(new_n)) / lr + wd),
    )
    return new_w, new_z, new_n


@register_op("signsgd_update")
def _signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    return weight - lr * jnp.sign(g)


@register_op("signum_update")
def _signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    new_mom = momentum * mom - (1 - momentum) * g
    out = (1 - lr * wd_lh) * weight + lr * jnp.sign(new_mom)
    return out, new_mom


@register_op("adagrad_update")
def _adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-7, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    new_h = history + jnp.square(g)
    return weight - lr * g / (jnp.sqrt(new_h) + epsilon), new_h


@register_op("adadelta_update")
def _adadelta_update(weight, grad, acc_g, acc_delta, lr=1.0, rho=0.9, epsilon=1e-5,
                     wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    new_acc_g = rho * acc_g + (1 - rho) * jnp.square(g)
    delta = jnp.sqrt(acc_delta + epsilon) / jnp.sqrt(new_acc_g + epsilon) * g
    new_acc_delta = rho * acc_delta + (1 - rho) * jnp.square(delta)
    return weight - lr * delta, new_acc_g, new_acc_delta


@register_op("lamb_update_phase1")
def _lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                        epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                        rescale_grad=1.0, clip_gradient=-1.0):
    """ref: src/operator/optimizer_op.cc — lamb_update_phase1."""
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    m_hat, v_hat = new_mean, new_var
    if bias_correction:
        m_hat = new_mean / (1 - beta1 ** t)
        v_hat = new_var / (1 - beta2 ** t)
    update = m_hat / (jnp.sqrt(v_hat) + epsilon) + wd * weight
    return update, new_mean, new_var


@register_op("lamb_update_phase2")
def _lamb_update_phase2(weight, g_update, r1, r2, lr=0.01, lower_bound=-1.0,
                        upper_bound=-1.0):
    """ref: src/operator/optimizer_op.cc — lamb_update_phase2 (trust ratio)."""
    r1c = r1
    if lower_bound is not None and lower_bound > 0:
        r1c = jnp.maximum(r1c, lower_bound)
    if upper_bound is not None and upper_bound > 0:
        r1c = jnp.minimum(r1c, upper_bound)
    ratio = jnp.where(jnp.logical_and(r1c > 0, r2 > 0), r1c / r2, jnp.ones_like(r1c))
    return weight - lr * ratio * g_update


@register_op("mp_sgd_update")
def _mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, lazy_update=True):
    """Mixed-precision: bf16 weight + fp32 master copy
    (ref: src/operator/optimizer_op.cc — mp_sgd_update)."""
    g = _apply_wd(grad.astype(jnp.float32), weight32, wd, rescale_grad, clip_gradient)
    new_w32 = weight32 - lr * g
    return new_w32.astype(weight.dtype), new_w32


@register_op("mp_sgd_mom_update")
def _mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _apply_wd(grad.astype(jnp.float32), weight32, wd, rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * g
    new_w32 = weight32 + new_mom
    return new_w32.astype(weight.dtype), new_mom, new_w32


def _per_weight(vals, n, name, op="multi_sgd_update"):
    """lrs/wds are REQUIRED by the reference op; a scalar broadcasts,
    a sequence must match num_weights (ADVICE r4: None used to surface
    as an opaque ``list(None)`` TypeError)."""
    if vals is None:
        raise ValueError(f"{op} requires {name} (scalar or one per weight)")
    if isinstance(vals, (int, float)):
        return [vals] * n
    vals = list(vals)
    if len(vals) != n:
        raise ValueError(f"{name} has {len(vals)} entries for {n} weights")
    return vals


@register_op("multi_sgd_update")
def _multi_sgd_update(*arrays, lrs=None, wds=None, rescale_grad=1.0,
                      clip_gradient=None, num_weights=None):
    """ref: src/operator/contrib/multi_sgd — fused multi-tensor SGD over
    interleaved (weight_0, grad_0, weight_1, grad_1, ...).  On TPU the
    whole-model fusion lives in parallel.TrainStep; this op exists for
    API parity and small eager sweeps — XLA still compiles the chain into
    few kernels.  Returns the updated weights, positionally."""
    n = num_weights if num_weights is not None else len(arrays) // 2
    lrs, wds = _per_weight(lrs, n, "lrs"), _per_weight(wds, n, "wds")
    outs = []
    for i in range(n):
        w, g = arrays[2 * i], arrays[2 * i + 1]
        g = _apply_wd(g.astype(w.dtype), w, wds[i], rescale_grad,
                      clip_gradient)
        outs.append(w - lrs[i] * g)
    return tuple(outs) if n > 1 else outs[0]


@register_op("multi_mp_sgd_update")
def _multi_mp_sgd_update(*arrays, lrs=None, wds=None, rescale_grad=1.0,
                         clip_gradient=None, num_weights=None):
    """ref: multi_mp_sgd_update — fp32 master-weight variant over
    interleaved (weight, grad, master) triples.  Returns (weight',
    master') pairs flattened positionally."""
    n = num_weights if num_weights is not None else len(arrays) // 3
    lrs = _per_weight(lrs, n, "lrs", "multi_mp_sgd_update")
    wds = _per_weight(wds, n, "wds", "multi_mp_sgd_update")
    outs = []
    for i in range(n):
        w, g, m = arrays[3 * i], arrays[3 * i + 1], arrays[3 * i + 2]
        g32 = _apply_wd(g.astype(jnp.float32), m, wds[i], rescale_grad,
                        clip_gradient)
        m_new = m - lrs[i] * g32
        outs.append(m_new.astype(w.dtype))
        outs.append(m_new)
    return tuple(outs)
