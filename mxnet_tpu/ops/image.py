"""Image ops (ref: src/operator/image/image_random*.{h,cc}, resize-inl.h,
crop-inl.h).  Layout HWC / NHWC like the reference's mx.image namespace;
augmentations draw from the functional key stream so they trace cleanly."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op
from .. import random as _random


@register_op("image_resize", aliases=("resize",))
def _resize(data, size=None, keep_ratio=False, interp=1):
    """size int or (w, h); bilinear (interp=1) or nearest (interp=0)."""
    hwc = data.ndim == 3
    x = data[None] if hwc else data
    n, h, w, c = x.shape
    if isinstance(size, int):
        if keep_ratio:
            if h < w:
                new_h, new_w = size, int(w * size / h)
            else:
                new_h, new_w = int(h * size / w), size
        else:
            new_h = new_w = size
    else:
        new_w, new_h = size
    method = "nearest" if interp == 0 else "bilinear"
    out = jax.image.resize(x, (n, new_h, new_w, c), method=method)
    if data.dtype == jnp.uint8:
        out = jnp.clip(jnp.round(out), 0, 255).astype(jnp.uint8)
    return out[0] if hwc else out


@register_op("image_normalize", aliases=("normalize",))
def _normalize(data, mean=0.0, std=1.0):
    """CHW / NCHW float normalise (ref: image_random-inl.h — NormalizeImpl)."""
    mean = jnp.asarray(mean, data.dtype)
    std = jnp.asarray(std, data.dtype)
    if mean.ndim == 1:
        mean = mean.reshape(-1, 1, 1)
        std = std.reshape(-1, 1, 1)
    return (data - mean) / std


@register_op("image_to_tensor", aliases=("to_tensor",))
def _to_tensor(data):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (ref: ToTensorImpl)."""
    x = data.astype(jnp.float32) / 255.0
    if x.ndim == 3:
        return jnp.transpose(x, (2, 0, 1))
    return jnp.transpose(x, (0, 3, 1, 2))


@register_op("image_crop", aliases=("crop",))
def _crop(data, x=0, y=0, width=1, height=1):
    if data.ndim == 3:
        return data[y:y + height, x:x + width, :]
    return data[:, y:y + height, x:x + width, :]


@register_op("image_flip_left_right", aliases=("flip_left_right",))
def _flip_lr(data):
    return jnp.flip(data, axis=-2)


@register_op("image_flip_top_bottom", aliases=("flip_top_bottom",))
def _flip_tb(data):
    return jnp.flip(data, axis=-3)


@register_op("image_random_flip_left_right", aliases=("random_flip_left_right",), needs_rng=True)
def _random_flip_lr(data):
    key = _random.next_key()
    return jnp.where(jax.random.bernoulli(key), jnp.flip(data, axis=-2), data)


@register_op("image_random_brightness", aliases=("random_brightness",), needs_rng=True)
def _random_brightness(data, min_factor=0.5, max_factor=1.5):
    key = _random.next_key()
    f = jax.random.uniform(key, (), minval=min_factor, maxval=max_factor)
    return data * f.astype(data.dtype)


@register_op("image_random_contrast", aliases=("random_contrast",), needs_rng=True)
def _random_contrast(data, min_factor=0.5, max_factor=1.5):
    key = _random.next_key()
    f = jax.random.uniform(key, (), minval=min_factor, maxval=max_factor).astype(jnp.float32)
    x = data.astype(jnp.float32)
    mean = jnp.mean(x, axis=(-3, -2), keepdims=True)
    out = (x - mean) * f + mean
    return out.astype(data.dtype) if data.dtype == jnp.uint8 else out
