"""Loss ops (ref: src/operator/nn/ctc_loss*, loss_binary_op*,
softmax_cross_entropy).  Gluon losses build on these."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


@register_op("softmax_cross_entropy")
def _softmax_cross_entropy(data, label):
    """ref: src/operator/loss_binary_op-inl.h — per-batch summed CE."""
    logp = jax.nn.log_softmax(data, axis=-1)
    picked = jnp.take_along_axis(logp, label.astype(jnp.int32)[..., None], axis=-1)
    return -jnp.sum(picked)


@register_op("LinearRegressionOutput", aliases=("linear_regression_output",))
def _linear_regression_output(data, label=None, grad_scale=1.0):
    """ref: src/operator/regression_output-inl.h — forward is identity; the
    L2 gradient (data - label) * grad_scale is the op's IMPLICIT loss,
    applied by the symbolic executor's backward (executor.py _HEAD_LOSSES;
    under autograd, use gluon.loss.L2Loss instead)."""
    return data


@register_op("MAERegressionOutput", aliases=("mae_regression_output",))
def _mae_regression_output(data, label=None, grad_scale=1.0):
    """ref: regression_output-inl.h — identity forward, L1 implicit loss."""
    return data


@register_op("LogisticRegressionOutput",
             aliases=("logistic_regression_output",))
def _logistic_regression_output(data, label=None, grad_scale=1.0):
    """ref: regression_output-inl.h — sigmoid forward; the executor's
    implicit BCE loss yields the reference's (sigmoid - label) gradient."""
    return jax.nn.sigmoid(data)


@register_op("CTCLoss", aliases=("ctc_loss",))
def _ctc_loss(data, label, data_lengths=None, label_lengths=None,
              use_data_lengths=False, use_label_lengths=False, blank_label="first"):
    """CTC forward (log-space alpha recursion) — replaces the reference's
    warp-ctc kernel (ref: src/operator/nn/ctc_loss-inl.h) with a lax.scan that
    XLA pipelines; fixed shapes, masked tails.

    data: (T, N, C) unnormalised; label: (N, L) int; returns (N,) loss.
    """
    t_max, n, c = data.shape
    l_max = label.shape[1]
    logp = jax.nn.log_softmax(data.astype(jnp.float32), axis=-1)
    blank = 0 if blank_label == "first" else c - 1
    labels = label.astype(jnp.int32)
    if blank_label != "first":
        pass  # labels already use 0..c-2, blank at end
    if data_lengths is None or not use_data_lengths:
        data_lengths = jnp.full((n,), t_max, jnp.int32)
    else:
        data_lengths = data_lengths.astype(jnp.int32)
    if label_lengths is None or not use_label_lengths:
        label_lengths = jnp.sum((labels != (0 if blank_label == "first" else -1)).astype(jnp.int32)
                                 if blank_label == "first" else jnp.ones_like(labels), axis=1)
        if blank_label == "first":
            label_lengths = jnp.sum((labels > 0).astype(jnp.int32), axis=1)
    else:
        label_lengths = label_lengths.astype(jnp.int32)

    # extended label sequence with blanks: length S = 2L+1
    s_max = 2 * l_max + 1
    ext = jnp.full((n, s_max), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(labels)
    neg_inf = jnp.float32(-1e30)

    def step(alpha, logp_t):
        # alpha: (N, S)
        em = jnp.take_along_axis(logp_t, ext, axis=-1)  # (N, S)
        a_shift1 = jnp.concatenate([jnp.full((n, 1), neg_inf), alpha[:, :-1]], axis=1)
        a_shift2 = jnp.concatenate([jnp.full((n, 2), neg_inf), alpha[:, :-2]], axis=1)
        ext_shift2 = jnp.concatenate([jnp.full((n, 2), -1, jnp.int32), ext[:, :-2]], axis=1)
        allow_skip = (ext != blank) & (ext != ext_shift2)
        cand = jnp.logaddexp(alpha, a_shift1)
        cand = jnp.where(allow_skip, jnp.logaddexp(cand, a_shift2), cand)
        return cand + em, cand + em

    alpha0 = jnp.full((n, s_max), neg_inf)
    alpha0 = alpha0.at[:, 0].set(logp[0, :, blank])
    alpha0 = alpha0.at[:, 1].set(jnp.take_along_axis(logp[0], ext[:, 1:2], axis=-1)[:, 0])
    alphas_last, alphas = jax.lax.scan(step, alpha0, logp[1:])
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # (T, N, S)
    # pick alpha at t = len-1, s in {2L, 2L-1}
    t_idx = jnp.clip(data_lengths - 1, 0, t_max - 1)
    a_t = jnp.take_along_axis(alphas, t_idx.reshape(1, n, 1), axis=0)[0]  # (N, S)
    s1 = jnp.clip(2 * label_lengths, 0, s_max - 1)
    s2 = jnp.clip(2 * label_lengths - 1, 0, s_max - 1)
    ll = jnp.logaddexp(
        jnp.take_along_axis(a_t, s1[:, None], axis=1)[:, 0],
        jnp.take_along_axis(a_t, s2[:, None], axis=1)[:, 0],
    )
    return -ll


@jax.custom_vjp
def _make_loss_core(data, scale):
    return data


def _make_loss_fwd(data, scale):
    return data, scale


def _make_loss_bwd(scale, g):
    # the reference's MakeLoss backward IGNORES the head gradient and
    # writes grad_scale itself (make_loss.cc) — do exactly that
    return (jnp.broadcast_to(scale, g.shape).astype(g.dtype),
            jnp.zeros_like(scale))


_make_loss_core.defvjp(_make_loss_fwd, _make_loss_bwd)


@register_op("make_loss", aliases=("MakeLoss",))
def _make_loss(data, grad_scale=1.0, valid_thresh=0.0,
               normalization="null"):
    """ref: src/operator/make_loss.cc — mark an output as a loss head:
    forward is identity; backward REPLACES the incoming gradient with
    ``grad_scale`` (normalized per batch/valid count when requested),
    exactly like the reference."""
    scale = jnp.asarray(grad_scale, jnp.float32)
    if normalization == "batch":
        scale = scale / data.shape[0]
    elif normalization == "valid":
        valid = jnp.maximum(jnp.sum((data > valid_thresh)
                                    .astype(jnp.float32)), 1.0)
        scale = scale / valid
    return _make_loss_core(data, scale)
