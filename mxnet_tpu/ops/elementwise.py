"""Elementwise and broadcast ops.

Re-emission of the reference's elementwise families (ref:
src/operator/tensor/elemwise_binary_broadcast_op*.{h,cc,cu},
elemwise_unary_op*, mshadow_op.h) as jnp expressions.  Broadcasting is native
in XLA so the ``broadcast_*`` names are aliases of the plain binary ops —
the reference needed separate kernels; we do not.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op, alias_op

# ---------------------------------------------------------------- binary ----
_BINARY = {
    "add": jnp.add,
    "subtract": jnp.subtract,
    "multiply": jnp.multiply,
    "divide": jnp.divide,
    "mod": jnp.mod,
    "power": jnp.power,
    "maximum": jnp.maximum,
    "minimum": jnp.minimum,
    "hypot": jnp.hypot,
    "arctan2": jnp.arctan2,
    "equal": lambda a, b: jnp.equal(a, b).astype(_res_dtype(a)),
    "not_equal": lambda a, b: jnp.not_equal(a, b).astype(_res_dtype(a)),
    "greater": lambda a, b: jnp.greater(a, b).astype(_res_dtype(a)),
    "greater_equal": lambda a, b: jnp.greater_equal(a, b).astype(_res_dtype(a)),
    "lesser": lambda a, b: jnp.less(a, b).astype(_res_dtype(a)),
    "lesser_equal": lambda a, b: jnp.less_equal(a, b).astype(_res_dtype(a)),
    "logical_and": lambda a, b: jnp.logical_and(a, b).astype(_res_dtype(a)),
    "logical_or": lambda a, b: jnp.logical_or(a, b).astype(_res_dtype(a)),
    "logical_xor": lambda a, b: jnp.logical_xor(a, b).astype(_res_dtype(a)),
}


def _res_dtype(a):
    # Reference comparison ops return the input float dtype, not bool
    # (ref: src/operator/tensor/elemwise_binary_broadcast_op_logic.cc).
    d = jnp.result_type(a)
    return d if jnp.issubdtype(d, jnp.floating) else jnp.float32


for _name, _fn in _BINARY.items():
    register_op(_name, _fn)

# broadcast_* compat aliases (ref: broadcast_add etc.)
for _name in ("add", "subtract", "multiply", "divide", "mod", "power",
              "maximum", "minimum", "hypot", "equal", "not_equal", "greater",
              "greater_equal", "lesser", "lesser_equal", "logical_and",
              "logical_or", "logical_xor"):
    alias_op(f"broadcast_{_name}", _name)
alias_op("broadcast_sub", "subtract")
alias_op("broadcast_mul", "multiply")
alias_op("broadcast_div", "divide")
alias_op("broadcast_plus", "add")
alias_op("broadcast_minus", "subtract")
alias_op("elemwise_add", "add")
alias_op("elemwise_sub", "subtract")
alias_op("elemwise_mul", "multiply")
alias_op("elemwise_div", "divide")

# ----------------------------------------------------------------- unary ----
_UNARY = {
    "abs": jnp.abs,
    "sign": jnp.sign,
    "square": jnp.square,
    "sqrt": jnp.sqrt,
    "rsqrt": jax.lax.rsqrt,
    "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "exp": jnp.exp,
    "expm1": jnp.expm1,
    "log": jnp.log,
    "log1p": jnp.log1p,
    "log2": jnp.log2,
    "log10": jnp.log10,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "floor": jnp.floor,
    "ceil": jnp.ceil,
    "round": jnp.round,
    "rint": jnp.rint,
    "trunc": jnp.trunc,
    "fix": jnp.trunc,
    "negative": jnp.negative,
    "reciprocal": jnp.reciprocal,
    "sigmoid": jax.nn.sigmoid,
    "softsign": jax.nn.soft_sign,
    "relu": jax.nn.relu,
    "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": jax.scipy.special.gammaln,
    "logical_not": lambda x: jnp.logical_not(x).astype(_res_dtype(x)),
    "isnan": lambda x: jnp.isnan(x).astype(_res_dtype(x)),
    "isinf": lambda x: jnp.isinf(x).astype(_res_dtype(x)),
    "isfinite": lambda x: jnp.isfinite(x).astype(_res_dtype(x)),
    "degrees": jnp.degrees,
    "radians": jnp.radians,
}

for _name, _fn in _UNARY.items():
    register_op(_name)(lambda x, _f=_fn: _f(x))

@register_op("copy", aliases=("identity", "_copy"))
def _copy(x):
    return jnp.asarray(x)


@register_op("stop_gradient", aliases=("BlockGrad", "block_grad"))
def _stop_gradient(x):
    return jax.lax.stop_gradient(x)


@register_op("clip")
def _clip(x, a_min=None, a_max=None):
    return jnp.clip(x, a_min, a_max)


@register_op("where")
def _where(cond, a, b):
    return jnp.where(cond.astype(bool) if cond.dtype != jnp.bool_ else cond, a, b)


@register_op("cast", aliases=("Cast", "astype"))
def _cast(x, dtype="float32"):
    from ..base import dtype_np

    return x.astype(dtype_np(dtype))


@register_op("amp_cast")
def _amp_cast(x, dtype="float16"):
    from ..base import dtype_np

    # bf16 is the TPU half type; fp16 requests map to bf16 by design
    # (ref: src/operator/tensor/amp_cast.h — amp_cast).
    if str(dtype) == "float16":
        dtype = "bfloat16"
    return x.astype(dtype_np(dtype))


@register_op("smooth_l1")
def _smooth_l1(x, scalar=1.0):
    # ref: src/operator/tensor/elemwise_unary_op.h — smooth_l1 with sigma
    sigma2 = scalar * scalar
    absx = jnp.abs(x)
    return jnp.where(absx < 1.0 / sigma2, 0.5 * sigma2 * x * x, absx - 0.5 / sigma2)


@register_op("lerp")
def _lerp(a, b, t):
    return a + (b - a) * t


@register_op("zeros_like")
def _zeros_like(x):
    return jnp.zeros_like(x)


@register_op("ones_like")
def _ones_like(x):
    return jnp.ones_like(x)


# ---- creation ops (ref: src/operator/tensor/init_op.cc — _zeros/_ones/
# _arange/_full are registry ops so the SYMBOL frontend can create
# constants; mx.nd keeps its richer module-level creation functions) ----

def _shape_tuple(shape):
    return (shape,) if isinstance(shape, int) else tuple(shape)


@register_op("_zeros", aliases=("zeros",))
def _zeros_op(shape=(1,), dtype="float32", ctx=None):
    from ..base import dtype_np

    return jnp.zeros(_shape_tuple(shape), dtype_np(dtype))


@register_op("_ones", aliases=("ones",))
def _ones_op(shape=(1,), dtype="float32", ctx=None):
    from ..base import dtype_np

    return jnp.ones(_shape_tuple(shape), dtype_np(dtype))


@register_op("_full", aliases=("full",))
def _full_op(shape=(1,), value=0.0, dtype="float32", ctx=None, val=None):
    """`value` is the reference op's name; `val` (mx.nd.full's spelling)
    is accepted as an alias so sym/nd calls stay interchangeable."""
    from ..base import dtype_np

    if val is not None:
        value = val
    return jnp.full(_shape_tuple(shape), value, dtype_np(dtype))


@register_op("_arange", aliases=("arange",))
def _arange_op(start=0.0, stop=None, step=1.0, repeat=1, infer_range=False,
               dtype="float32", ctx=None):
    from ..base import dtype_np

    if stop is None:
        start, stop = 0.0, start
    out = jnp.arange(start, stop, step, dtype_np(dtype))
    if repeat != 1:
        out = jnp.repeat(out, repeat)
    return out
