"""Matrix, shape-manipulation and indexing ops.

Re-emission of (ref: src/operator/tensor/dot*.{h,cc,cu}, matrix_op*.{h,cc,cu},
indexing_op.{h,cc,cu}, la_op*.{h,cc}).  All matmuls go through jnp.dot /
lax.dot_general so XLA tiles them onto the MXU; gathers/scatters use XLA
gather/scatter which the reference hand-wrote as CUDA kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


# ------------------------------------------------------------------- dot ----
@register_op("dot")
def _dot(a, b, transpose_a=False, transpose_b=False):
    """Reference dot semantics: contract last axis of a with first of b
    (ref: src/operator/tensor/dot-inl.h — DotForward_)."""
    if transpose_a:
        a = jnp.moveaxis(a, 0, -1) if a.ndim > 1 else a
    if transpose_b:
        b = jnp.moveaxis(b, -1, 0) if b.ndim > 1 else b
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    return jnp.tensordot(a, b, axes=1)


@register_op("batch_dot")
def _batch_dot(a, b, transpose_a=False, transpose_b=False):
    """ref: src/operator/tensor/dot-inl.h — BatchDotForward_ (cuBLAS strided)."""
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


@register_op("linalg_gemm2")
def _linalg_gemm2(a, b, transpose_a=False, transpose_b=False, alpha=1.0, axis=-2):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return alpha * jnp.matmul(a, b)


@register_op("linalg_gemm")
def _linalg_gemm(a, b, c, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0, axis=-2):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return alpha * jnp.matmul(a, b) + beta * c


@register_op("linalg_potrf")
def _potrf(a):
    return jnp.linalg.cholesky(a)


@register_op("linalg_trsm")
def _trsm(a, b, transpose=False, rightside=False, lower=True, alpha=1.0):
    import jax.scipy.linalg as jsl

    if rightside:
        # solve X A = alpha B  <=>  A^T X^T = alpha B^T
        out = jsl.solve_triangular(
            jnp.swapaxes(a, -1, -2), jnp.swapaxes(alpha * b, -1, -2),
            lower=not lower, trans=1 if transpose else 0)
        return jnp.swapaxes(out, -1, -2)
    return jsl.solve_triangular(a, alpha * b, lower=lower, trans=1 if transpose else 0)


@register_op("linalg_syrk")
def _syrk(a, transpose=False, alpha=1.0):
    at = jnp.swapaxes(a, -1, -2)
    return alpha * (jnp.matmul(at, a) if transpose else jnp.matmul(a, at))


@register_op("linalg_extractdiag")
def _extractdiag(a, offset=0):
    return jnp.diagonal(a, offset=offset, axis1=-2, axis2=-1)


@register_op("linalg_sumlogdiag")
def _sumlogdiag(a):
    return jnp.sum(jnp.log(jnp.diagonal(a, axis1=-2, axis2=-1)), axis=-1)


# ----------------------------------------------------------------- shape ----
@register_op("reshape", aliases=("Reshape",))
def _reshape(x, shape=None, reverse=False):
    """Supports the reference's special codes 0,-1,-2,-3,-4
    (ref: src/operator/tensor/matrix_op-inl.h — InferReshapeShape)."""
    if shape is None:
        return x
    shape = list(shape)
    src = list(x.shape)
    if reverse:
        src = src[::-1]
        shape = shape[::-1]
    out = []
    i = 0  # index into src
    j = 0
    while j < len(shape):
        s = shape[j]
        if s == 0:
            out.append(src[i]); i += 1
        elif s == -1:
            out.append(-1); i += 1
        elif s == -2:
            out.extend(src[i:]); i = len(src)
        elif s == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif s == -4:
            a, b = shape[j + 1], shape[j + 2]
            if a == -1:
                a = src[i] // b
            if b == -1:
                b = src[i] // a
            out.extend([a, b]); i += 1; j += 2
        else:
            out.append(s); i += 1
        j += 1
    if reverse:
        out = out[::-1]
    if -1 in out:
        known = 1
        for s in out:
            if s != -1:
                known *= s
        out[out.index(-1)] = int(x.size // known) if known else 0
    return x.reshape(out)


@register_op("reshape_like")
def _reshape_like(x, y):
    return x.reshape(y.shape)


@register_op("shape_array")
def _shape_array(x):
    return jnp.asarray(x.shape, dtype=jnp.int64)


@register_op("size_array")
def _size_array(x):
    return jnp.asarray([x.size], dtype=jnp.int64)


@register_op("transpose")
def _transpose(x, axes=None):
    if axes is None or len(axes) == 0:
        axes = tuple(reversed(range(x.ndim)))
    return jnp.transpose(x, axes)


@register_op("swapaxes", aliases=("SwapAxis",))
def _swapaxes(x, dim1=0, dim2=0):
    return jnp.swapaxes(x, dim1, dim2)


@register_op("expand_dims")
def _expand_dims(x, axis=0):
    return jnp.expand_dims(x, axis)


@register_op("squeeze")
def _squeeze(x, axis=None):
    return jnp.squeeze(x, axis=axis)


@register_op("flatten", aliases=("Flatten",))
def _flatten(x):
    return x.reshape(x.shape[0], -1)


@register_op("broadcast_to")
def _broadcast_to(x, shape=None):
    tgt = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
    return jnp.broadcast_to(x, tgt)


@register_op("broadcast_like")
def _broadcast_like(x, y):
    return jnp.broadcast_to(x, y.shape)


@register_op("broadcast_axis", aliases=("broadcast_axes",))
def _broadcast_axis(x, axis=(), size=()):
    if isinstance(axis, int):
        axis = (axis,)
    if isinstance(size, int):
        size = (size,)
    tgt = list(x.shape)
    for a, s in zip(axis, size):
        tgt[a] = s
    return jnp.broadcast_to(x, tgt)


@register_op("tile")
def _tile(x, reps=()):
    return jnp.tile(x, reps)


@register_op("repeat")
def _repeat(x, repeats=1, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@register_op("flip", aliases=("reverse",))
def _flip(x, axis=()):
    return jnp.flip(x, axis=axis)


@register_op("pad", aliases=("Pad",))
def _pad(x, mode="constant", pad_width=(), constant_value=0.0):
    """ref: src/operator/pad-inl.h; pad_width is the flattened (before,after)
    per-axis list like the reference's."""
    pw = list(pad_width)
    pairs = [(pw[i], pw[i + 1]) for i in range(0, len(pw), 2)]
    while len(pairs) < x.ndim:
        pairs.append((0, 0))
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    if jmode == "constant":
        return jnp.pad(x, pairs, mode=jmode, constant_values=constant_value)
    return jnp.pad(x, pairs, mode=jmode)


@register_op("concat", aliases=("Concat", "concatenate"))
def _concat(*xs, dim=1, num_args=None):
    return jnp.concatenate(xs, axis=dim)


@register_op("stack")
def _stack(*xs, axis=0, num_args=None):
    return jnp.stack(xs, axis=axis)


@register_op("split", aliases=("SliceChannel",))
def _split(x, num_outputs=1, axis=1, squeeze_axis=False):
    outs = jnp.split(x, num_outputs, axis=axis)
    if squeeze_axis:
        outs = [jnp.squeeze(o, axis=axis) for o in outs]
    return tuple(outs) if len(outs) > 1 else outs[0]


@register_op("split_v2")
def _split_v2(x, indices=(), axis=0, squeeze_axis=False, sections=0):
    if sections:
        outs = jnp.split(x, sections, axis=axis)
    else:
        outs = jnp.split(x, list(indices), axis=axis)
    if squeeze_axis:
        outs = [jnp.squeeze(o, axis=axis) for o in outs]
    return tuple(outs) if len(outs) > 1 else outs[0]


@register_op("slice")
def _slice(x, begin=(), end=(), step=()):
    slices = []
    step = list(step) if step else [None] * len(begin)
    for b, e, s in zip(begin, end, step):
        slices.append(slice(b, e, s))
    return x[tuple(slices)]


@register_op("slice_axis")
def _slice_axis(x, axis=0, begin=0, end=None):
    sl = [slice(None)] * x.ndim
    sl[axis] = slice(begin, end)
    return x[tuple(sl)]


@register_op("slice_like")
def _slice_like(x, y, axes=()):
    sl = [slice(None)] * x.ndim
    if not axes:
        axes = range(min(x.ndim, y.ndim))
    for a in axes:
        sl[a] = slice(0, y.shape[a])
    return x[tuple(sl)]


# -------------------------------------------------------------- indexing ----
@register_op("take")
def _take(a, indices, axis=0, mode="clip"):
    """ref: src/operator/tensor/indexing_op.h — TakeOpForward."""
    idx = indices.astype(jnp.int32)
    if mode == "wrap":
        idx = jnp.mod(idx, a.shape[axis])
    else:
        idx = jnp.clip(idx, 0, a.shape[axis] - 1)
    return jnp.take(a, idx, axis=axis)


@register_op("Embedding", aliases=("embedding",))
def _embedding(data, weight, input_dim=None, output_dim=None, dtype="float32", sparse_grad=False):
    """ref: src/operator/tensor/indexing_op.h — EmbeddingOpForward; XLA gather."""
    idx = jnp.clip(data.astype(jnp.int32), 0, weight.shape[0] - 1)
    return jnp.take(weight, idx, axis=0)


@register_op("pick")
def _pick(data, index, axis=-1, keepdims=False, mode="clip"):
    idx = jnp.clip(index.astype(jnp.int32), 0, data.shape[axis] - 1)
    out = jnp.take_along_axis(data, jnp.expand_dims(idx, axis), axis=axis)
    return out if keepdims else jnp.squeeze(out, axis=axis)


@register_op("gather_nd")
def _gather_nd(data, indices):
    """ref: src/operator/tensor/indexing_op.h — GatherNDForward.
    indices shape (M, ...) indexes the first M dims of data."""
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return data[tuple(idx[i] for i in range(m))]


@register_op("scatter_nd")
def _scatter_nd(data, indices, shape=None):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    out = jnp.zeros(shape, dtype=data.dtype)
    return out.at[tuple(idx[i] for i in range(m))].set(data)


@register_op("one_hot")
def _one_hot(indices, depth=0, on_value=1.0, off_value=0.0, dtype="float32"):
    from ..base import dtype_np

    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth, dtype=dtype_np(dtype))
    return oh * on_value + (1.0 - oh) * off_value


@register_op("diag")
def _diag(x, k=0, axis1=0, axis2=1):
    if x.ndim == 1:
        return jnp.diag(x, k=k)
    return jnp.diagonal(x, offset=k, axis1=axis1, axis2=axis2)


@register_op("depth_to_space")
def _depth_to_space(x, block_size=1):
    b, c, h, w = x.shape
    bs = block_size
    y = x.reshape(b, bs, bs, c // (bs * bs), h, w)
    y = jnp.transpose(y, (0, 3, 4, 1, 5, 2))
    return y.reshape(b, c // (bs * bs), h * bs, w * bs)


@register_op("space_to_depth")
def _space_to_depth(x, block_size=1):
    b, c, h, w = x.shape
    bs = block_size
    y = x.reshape(b, c, h // bs, bs, w // bs, bs)
    y = jnp.transpose(y, (0, 3, 5, 1, 2, 4))
    return y.reshape(b, c * bs * bs, h // bs, w // bs)


@register_op("meshgrid_like")
def _arange_like(x, axis=0, start=0.0, step=1.0):
    n = x.shape[axis]
    return start + step * jnp.arange(n, dtype=jnp.float32)


@register_op("masked_fill")
def _masked_fill(x, mask, value=0.0):
    return jnp.where(mask.astype(bool), jnp.asarray(value, x.dtype), x)
