"""Neural-network core ops.

Re-emission of (ref: src/operator/nn/ — convolution-inl.h, fully_connected-inl.h,
batch_norm-inl.h, layer_norm-inl.h, pooling-inl.h, softmax-inl.h, dropout-inl.h,
activation-inl.h, ../leaky_relu-inl.h).  Convs lower to lax.conv_general_dilated
(MXU path, replacing cuDNN autotuned algos — XLA picks the tiling); pooling to
lax.reduce_window; normalisations are jnp expressions XLA fuses into one kernel.
Layout is NCHW/NCW/NCDHW to match the reference's default.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register_op
from .. import random as _random
from .. import autograd as _autograd


def _tup(v, n):
    if v is None or (isinstance(v, (tuple, list)) and len(v) == 0):
        return (1,) * n if n else ()
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


# -------------------------------------------------------------- linear ------
@register_op("FullyConnected", aliases=("fully_connected",))
def _fully_connected(data, weight, bias=None, num_hidden=None, no_bias=False, flatten=True):
    """ref: src/operator/nn/fully_connected-inl.h — FCForward (cuBLAS gemm).
    Weight layout (num_hidden, in_units), reference convention."""
    x = data.reshape(data.shape[0], -1) if flatten else data
    out = jnp.matmul(x, weight.T)
    if bias is not None and not no_bias:
        out = out + bias
    return out


# ---------------------------------------------------------------- conv ------
# channel-first (reference default) and channel-last (TPU-preferred: feature
# dim maps onto lanes without layout-change copies around every conv).
# Weight conventions follow the reference: O,I,*k channel-first; O,*k,I
# channel-last (src/operator/nn/convolution-inl.h layout table).
_CONV_LAYOUTS = {"NCW": ("NCW", "OIW", "NCW"), "NCHW": ("NCHW", "OIHW", "NCHW"),
                 "NCDHW": ("NCDHW", "OIDHW", "NCDHW"),
                 "NWC": ("NWC", "OWI", "NWC"), "NHWC": ("NHWC", "OHWI", "NHWC"),
                 "NDHWC": ("NDHWC", "ODHWI", "NDHWC")}
_DEFAULT_CONV_LAYOUT = {1: "NCW", 2: "NCHW", 3: "NCDHW"}


def _conv_layout(layout, nd):
    l = layout or _DEFAULT_CONV_LAYOUT[nd]
    if l not in _CONV_LAYOUTS:
        raise ValueError(f"unsupported conv layout {l!r}")
    return l, _CONV_LAYOUTS[l], l[-1] == "C"


@register_op("Convolution", aliases=("convolution",))
def _convolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                 pad=None, num_filter=None, num_group=1, workspace=1024,
                 no_bias=False, cudnn_tune=None, cudnn_off=False, layout=None):
    """ref: src/operator/nn/convolution-inl.h — ConvolutionOp::Forward.
    cuDNN algo selection is replaced by XLA's conv emitter onto the MXU."""
    nd = data.ndim - 2
    kernel = _tup(kernel, nd)
    stride = _tup(stride, nd)
    dilate = _tup(dilate, nd)
    pad = _tup(pad, nd) if pad else (0,) * nd
    _, dnl, chan_last = _conv_layout(layout, nd)
    dn = jax.lax.conv_dimension_numbers(data.shape, weight.shape, dnl)
    out = jax.lax.conv_general_dilated(
        data, weight,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
        precision=None,
    )
    if bias is not None and not no_bias:
        bshape = ((1,) * (nd + 1) + (-1,)) if chan_last \
            else ((1, -1) + (1,) * nd)
        out = out + bias.reshape(bshape)
    return out


@register_op("Deconvolution", aliases=("deconvolution",))
def _deconvolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                   pad=None, adj=None, target_shape=None, num_filter=None,
                   num_group=1, workspace=512, no_bias=True, cudnn_tune=None,
                   cudnn_off=False, layout=None):
    """ref: src/operator/nn/deconvolution-inl.h — transposed conv via
    lax.conv_transpose; weight layout (in, out/group, *k) like the reference."""
    nd = data.ndim - 2
    kernel = _tup(kernel, nd)
    stride = _tup(stride, nd)
    dilate = _tup(dilate, nd)
    pad = _tup(pad, nd) if pad else (0,) * nd
    adj = _tup(adj, nd) if adj else (0,) * nd
    # Gradient-of-conv formulation: with transpose_kernel=True jax itself
    # swaps the kernel's I/O axes, so the reference layout (in, out/group, *k)
    # is passed through as-is in the O-I slot order.  jax applies ``padding``
    # to the stride-dilated input, so the reference's output-size contract
    # out = (in-1)*stride - 2*pad + kernel (+adj) needs (ke-1-pad) here.
    _, (lhs, rhs, out_l), chan_last = _conv_layout(layout, nd)
    ke = [(k - 1) * d + 1 for k, d in zip(kernel, dilate)]
    out = jax.lax.conv_transpose(
        data, weight,
        strides=stride,
        padding=[(e - 1 - p, e - 1 - p) for e, p in zip(ke, pad)],
        rhs_dilation=dilate,
        dimension_numbers=(lhs, rhs, out_l),
        transpose_kernel=True,
    )
    if adj != (0,) * nd:
        pads = ([(0, 0)] + [(0, a) for a in adj] + [(0, 0)]) if chan_last \
            else ([(0, 0), (0, 0)] + [(0, a) for a in adj])
        out = jnp.pad(out, pads)
    if bias is not None and not no_bias:
        bshape = ((1,) * (nd + 1) + (-1,)) if chan_last \
            else ((1, -1) + (1,) * nd)
        out = out + bias.reshape(bshape)
    return out


# ------------------------------------------------------------- pooling ------
@register_op("Pooling", aliases=("pooling",))
def _pooling(data, kernel=None, pool_type="max", global_pool=False, cudnn_off=False,
             pooling_convention="valid", stride=None, pad=None, p_value=2,
             count_include_pad=True, layout=None):
    """ref: src/operator/nn/pooling-inl.h — PoolingOp; lax.reduce_window.
    ``layout`` accepts the channel-first defaults and the channel-last
    (NWC/NHWC/NDHWC) TPU-preferred variants."""
    nd = data.ndim - 2
    chan_last = _conv_layout(layout, nd)[2]
    sp0 = 1 if chan_last else 2  # first spatial axis
    if global_pool:
        axes = tuple(range(sp0, sp0 + nd))
        if pool_type == "max":
            return jnp.max(data, axis=axes, keepdims=True)
        if pool_type == "sum":
            return jnp.sum(data, axis=axes, keepdims=True)
        return jnp.mean(data, axis=axes, keepdims=True)
    kernel = _tup(kernel, nd)
    stride = _tup(stride, nd) if stride else kernel
    pad = _tup(pad, nd) if pad else (0,) * nd
    if pooling_convention == "full":
        # ceil-mode output: extend padding on the right so the last window fits
        extra = []
        for i in range(nd):
            size = data.shape[sp0 + i] + 2 * pad[i]
            rem = (size - kernel[i]) % stride[i]
            extra.append((stride[i] - rem) % stride[i] if rem else 0)
        spads = tuple((p, p + e) for p, e in zip(pad, extra))
    else:
        spads = tuple((p, p) for p in pad)
    if chan_last:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        pads = ((0, 0),) + spads + ((0, 0),)
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        pads = ((0, 0), (0, 0)) + spads
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).min
        return jax.lax.reduce_window(data, init, jax.lax.max, window, strides, pads)
    if pool_type in ("avg", "sum"):
        summed = jax.lax.reduce_window(data, 0.0, jax.lax.add, window, strides, pads)
        if pool_type == "sum":
            return summed
        if count_include_pad:
            denom = float(np.prod(kernel))
            return summed / denom
        ones = jnp.ones_like(data)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, pads)
        return summed / counts
    if pool_type == "lp":
        p = float(p_value)
        powed = jax.lax.reduce_window(jnp.abs(data) ** p, 0.0, jax.lax.add, window, strides, pads)
        return powed ** (1.0 / p)
    raise ValueError(f"unknown pool_type {pool_type}")


# ---------------------------------------------------------- normalisation ---
def _norm_axes(axes, ndim):
    axes = (axes,) if isinstance(axes, int) else tuple(axes)
    return tuple(a % ndim for a in axes)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _moments(data, axes, keepdims=False):
    """Centred mean/variance with wide accumulators (f32; f64 for f64 in).

    Centred (not E[x²]−E[x]²) so large-mean/small-std data keeps precision;
    the custom VJP recomputes the centred values in the backward instead of
    letting jax store a widened full-activation residual — the norm ops sit
    on the HBM-bound hot path and must never materialise an f32 activation
    (that residual alone cost ~15% ResNet-50 step time; see PERF.md)."""
    ax = _norm_axes(axes, data.ndim)
    if data.dtype in (jnp.bfloat16, jnp.float16):
        # half-precision hot path: one fused pass, f32 accumulators.  The
        # E[x²]−E[x]² cancellation floor (eps_f32·mean²) sits far below the
        # input's own quantisation noise for any data bf16 can represent,
        # and a single pass keeps the HBM-bound step at one read of x.
        x = data.astype(jnp.float32)
        mean = jnp.mean(x, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(x), axis=axes, keepdims=True) \
            - jnp.square(mean)
        var = jnp.maximum(var, 0.0)
    else:
        # full-precision path: centred two-pass — immune to large-mean
        # cancellation (the custom VJP below still avoids storing any
        # widened residual for the backward).
        acc_dt = jnp.float64 if data.dtype == jnp.float64 else jnp.float32
        x = data.astype(acc_dt)
        mean = jnp.mean(x, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(x - mean), axis=axes, keepdims=True)
    if not keepdims:
        shape = [d for i, d in enumerate(data.shape) if i not in ax]
        mean = mean.reshape(shape)
        var = var.reshape(shape)
    return mean, var


def _moments_fwd(data, axes, keepdims):
    mean, var = _moments(data, axes, keepdims)
    return (mean, var), (data, mean)


def _moments_bwd(axes, keepdims, res, cts):
    data, mean = res
    dmean, dvar = cts
    ax = _norm_axes(axes, data.ndim)
    n = 1
    for a in ax:
        n *= data.shape[a]
    kshape = [1 if i in ax else s for i, s in enumerate(data.shape)]
    mean_k = mean.reshape(kshape)
    dmean_k = dmean.reshape(kshape).astype(mean.dtype)
    dvar_k = dvar.reshape(kshape).astype(mean.dtype)
    xm = data.astype(mean.dtype) - mean_k  # recomputed, fuses, not stored
    dx = dmean_k / n + xm * (2.0 * dvar_k / n)
    return (dx.astype(data.dtype),)


_moments.defvjp(_moments_fwd, _moments_bwd)


@register_op("BatchNorm", aliases=("batch_norm",))
def _batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3, momentum=0.9,
                fix_gamma=True, use_global_stats=False, output_mean_var=False,
                axis=1, cudnn_off=False, training=None):
    """ref: src/operator/nn/batch_norm-inl.h — BatchNormForward.

    Functional form: returns (out, new_moving_mean, new_moving_var); the Gluon
    layer threads the aux state (the reference mutates aux in-place via the
    engine; under XLA state must be explicit).
    """
    if training is None:
        training = _autograd.is_training()
    axis = axis % data.ndim
    axes = tuple(i for i in range(data.ndim) if i != axis)
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    bshape = [1] * data.ndim
    bshape[axis] = data.shape[axis]
    if training and not use_global_stats:
        mean, var = _moments(data, axes)
        new_mm = moving_mean * momentum + mean.astype(moving_mean.dtype) * (1 - momentum)
        new_mv = moving_var * momentum + var.astype(moving_var.dtype) * (1 - momentum)
    else:
        mean = moving_mean.astype(jnp.float32)
        var = moving_var.astype(jnp.float32)
        new_mm, new_mv = moving_mean, moving_var
    # per-channel scale in wide precision (tiny), then one fused centred
    # multiply-add over the activation in ITS OWN dtype — the bf16 hot path
    # never materialises a widened activation (the step is HBM-bound), and
    # subtracting mean before scaling keeps large-mean data well-conditioned
    inv = jax.lax.rsqrt(var + eps)
    scale = (inv * g.astype(var.dtype)).astype(data.dtype)
    out = ((data - mean.astype(data.dtype).reshape(bshape))
           * scale.reshape(bshape) + beta.reshape(bshape))
    if output_mean_var:
        return out, mean.astype(data.dtype), inv.astype(data.dtype)
    return out, new_mm, new_mv


@register_op("FusedNormReluConv", aliases=("fused_norm_relu_conv",))
def _fused_norm_relu_conv(data, weight, gamma, beta, moving_mean,
                          moving_var, residual=None, eps=1e-5, momentum=0.9,
                          relu=True, stride=1, training=None):
    """BatchNorm(+residual)+ReLU folded into the following conv via the
    Pallas kernel (ops/pallas/fused_conv.py) — the normalized activation
    never reaches HBM.  NHWC data, HWIO weight, 1x1/3x3, stride 1 or 2.

    Functional like BatchNorm: returns (out, new_moving_mean,
    new_moving_var); the gluon NormReluConv2D layer threads the aux state.
    """
    from .pallas.fused_conv import norm_relu_conv

    if training is None:
        training = _autograd.is_training()
    axes = tuple(range(data.ndim - 1))  # NHWC: all but channels
    if training:
        mean, var = _moments(data, axes)
        new_mm = moving_mean * momentum + \
            jax.lax.stop_gradient(mean).astype(moving_mean.dtype) * (1 - momentum)
        new_mv = moving_var * momentum + \
            jax.lax.stop_gradient(var).astype(moving_var.dtype) * (1 - momentum)
    else:
        mean = moving_mean.astype(jnp.float32)
        var = moving_var.astype(jnp.float32)
        new_mm, new_mv = moving_mean, moving_var
    inv = jax.lax.rsqrt(var + eps)
    scale = gamma.astype(jnp.float32) * inv
    shift = beta.astype(jnp.float32) - mean.astype(jnp.float32) * scale
    out = norm_relu_conv(data, scale, shift, weight, residual=residual,
                         relu=relu, stride=stride)
    return out, new_mm, new_mv


@register_op("LayerNorm", aliases=("layer_norm",))
def _layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    """ref: src/operator/nn/layer_norm-inl.h — LayerNormCompute."""
    mean, var = _moments(data, axis, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    out = ((data - mean.astype(data.dtype)) * inv.astype(data.dtype)
           * gamma.reshape(shape) + beta.reshape(shape))
    if output_mean_var:
        return (out, jnp.squeeze(mean, axis).astype(data.dtype),
                jnp.squeeze(inv, axis).astype(data.dtype))
    return out


@register_op("RMSNorm", aliases=("rms_norm",))
def _rms_norm(data, gamma, axis=-1, eps=1e-6):
    """TPU-era extension (no reference analogue; standard in modern LMs)."""
    ms = jnp.mean(jnp.square(data.astype(jnp.float32)), axis=axis,
                  keepdims=True)
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    return data * jax.lax.rsqrt(ms + eps).astype(data.dtype) * gamma.reshape(shape)


@register_op("GroupNorm", aliases=("group_norm",))
def _group_norm(data, gamma, beta, num_groups=1, eps=1e-5):
    """ref: src/operator/nn/group_norm-inl.h."""
    n, c = data.shape[0], data.shape[1]
    rest = data.shape[2:]
    x = data.reshape(n, num_groups, c // num_groups, *rest)
    axes = tuple(range(2, x.ndim))
    mean, var = _moments(x, axes, keepdims=True)
    x = ((x - mean.astype(x.dtype))
         * jax.lax.rsqrt(var + eps).astype(x.dtype))
    x = x.reshape(data.shape)
    bshape = (1, c) + (1,) * len(rest)
    return x * gamma.reshape(bshape) + beta.reshape(bshape)


@register_op("InstanceNorm", aliases=("instance_norm",))
def _instance_norm(data, gamma, beta, eps=1e-3):
    """ref: src/operator/instance_norm-inl.h."""
    axes = tuple(range(2, data.ndim))
    mean, var = _moments(data, axes, keepdims=True)
    bshape = (1, data.shape[1]) + (1,) * (data.ndim - 2)
    return ((data - mean.astype(data.dtype))
            * jax.lax.rsqrt(var + eps).astype(data.dtype)
            * gamma.reshape(bshape) + beta.reshape(bshape))


# ------------------------------------------------------------ activation ----
@register_op("Activation", aliases=("activation",))
def _activation(data, act_type="relu"):
    """ref: src/operator/nn/activation-inl.h."""
    if act_type == "relu":
        return jax.nn.relu(data)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jax.nn.softplus(data)
    if act_type == "softsign":
        return jax.nn.soft_sign(data)
    if act_type == "gelu":
        # reference routes gelu via LeakyReLU(act_type='gelu'); accepted here
        # too so Dense(activation='gelu') works (the BERT FFN path)
        return jax.nn.gelu(data, approximate=False)
    if act_type == "silu" or act_type == "swish":
        return jax.nn.silu(data)
    raise ValueError(f"unknown act_type {act_type}")


@register_op("LeakyReLU", aliases=("leaky_relu",))
def _leaky_relu(data, gamma=None, act_type="leaky", slope=0.25, lower_bound=0.125,
                upper_bound=0.334):
    """ref: src/operator/leaky_relu-inl.h (leaky/prelu/elu/selu/gelu/rrelu)."""
    if act_type == "leaky":
        return jnp.where(data >= 0, data, slope * data)
    if act_type == "prelu":
        g = gamma
        if g.ndim < data.ndim and g.ndim == 1:
            g = g.reshape((1, -1) + (1,) * (data.ndim - 2))
        return jnp.where(data >= 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(data >= 0, data, alpha * jnp.expm1(data))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "rrelu":
        mid = (lower_bound + upper_bound) / 2.0
        return jnp.where(data >= 0, data, mid * data)
    raise ValueError(f"unknown act_type {act_type}")


@register_op("gelu_tanh")
def _gelu_tanh(data):
    return jax.nn.gelu(data, approximate=True)


@register_op("silu")
def _silu(data):
    return jax.nn.silu(data)


# --------------------------------------------------------------- softmax ----
@register_op("softmax")
def _softmax(data, axis=-1, temperature=None, length=None, use_length=False, dtype=None):
    """ref: src/operator/nn/softmax-inl.h — Softmax with optional length mask."""
    x = data / temperature if temperature else data
    if length is not None:
        pos = jnp.arange(x.shape[axis])
        shape = [1] * x.ndim
        shape[axis] = x.shape[axis]
        mask = pos.reshape(shape) < jnp.expand_dims(length.astype(jnp.int32), axis)
        x = jnp.where(mask, x, -jnp.inf)
        out = jax.nn.softmax(x, axis=axis)
        return jnp.where(mask, out, 0.0)
    return jax.nn.softmax(x, axis=axis)


@register_op("log_softmax")
def _log_softmax(data, axis=-1, temperature=None, dtype=None):
    x = data / temperature if temperature else data
    return jax.nn.log_softmax(x, axis=axis)


@register_op("softmin")
def _softmin(data, axis=-1, temperature=None, dtype=None):
    return _softmax(-data, axis=axis, temperature=temperature)


# --------------------------------------------------------------- dropout ----
@register_op("Dropout", aliases=("dropout",), needs_rng=True)
def _dropout(data, p=0.5, mode="training", axes=(), cudnn_off=False, training=None):
    """ref: src/operator/nn/dropout-inl.h — DropoutOp (inverted dropout)."""
    if training is None:
        training = _autograd.is_training()
    if (not training and mode != "always") or p == 0:
        return data
    key = _random.next_key()
    shape = list(data.shape)
    for a in axes:
        shape[a] = 1  # broadcast dropout over these axes
    keep = jax.random.bernoulli(key, 1.0 - p, shape=tuple(shape))
    return jnp.where(keep, data / (1.0 - p), jnp.zeros((), data.dtype))


# ------------------------------------------------------------- legacy fused -
@register_op("SoftmaxOutput", aliases=("softmax_output",))
def _softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                    multi_output=False, use_ignore=False, preserve_shape=False,
                    normalization="null", out_grad=False, smooth_alpha=0.0):
    """ref: src/operator/softmax_output-inl.h — forward only returns softmax;
    the fused backward trick is replaced by SoftmaxCrossEntropyLoss + autograd."""
    return jax.nn.softmax(data, axis=1 if multi_output else -1)
