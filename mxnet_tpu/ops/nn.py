"""Neural-network core ops.

Re-emission of (ref: src/operator/nn/ — convolution-inl.h, fully_connected-inl.h,
batch_norm-inl.h, layer_norm-inl.h, pooling-inl.h, softmax-inl.h, dropout-inl.h,
activation-inl.h, ../leaky_relu-inl.h).  Convs lower to lax.conv_general_dilated
(MXU path, replacing cuDNN autotuned algos — XLA picks the tiling); pooling to
lax.reduce_window; normalisations are jnp expressions XLA fuses into one kernel.
Layout is NCHW/NCW/NCDHW to match the reference's default.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register_op
from .. import random as _random
from .. import autograd as _autograd


def _tup(v, n):
    if v is None or (isinstance(v, (tuple, list)) and len(v) == 0):
        return (1,) * n if n else ()
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


# -------------------------------------------------------------- linear ------
@register_op("FullyConnected", aliases=("fully_connected",))
def _fully_connected(data, weight, bias=None, num_hidden=None, no_bias=False, flatten=True):
    """ref: src/operator/nn/fully_connected-inl.h — FCForward (cuBLAS gemm).
    Weight layout (num_hidden, in_units), reference convention."""
    x = data.reshape(data.shape[0], -1) if flatten else data
    out = jnp.matmul(x, weight.T)
    if bias is not None and not no_bias:
        out = out + bias
    return out


# ---------------------------------------------------------------- conv ------
_CONV_LAYOUTS = {1: ("NCW", "OIW", "NCW"), 2: ("NCHW", "OIHW", "NCHW"),
                 3: ("NCDHW", "OIDHW", "NCDHW")}


@register_op("Convolution", aliases=("convolution",))
def _convolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                 pad=None, num_filter=None, num_group=1, workspace=1024,
                 no_bias=False, cudnn_tune=None, cudnn_off=False, layout=None):
    """ref: src/operator/nn/convolution-inl.h — ConvolutionOp::Forward.
    cuDNN algo selection is replaced by XLA's conv emitter onto the MXU."""
    nd = data.ndim - 2
    kernel = _tup(kernel, nd)
    stride = _tup(stride, nd)
    dilate = _tup(dilate, nd)
    pad = _tup(pad, nd) if pad else (0,) * nd
    dn = jax.lax.conv_dimension_numbers(data.shape, weight.shape, _CONV_LAYOUTS[nd])
    out = jax.lax.conv_general_dilated(
        data, weight,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
        precision=None,
    )
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


@register_op("Deconvolution", aliases=("deconvolution",))
def _deconvolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                   pad=None, adj=None, target_shape=None, num_filter=None,
                   num_group=1, workspace=512, no_bias=True, cudnn_tune=None,
                   cudnn_off=False, layout=None):
    """ref: src/operator/nn/deconvolution-inl.h — transposed conv via
    lax.conv_transpose; weight layout (in, out/group, *k) like the reference."""
    nd = data.ndim - 2
    kernel = _tup(kernel, nd)
    stride = _tup(stride, nd)
    dilate = _tup(dilate, nd)
    pad = _tup(pad, nd) if pad else (0,) * nd
    adj = _tup(adj, nd) if adj else (0,) * nd
    # Gradient-of-conv formulation: with transpose_kernel=True jax itself
    # swaps the kernel's I/O axes, so the reference layout (in, out/group, *k)
    # is passed through as-is in the O-I slot order.  jax applies ``padding``
    # to the stride-dilated input, so the reference's output-size contract
    # out = (in-1)*stride - 2*pad + kernel (+adj) needs (ke-1-pad) here.
    lhs, rhs, out_l = _CONV_LAYOUTS[nd]
    ke = [(k - 1) * d + 1 for k, d in zip(kernel, dilate)]
    out = jax.lax.conv_transpose(
        data, weight,
        strides=stride,
        padding=[(e - 1 - p, e - 1 - p) for e, p in zip(ke, pad)],
        rhs_dilation=dilate,
        dimension_numbers=(lhs, rhs, out_l),
        transpose_kernel=True,
    )
    if adj != (0,) * nd:
        pads = [(0, 0), (0, 0)] + [(0, a) for a in adj]
        out = jnp.pad(out, pads)
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


# ------------------------------------------------------------- pooling ------
@register_op("Pooling", aliases=("pooling",))
def _pooling(data, kernel=None, pool_type="max", global_pool=False, cudnn_off=False,
             pooling_convention="valid", stride=None, pad=None, p_value=2,
             count_include_pad=True, layout=None):
    """ref: src/operator/nn/pooling-inl.h — PoolingOp; lax.reduce_window."""
    nd = data.ndim - 2
    if global_pool:
        axes = tuple(range(2, data.ndim))
        if pool_type == "max":
            return jnp.max(data, axis=axes, keepdims=True)
        if pool_type == "sum":
            return jnp.sum(data, axis=axes, keepdims=True)
        return jnp.mean(data, axis=axes, keepdims=True)
    kernel = _tup(kernel, nd)
    stride = _tup(stride, nd) if stride else kernel
    pad = _tup(pad, nd) if pad else (0,) * nd
    window = (1, 1) + kernel
    strides = (1, 1) + stride
    pads = ((0, 0), (0, 0)) + tuple((p, p) for p in pad)
    if pooling_convention == "full":
        # ceil-mode output: extend padding on the right so the last window fits
        extra = []
        for i in range(nd):
            size = data.shape[2 + i] + 2 * pad[i]
            rem = (size - kernel[i]) % stride[i]
            extra.append((stride[i] - rem) % stride[i] if rem else 0)
        pads = ((0, 0), (0, 0)) + tuple((p, p + e) for p, e in zip(pad, extra))
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).min
        return jax.lax.reduce_window(data, init, jax.lax.max, window, strides, pads)
    if pool_type in ("avg", "sum"):
        summed = jax.lax.reduce_window(data, 0.0, jax.lax.add, window, strides, pads)
        if pool_type == "sum":
            return summed
        if count_include_pad:
            denom = float(np.prod(kernel))
            return summed / denom
        ones = jnp.ones_like(data)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, pads)
        return summed / counts
    if pool_type == "lp":
        p = float(p_value)
        powed = jax.lax.reduce_window(jnp.abs(data) ** p, 0.0, jax.lax.add, window, strides, pads)
        return powed ** (1.0 / p)
    raise ValueError(f"unknown pool_type {pool_type}")


# ---------------------------------------------------------- normalisation ---
@register_op("BatchNorm", aliases=("batch_norm",))
def _batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3, momentum=0.9,
                fix_gamma=True, use_global_stats=False, output_mean_var=False,
                axis=1, cudnn_off=False, training=None):
    """ref: src/operator/nn/batch_norm-inl.h — BatchNormForward.

    Functional form: returns (out, new_moving_mean, new_moving_var); the Gluon
    layer threads the aux state (the reference mutates aux in-place via the
    engine; under XLA state must be explicit).
    """
    if training is None:
        training = _autograd.is_training()
    axes = tuple(i for i in range(data.ndim) if i != axis)
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    bshape = [1] * data.ndim
    bshape[axis] = data.shape[axis]
    if training and not use_global_stats:
        mean = jnp.mean(data, axis=axes)
        var = jnp.var(data, axis=axes)
        new_mm = moving_mean * momentum + mean * (1 - momentum)
        new_mv = moving_var * momentum + var * (1 - momentum)
    else:
        mean, var = moving_mean, moving_var
        new_mm, new_mv = moving_mean, moving_var
    inv = jax.lax.rsqrt(var + eps)
    out = (data - mean.reshape(bshape)) * (inv * g).reshape(bshape) + beta.reshape(bshape)
    if output_mean_var:
        return out, mean, inv
    return out, new_mm, new_mv


@register_op("LayerNorm", aliases=("layer_norm",))
def _layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    """ref: src/operator/nn/layer_norm-inl.h — LayerNormCompute."""
    mean = jnp.mean(data, axis=axis, keepdims=True)
    var = jnp.var(data, axis=axis, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    out = (data - mean) * inv * gamma.reshape(shape) + beta.reshape(shape)
    if output_mean_var:
        return out, jnp.squeeze(mean, axis), jnp.squeeze(inv, axis)
    return out


@register_op("RMSNorm", aliases=("rms_norm",))
def _rms_norm(data, gamma, axis=-1, eps=1e-6):
    """TPU-era extension (no reference analogue; standard in modern LMs)."""
    ms = jnp.mean(jnp.square(data), axis=axis, keepdims=True)
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    return data * jax.lax.rsqrt(ms + eps) * gamma.reshape(shape)


@register_op("GroupNorm", aliases=("group_norm",))
def _group_norm(data, gamma, beta, num_groups=1, eps=1e-5):
    """ref: src/operator/nn/group_norm-inl.h."""
    n, c = data.shape[0], data.shape[1]
    rest = data.shape[2:]
    x = data.reshape(n, num_groups, c // num_groups, *rest)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    x = (x - mean) * jax.lax.rsqrt(var + eps)
    x = x.reshape(data.shape)
    bshape = (1, c) + (1,) * len(rest)
    return x * gamma.reshape(bshape) + beta.reshape(bshape)


@register_op("InstanceNorm", aliases=("instance_norm",))
def _instance_norm(data, gamma, beta, eps=1e-3):
    """ref: src/operator/instance_norm-inl.h."""
    axes = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=axes, keepdims=True)
    var = jnp.var(data, axis=axes, keepdims=True)
    bshape = (1, data.shape[1]) + (1,) * (data.ndim - 2)
    return (data - mean) * jax.lax.rsqrt(var + eps) * gamma.reshape(bshape) + beta.reshape(bshape)


# ------------------------------------------------------------ activation ----
@register_op("Activation", aliases=("activation",))
def _activation(data, act_type="relu"):
    """ref: src/operator/nn/activation-inl.h."""
    if act_type == "relu":
        return jax.nn.relu(data)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jax.nn.softplus(data)
    if act_type == "softsign":
        return jax.nn.soft_sign(data)
    if act_type == "gelu":
        # reference routes gelu via LeakyReLU(act_type='gelu'); accepted here
        # too so Dense(activation='gelu') works (the BERT FFN path)
        return jax.nn.gelu(data, approximate=False)
    if act_type == "silu" or act_type == "swish":
        return jax.nn.silu(data)
    raise ValueError(f"unknown act_type {act_type}")


@register_op("LeakyReLU", aliases=("leaky_relu",))
def _leaky_relu(data, gamma=None, act_type="leaky", slope=0.25, lower_bound=0.125,
                upper_bound=0.334):
    """ref: src/operator/leaky_relu-inl.h (leaky/prelu/elu/selu/gelu/rrelu)."""
    if act_type == "leaky":
        return jnp.where(data >= 0, data, slope * data)
    if act_type == "prelu":
        g = gamma
        if g.ndim < data.ndim and g.ndim == 1:
            g = g.reshape((1, -1) + (1,) * (data.ndim - 2))
        return jnp.where(data >= 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(data >= 0, data, alpha * jnp.expm1(data))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "rrelu":
        mid = (lower_bound + upper_bound) / 2.0
        return jnp.where(data >= 0, data, mid * data)
    raise ValueError(f"unknown act_type {act_type}")


@register_op("gelu_tanh")
def _gelu_tanh(data):
    return jax.nn.gelu(data, approximate=True)


@register_op("silu")
def _silu(data):
    return jax.nn.silu(data)


# --------------------------------------------------------------- softmax ----
@register_op("softmax")
def _softmax(data, axis=-1, temperature=None, length=None, use_length=False, dtype=None):
    """ref: src/operator/nn/softmax-inl.h — Softmax with optional length mask."""
    x = data / temperature if temperature else data
    if length is not None:
        pos = jnp.arange(x.shape[axis])
        shape = [1] * x.ndim
        shape[axis] = x.shape[axis]
        mask = pos.reshape(shape) < jnp.expand_dims(length.astype(jnp.int32), axis)
        x = jnp.where(mask, x, -jnp.inf)
        out = jax.nn.softmax(x, axis=axis)
        return jnp.where(mask, out, 0.0)
    return jax.nn.softmax(x, axis=axis)


@register_op("log_softmax")
def _log_softmax(data, axis=-1, temperature=None, dtype=None):
    x = data / temperature if temperature else data
    return jax.nn.log_softmax(x, axis=axis)


@register_op("softmin")
def _softmin(data, axis=-1, temperature=None, dtype=None):
    return _softmax(-data, axis=axis, temperature=temperature)


# --------------------------------------------------------------- dropout ----
@register_op("Dropout", aliases=("dropout",), needs_rng=True)
def _dropout(data, p=0.5, mode="training", axes=(), cudnn_off=False, training=None):
    """ref: src/operator/nn/dropout-inl.h — DropoutOp (inverted dropout)."""
    if training is None:
        training = _autograd.is_training()
    if (not training and mode != "always") or p == 0:
        return data
    key = _random.next_key()
    shape = list(data.shape)
    for a in axes:
        shape[a] = 1  # broadcast dropout over these axes
    keep = jax.random.bernoulli(key, 1.0 - p, shape=tuple(shape))
    return jnp.where(keep, data / (1.0 - p), jnp.zeros((), data.dtype))


# ------------------------------------------------------------- legacy fused -
@register_op("SoftmaxOutput", aliases=("softmax_output",))
def _softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                    multi_output=False, use_ignore=False, preserve_shape=False,
                    normalization="null", out_grad=False, smooth_alpha=0.0):
    """ref: src/operator/softmax_output-inl.h — forward only returns softmax;
    the fused backward trick is replaced by SoftmaxCrossEntropyLoss + autograd."""
    return jax.nn.softmax(data, axis=1 if multi_output else -1)
