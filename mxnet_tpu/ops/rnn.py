"""Fused RNN op (LSTM / GRU / vanilla), the cuDNN-RNN equivalent.

ref: src/operator/rnn{.cc,-inl.h}, rnn_impl.h — one op runs a multi-layer,
optionally bidirectional recurrent stack over a packed parameter vector.
TPU-native design: the time loop is a single ``lax.scan`` per layer/direction
(compiled once, pipelined by XLA, weights stay resident in registers/VMEM);
the packed layout matches cuDNN's (all i2h/h2h weights layer-major, then all
biases) so Gluon layers can pack/unpack identically to the reference.

Gate order: LSTM [i, f, g, o]; GRU [r, z, n] (cuDNN order).
Data layout: time-major (T, N, C) like the reference's default.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op
from .. import autograd as _autograd
from .. import random as _random

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_param_size(mode, input_size, state_size, num_layers, bidirectional, projection_size=None):
    """Total packed parameter count (matches cuDNN packing)."""
    g = _GATES[mode]
    dirs = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * dirs
        size += dirs * g * state_size * (in_sz + state_size + 2)
    return size


def _unpack(params, mode, input_size, state_size, num_layers, dirs):
    """Split the flat parameter vector into per-(layer, dir) weight/bias mats."""
    g = _GATES[mode]
    h = state_size
    shapes = []
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else h * dirs
        for _ in range(dirs):
            shapes.append(("w_ih", (g * h, in_sz)))
            shapes.append(("w_hh", (g * h, h)))
    for layer in range(num_layers):
        for _ in range(dirs):
            shapes.append(("b_ih", (g * h,)))
            shapes.append(("b_hh", (g * h,)))
    out, off = [], 0
    for _, shp in shapes:
        n = 1
        for s in shp:
            n *= s
        out.append(params[off:off + n].reshape(shp))
        off += n
    weights = out[: 2 * num_layers * dirs]
    biases = out[2 * num_layers * dirs:]
    cells = []
    for i in range(num_layers * dirs):
        cells.append((weights[2 * i], weights[2 * i + 1], biases[2 * i], biases[2 * i + 1]))
    return cells  # indexed [layer * dirs + dir]


def _lstm_cell(carry, xw, w_hh, b):
    h, c = carry
    gates = xw + jnp.matmul(h, w_hh.T) + b
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return (h_new, c_new), h_new


def _gru_cell(carry, x_t, w_ih, w_hh, b_ih, b_hh):
    (h,) = carry
    gi = jnp.matmul(x_t, w_ih.T) + b_ih
    gh = jnp.matmul(h, w_hh.T) + b_hh
    i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
    h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(i_r + h_r)
    z = jax.nn.sigmoid(i_z + h_z)
    n = jnp.tanh(i_n + r * h_n)
    h_new = (1 - z) * n + z * h
    return (h_new,), h_new


def _vanilla_cell(carry, xw, w_hh, b, act):
    (h,) = carry
    pre = xw + jnp.matmul(h, w_hh.T) + b
    h_new = act(pre)
    return (h_new,), h_new


def _run_direction(x, h0, c0, cell_params, mode, reverse):
    """Scan one layer in one direction. x: (T, N, C_in)."""
    w_ih, w_hh, b_ih, b_hh = cell_params
    xs = jnp.flip(x, axis=0) if reverse else x
    if mode == "lstm":
        # precompute input projections for the whole sequence: one big MXU
        # matmul; both biases fold into it, so the scan body is h2h-only
        xw = jnp.matmul(xs, w_ih.T) + b_ih + b_hh

        def step(carry, xw_t):
            return _lstm_cell(carry, xw_t, w_hh, jnp.zeros((), xw_t.dtype))

        (h_n, c_n), ys = jax.lax.scan(step, (h0, c0), xw)
    elif mode == "gru":
        def step(carry, x_t):
            return _gru_cell(carry, x_t, w_ih, w_hh, b_ih, b_hh)
        (h_n,), ys = jax.lax.scan(step, (h0,), xs)
        c_n = None
    else:
        act = jax.nn.relu if mode == "rnn_relu" else jnp.tanh
        xw = jnp.matmul(xs, w_ih.T) + b_ih + b_hh
        def step(carry, xw_t):
            return _vanilla_cell(carry, xw_t, w_hh, jnp.zeros((), xw_t.dtype), act)
        (h_n,), ys = jax.lax.scan(step, (h0,), xw)
        c_n = None
    if reverse:
        ys = jnp.flip(ys, axis=0)
    return ys, h_n, c_n


@register_op("RNN", needs_rng=True)
def _rnn(data, parameters, state=None, state_cell=None, state_size=None,
         num_layers=1,
         bidirectional=False, mode="lstm", p=0.0, state_outputs=False,
         projection_size=None, lstm_state_clip_min=None, lstm_state_clip_max=None,
         lstm_state_clip_nan=False, use_sequence_length=False, training=None):
    """Fused multi-layer RNN (ref: src/operator/rnn.cc — the PTB-LSTM hot path).

    data (T, N, C); state (L*dirs, N, H); lstm also takes state_cell.
    state/state_cell may be omitted (None) for zero initial states — the
    common `mx.rnn.FusedRNNCell.unroll` start.  Returns out, state_h
    [, state_c] — always the tuple; callers select.
    """
    if training is None:
        training = _autograd.is_training()
    dirs = 2 if bidirectional else 1
    h = state_size
    if state is None:
        state = jnp.zeros((num_layers * dirs, data.shape[1], h), data.dtype)
    if state_cell is None and mode == "lstm":
        state_cell = jnp.zeros((num_layers * dirs, data.shape[1], h),
                               data.dtype)
    cells = _unpack(parameters, mode, data.shape[-1], h, num_layers, dirs)
    x = data
    h_states, c_states = [], []
    for layer in range(num_layers):
        outs = []
        for d in range(dirs):
            idx = layer * dirs + d
            h0 = state[idx]
            c0 = state_cell[idx] if mode == "lstm" else None
            ys, h_n, c_n = _run_direction(x, h0, c0, cells[idx], mode, reverse=(d == 1))
            outs.append(ys)
            h_states.append(h_n)
            if mode == "lstm":
                c_states.append(c_n)
        x = outs[0] if dirs == 1 else jnp.concatenate(outs, axis=-1)
        if p > 0 and training and layer < num_layers - 1:
            key = _random.next_key()
            keep = jax.random.bernoulli(key, 1.0 - p, shape=x.shape)
            x = jnp.where(keep, x / (1.0 - p), jnp.zeros((), x.dtype))
    h_out = jnp.stack(h_states, axis=0)
    if mode == "lstm":
        c_out = jnp.stack(c_states, axis=0)
        if lstm_state_clip_min is not None and lstm_state_clip_max is not None:
            c_out = jnp.clip(c_out, lstm_state_clip_min, lstm_state_clip_max)
        return x, h_out, c_out
    return x, h_out
