"""SSD detection ops.

ref: src/operator/contrib/multibox_prior-inl.h, multibox_target-inl.h,
multibox_detection-inl.h, bounding_box-inl.h (box_nms / box_iou).
The reference's CUDA kernels use data-dependent loops; TPU formulation is
fixed-shape and mask-based: NMS is a lax.fori_loop over a static candidate
count with suppression masks, which XLA compiles to a tight on-chip loop.
Boxes are corner-format (xmin, ymin, xmax, ymax) normalised to [0,1].
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register_op


@register_op("MultiBoxPrior", aliases=("multibox_prior", "_contrib_MultiBoxPrior"))
def _multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False, steps=(-1.0, -1.0),
                    offsets=(0.5, 0.5)):
    """Anchor generation (ref: MultiBoxPriorForward). data: (N, C, H, W);
    returns (1, H*W*A, 4) with A = len(sizes)+len(ratios)-1."""
    h, w = data.shape[2], data.shape[3]
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    cy = (jnp.arange(h, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(w, dtype=jnp.float32) + offsets[1]) * step_x
    cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")
    centers = jnp.stack([cxg, cyg], axis=-1).reshape(-1, 2)  # (H*W, 2) as (x, y)
    ws, hs = [], []
    # anchor set: (sizes[0], ratios[*]) then (sizes[1:], ratios[0]) — reference order
    for i, s in enumerate(sizes):
        for j, r in enumerate(ratios):
            if i > 0 and j > 0:
                continue
            sr = float(np.sqrt(r))
            ws.append(s * sr / 2)
            hs.append(s / sr / 2)
    half_wh = jnp.asarray(list(zip(ws, hs)), jnp.float32)  # (A, 2)
    a = half_wh.shape[0]
    cs = jnp.repeat(centers[:, None, :], a, axis=1)  # (HW, A, 2)
    anchors = jnp.concatenate([cs - half_wh[None], cs + half_wh[None]], axis=-1)
    anchors = anchors.reshape(1, -1, 4)
    if clip:
        anchors = jnp.clip(anchors, 0.0, 1.0)
    return anchors


def box_iou_matrix(a, b):
    """IoU of (..., Na, 4) vs (..., Nb, 4) corner boxes -> (..., Na, Nb)."""
    tl = jnp.maximum(a[..., :, None, :2], b[..., None, :, :2])
    br = jnp.minimum(a[..., :, None, 2:], b[..., None, :, 2:])
    wh = jnp.maximum(br - tl, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum(a[..., 2] - a[..., 0], 0.0) * jnp.maximum(a[..., 3] - a[..., 1], 0.0)
    area_b = jnp.maximum(b[..., 2] - b[..., 0], 0.0) * jnp.maximum(b[..., 3] - b[..., 1], 0.0)
    union = area_a[..., :, None] + area_b[..., None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register_op("box_iou", aliases=("_contrib_box_iou",))
def _box_iou(lhs, rhs, format="corner"):
    if format == "center":
        def c2c(x):
            cx, cy, w, h = x[..., 0], x[..., 1], x[..., 2], x[..., 3]
            return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)
        lhs, rhs = c2c(lhs), c2c(rhs)
    return box_iou_matrix(lhs, rhs)


def _nms_single(boxes, scores, iou_thresh, topk):
    """Greedy NMS on one image, fixed shapes. Returns keep mask (N,)."""
    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    boxes_s = boxes[order]
    iou = box_iou_matrix(boxes_s, boxes_s)
    valid = scores[order] > -jnp.inf

    def body(i, keep):
        # suppress j > i if iou(i, j) > thresh and i is kept
        sup = (iou[i] > iou_thresh) & (jnp.arange(n) > i) & keep[i]
        return keep & ~sup

    keep_sorted = jax.lax.fori_loop(0, n if topk <= 0 else min(topk, n), body, valid)
    # scatter back to original order
    keep = jnp.zeros((n,), bool).at[order].set(keep_sorted)
    return keep


@register_op("box_nms", aliases=("_contrib_box_nms",))
def _box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1, coord_start=2,
             score_index=1, id_index=-1, background_id=-1, force_suppress=False,
             in_format="corner", out_format="corner"):
    """ref: bounding_box-inl.h — BoxNMSForward. data (B, N, K) rows of
    [id, score, x1, y1, x2, y2, ...]; suppressed rows get score/id = -1."""
    def one(img):
        scores = img[:, score_index]
        boxes = jax.lax.dynamic_slice_in_dim(img, coord_start, 4, axis=1)
        invalid = scores < valid_thresh
        if id_index >= 0 and background_id >= 0:
            invalid = invalid | (img[:, id_index] == background_id)
        s = jnp.where(invalid, -jnp.inf, scores)
        if id_index >= 0 and not force_suppress:
            # class-aware: offset boxes by class id so classes never overlap
            off = img[:, id_index:id_index + 1] * 4.0
            keep = _nms_single(boxes + off, s, overlap_thresh, topk)
        else:
            keep = _nms_single(boxes, s, overlap_thresh, topk)
        out = img
        dead = ~keep
        out = out.at[:, score_index].set(jnp.where(dead, -1.0, img[:, score_index]))
        if id_index >= 0:
            out = out.at[:, id_index].set(jnp.where(dead, -1.0, img[:, id_index]))
        return out

    return jax.vmap(one)(data)


@register_op("MultiBoxTarget", aliases=("multibox_target", "_contrib_MultiBoxTarget"))
def _multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                     ignore_label=-1.0, negative_mining_ratio=-1.0,
                     negative_mining_thresh=0.5, minimum_negative_samples=0,
                     variances=(0.1, 0.1, 0.2, 0.2)):
    """ref: multibox_target-inl.h — anchor/GT matching + box target encoding.

    anchor (1, A, 4); label (B, M, 5) rows [cls, x1, y1, x2, y2] (cls<0 pad);
    cls_pred (B, C+1, A).  Returns (box_target (B, A*4), box_mask (B, A*4),
    cls_target (B, A)).
    """
    anchors = anchor[0]  # (A, 4)
    a = anchors.shape[0]
    var = jnp.asarray(variances, jnp.float32)

    def one(lab, scores):
        gt_valid = lab[:, 0] >= 0  # (M,)
        gt_boxes = lab[:, 1:5]
        iou = box_iou_matrix(anchors, gt_boxes)  # (A, M)
        iou = jnp.where(gt_valid[None, :], iou, 0.0)
        best_gt = jnp.argmax(iou, axis=1)          # (A,)
        best_iou = jnp.max(iou, axis=1)
        # force-match: each gt claims its best anchor
        best_anchor = jnp.argmax(iou, axis=0)      # (M,)
        forced = jnp.zeros((a,), bool)
        m = gt_boxes.shape[0]
        forced = forced.at[best_anchor].set(gt_valid | forced[best_anchor])
        forced_gt = jnp.zeros((a,), jnp.int32).at[best_anchor].set(
            jnp.arange(m, dtype=jnp.int32))
        pos = forced | (best_iou >= overlap_threshold)
        gt_idx = jnp.where(forced, forced_gt, best_gt.astype(jnp.int32))
        matched = gt_boxes[gt_idx]                 # (A, 4)
        cls_target = jnp.where(pos, lab[gt_idx, 0] + 1.0, 0.0)
        # hard negative mining by background confidence
        if negative_mining_ratio > 0:
            neg_scores = 1.0 - scores[0]  # background prob proxy: (A,) from cls_pred[:,0,:]
            num_pos = jnp.sum(pos.astype(jnp.int32))
            max_neg = jnp.maximum((num_pos * negative_mining_ratio).astype(jnp.int32),
                                  minimum_negative_samples)
            neg_rank = jnp.argsort(jnp.argsort(-jnp.where(pos, -jnp.inf, neg_scores)))
            keep_neg = (~pos) & (neg_rank < max_neg)
            cls_target = jnp.where(~pos & ~keep_neg, ignore_label, cls_target)
        # encode box targets with variances (center form)
        aw = anchors[:, 2] - anchors[:, 0]
        ah = anchors[:, 3] - anchors[:, 1]
        acx = (anchors[:, 0] + anchors[:, 2]) / 2
        acy = (anchors[:, 1] + anchors[:, 3]) / 2
        gw = jnp.maximum(matched[:, 2] - matched[:, 0], 1e-8)
        gh = jnp.maximum(matched[:, 3] - matched[:, 1], 1e-8)
        gcx = (matched[:, 0] + matched[:, 2]) / 2
        gcy = (matched[:, 1] + matched[:, 3]) / 2
        tx = (gcx - acx) / jnp.maximum(aw, 1e-8) / var[0]
        ty = (gcy - acy) / jnp.maximum(ah, 1e-8) / var[1]
        tw = jnp.log(gw / jnp.maximum(aw, 1e-8)) / var[2]
        th = jnp.log(gh / jnp.maximum(ah, 1e-8)) / var[3]
        bt = jnp.stack([tx, ty, tw, th], axis=-1)  # (A, 4)
        mask = pos[:, None].astype(jnp.float32) * jnp.ones((1, 4), jnp.float32)
        return (bt * mask).reshape(-1), mask.reshape(-1), cls_target

    bt, bm, ct = jax.vmap(one)(label, cls_pred)
    return bt, bm, ct


@register_op("MultiBoxDetection", aliases=("multibox_detection", "_contrib_MultiBoxDetection"))
def _multibox_detection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                        background_id=0, nms_threshold=0.5, force_suppress=False,
                        variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """ref: multibox_detection-inl.h — decode + per-class NMS.
    cls_prob (B, C+1, A); loc_pred (B, A*4); anchor (1, A, 4).
    Output (B, A, 6) rows [cls_id, score, x1, y1, x2, y2]."""
    anchors = anchor[0]
    var = jnp.asarray(variances, jnp.float32)
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2

    def one(probs, loc):
        loc = loc.reshape(-1, 4)
        cx = loc[:, 0] * var[0] * aw + acx
        cy = loc[:, 1] * var[1] * ah + acy
        w = jnp.exp(loc[:, 2] * var[2]) * aw / 2
        h = jnp.exp(loc[:, 3] * var[3]) * ah / 2
        boxes = jnp.stack([cx - w, cy - h, cx + w, cy + h], axis=-1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        # best non-background class per anchor (reference picks argmax class)
        fg = jnp.concatenate([probs[:background_id], probs[background_id + 1:]], axis=0)
        cls_id = jnp.argmax(fg, axis=0).astype(jnp.float32)
        score = jnp.max(fg, axis=0)
        cls_id = jnp.where(score > threshold, cls_id, -1.0)
        score = jnp.where(score > threshold, score, -1.0)
        det = jnp.concatenate([cls_id[:, None], score[:, None], boxes], axis=-1)
        return det

    det = jax.vmap(one)(cls_prob, loc_pred)
    return _box_nms(det, overlap_thresh=nms_threshold, valid_thresh=0.0,
                    topk=nms_topk, coord_start=2, score_index=1, id_index=0,
                    background_id=-1, force_suppress=force_suppress)


@register_op("ROIPooling", aliases=("roi_pooling",))
def _roi_pooling(data, rois, pooled_size=(7, 7), spatial_scale=1.0):
    """ref: src/operator/roi_pooling-inl.h. rois (R, 5) [batch_idx, x1, y1, x2, y2]."""
    ph, pw = pooled_size

    def one(roi):
        bidx = roi[0].astype(jnp.int32)
        img = data[bidx]  # (C, H, W)
        x1 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * spatial_scale).astype(jnp.int32)
        h = jnp.maximum(y2 - y1 + 1, 1)
        w = jnp.maximum(x2 - x1 + 1, 1)
        c, ih, iw = img.shape
        ys = jnp.arange(ih)
        xs = jnp.arange(iw)
        # bin index of every pixel, -1 if outside roi
        ybin = jnp.where((ys >= y1) & (ys <= y2), ((ys - y1) * ph) // h, -1)
        xbin = jnp.where((xs >= x1) & (xs <= x2), ((xs - x1) * pw) // w, -1)
        yoh = (ybin[:, None] == jnp.arange(ph)[None, :])  # (H, ph)
        xoh = (xbin[:, None] == jnp.arange(pw)[None, :])  # (W, pw)
        neg = jnp.asarray(-1e30, img.dtype)
        # (C, ph, pw): max over pixels whose bin matches
        expanded = jnp.where(yoh[None, :, None, :, None] & xoh[None, None, :, None, :],
                             img[:, :, :, None, None], neg)
        return jnp.max(expanded, axis=(1, 2))

    return jax.vmap(one)(rois)


@register_op("Proposal", aliases=("_contrib_Proposal", "proposal"))
def _proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
              rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
              scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
              feature_stride=16, output_score=False, iou_loss=False):
    """ref: src/operator/contrib/proposal-inl.h — RPN proposal generation
    (Faster-RCNN family).  TPU-native: fixed-shape masked pipeline — no
    data-dependent filtering; below-minimum / suppressed proposals carry
    score -inf and the fixed top-k pads with the best survivors.

    cls_prob (N, 2A, H, W): [background..., foreground...] per anchor;
    bbox_pred (N, 4A, H, W); im_info (N, 3) rows [height, width, scale].
    Returns rois (N*post_nms_top_n, 5) rows [batch_idx, x1, y1, x2, y2]
    (+ scores (N*post, 1) when output_score).
    """
    if iou_loss:
        raise NotImplementedError(
            "Proposal(iou_loss=True) — the IoU-loss corner-offset box "
            "decoding is not implemented; the default ctr/size transform "
            "is (fail loudly rather than decode with the wrong transform)")
    a = len(scales) * len(ratios)
    n, _, h, w = cls_prob.shape

    # base anchors centered on stride cells (reference GenerateAnchors)
    base = []
    cx = cy = (feature_stride - 1) / 2.0
    for r in ratios:
        size = feature_stride * feature_stride
        size_r = round(math.sqrt(size / r))
        ws0, hs0 = size_r, round(size_r * r)
        for s in scales:
            ws, hs = ws0 * s, hs0 * s
            base.append([cx - (ws - 1) / 2, cy - (hs - 1) / 2,
                         cx + (ws - 1) / 2, cy + (hs - 1) / 2])
    base = jnp.asarray(base, jnp.float32)                      # (A, 4)
    sx = jnp.arange(w, dtype=jnp.float32) * feature_stride
    sy = jnp.arange(h, dtype=jnp.float32) * feature_stride
    shift = jnp.stack(jnp.meshgrid(sx, sy, indexing="xy"), -1)  # (H, W, 2)
    shift = jnp.tile(shift, (1, 1, 2)).reshape(h * w, 1, 4)
    anchors = (base[None] + shift).reshape(-1, 4)              # (H*W*A, 4)

    def one(scores_img, deltas_img, info):
        # foreground scores: channels [A:2A], layout (A, H, W) -> (HWA,)
        fg = scores_img[a:].transpose(1, 2, 0).reshape(-1)
        d = deltas_img.reshape(a, 4, h, w).transpose(2, 3, 0, 1) \
            .reshape(-1, 4)
        widths = anchors[:, 2] - anchors[:, 0] + 1.0
        heights = anchors[:, 3] - anchors[:, 1] + 1.0
        ctr_x = anchors[:, 0] + 0.5 * (widths - 1)
        ctr_y = anchors[:, 1] + 0.5 * (heights - 1)
        pred_ctr_x = d[:, 0] * widths + ctr_x
        pred_ctr_y = d[:, 1] * heights + ctr_y
        pred_w = jnp.exp(d[:, 2]) * widths
        pred_h = jnp.exp(d[:, 3]) * heights
        boxes = jnp.stack([pred_ctr_x - 0.5 * (pred_w - 1),
                           pred_ctr_y - 0.5 * (pred_h - 1),
                           pred_ctr_x + 0.5 * (pred_w - 1),
                           pred_ctr_y + 0.5 * (pred_h - 1)], -1)
        # clip to image, drop boxes below the scaled minimum size
        boxes = jnp.clip(boxes,
                         jnp.zeros((4,), jnp.float32),
                         jnp.stack([info[1] - 1, info[0] - 1,
                                    info[1] - 1, info[0] - 1]))
        min_size = rpn_min_size * info[2]
        keep = ((boxes[:, 2] - boxes[:, 0] + 1 >= min_size)
                & (boxes[:, 3] - boxes[:, 1] + 1 >= min_size))
        s = jnp.where(keep, fg, -jnp.inf)
        k_pre = min(rpn_pre_nms_top_n, s.shape[0])
        top_s, top_i = jax.lax.top_k(s, k_pre)
        top_b = boxes[top_i]
        nms_keep = _nms_single(top_b, top_s, threshold, -1)
        s2 = jnp.where(nms_keep, top_s, -jnp.inf)
        k_post = min(rpn_post_nms_top_n, s2.shape[0])
        out_s, out_i = jax.lax.top_k(s2, k_post)
        out_b = top_b[out_i]
        pad = rpn_post_nms_top_n - k_post
        if pad:
            out_b = jnp.pad(out_b, ((0, pad), (0, 0)))
            out_s = jnp.pad(out_s, (0, pad), constant_values=-jnp.inf)
        return out_b, out_s

    boxes, scores = jax.vmap(one)(cls_prob, bbox_pred, im_info)
    batch_idx = jnp.repeat(jnp.arange(n, dtype=jnp.float32),
                           rpn_post_nms_top_n)[:, None]
    rois = jnp.concatenate([batch_idx, boxes.reshape(-1, 4)], axis=1)
    if output_score:
        return rois, scores.reshape(-1, 1)
    return rois
