"""Operator library.

TPU-native re-emission of the reference's ``src/operator`` tree: every op is a
pure JAX function (XLA HLO), with Pallas kernels for the few fusions XLA cannot
express well.  Gradients come from JAX VJP — the FGradient registry of the
reference (ref: 3rdparty/tvm/nnvm — NNVM_REGISTER_OP / FGradient) is subsumed
by jax.vjp, which is strictly more general.
"""
from . import registry  # noqa: F401
from .registry import OPS, register_op, get_op, alias_op  # noqa: F401

# Import op families for registration side-effects.
from . import elementwise  # noqa: F401
from . import reduce as reduce_ops  # noqa: F401
from . import matrix  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import sequence  # noqa: F401
from . import loss  # noqa: F401
from . import rnn  # noqa: F401
from . import attention  # noqa: F401
from . import paged_attention  # noqa: F401
from . import image  # noqa: F401
from . import multibox  # noqa: F401
from . import quantization  # noqa: F401
from . import control_flow  # noqa: F401
from . import random_ops  # noqa: F401
