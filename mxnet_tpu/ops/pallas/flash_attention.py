"""Flash attention Pallas kernel — the long-context hot path.

The reference's attention is two cuBLAS strided-batched matmuls with the full
(B*H, S, S) score matrix materialised (ref: src/operator/contrib/
transformer.cc).  On TPU that matrix is the HBM wall at long sequence; this
kernel computes softmax(QK^T)V blockwise with the online-softmax recurrence so
peak memory is O(S·D + block_q·S) instead of O(S^2) per head, with the two
matmuls staying resident on the MXU (SURVEY.md §7.0.2 names this kernel).

Forward: one Pallas program per (batch·head, q-block): K/V live in VMEM and
the kernel loops over k-blocks with fori_loop, carrying (acc, m, l).
Backward: custom-vjp recomputation — per q-block the scores are rebuilt in a
``lax.map`` over blocks (pure XLA, never materialising S×S), the flash-
standard trade of FLOPs for memory.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, block_k):
    # q_ref: (1, block_q, D); k_ref/v_ref: (1, S, D); o_ref: (1, block_q, D)
    q = q_ref[0].astype(jnp.float32) * scale          # (bq, D)
    bq = q.shape[0]
    s_len = k_ref.shape[1]
    n_kv = s_len // block_k
    qi = pl.program_id(1)

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        s = q @ k.T                                   # (bq, bk)
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = acc * alpha[:, None] + p @ v
        return acc_new, m_new, l_new

    d = q.shape[-1]
    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, n_kv, body, (acc0, m0, l0))
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    bh, s, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    assert s % block_q == 0 and s % block_k == 0, (s, block_q, block_k)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_k=block_k)
    return pl.pallas_call(
        kernel,
        grid=(bh, s // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v)


def _dense_block_bwd(q, k, v, o, do, scale, causal, block_q):
    """Recompute-based backward: map over q-blocks; each block rebuilds its
    (block_q, S) score rows (flash-style memory profile, plain XLA)."""
    bh, s, d = q.shape
    n_blocks = s // block_q
    delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32), axis=-1)

    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)

    def one_block(args):
        qb, dob, deltab, idx = args          # (bh, bq, d), ..., scalar block idx
        sc = jnp.einsum("bqd,bkd->bqk", qb.astype(jnp.float32) * scale, kf)
        if causal:
            q_pos = idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, sc.shape, 1)
            k_pos = jax.lax.broadcasted_iota(jnp.int32, sc.shape, 2)
            sc = jnp.where(q_pos >= k_pos, sc, _NEG_INF)
        p = jax.nn.softmax(sc, axis=-1)
        dv_b = jnp.einsum("bqk,bqd->bkd", p, dob.astype(jnp.float32))
        dp = jnp.einsum("bqd,bkd->bqk", dob.astype(jnp.float32), vf)
        ds = p * (dp - deltab[..., None])
        dq_b = jnp.einsum("bqk,bkd->bqd", ds, kf) * scale
        dk_b = jnp.einsum("bqk,bqd->bkd", ds, qb.astype(jnp.float32)) * scale
        return dq_b, dk_b, dv_b

    qb = q.reshape(bh, n_blocks, block_q, d).transpose(1, 0, 2, 3)
    dob = do.reshape(bh, n_blocks, block_q, d).transpose(1, 0, 2, 3)
    deltab = delta.reshape(bh, n_blocks, block_q).transpose(1, 0, 2)
    idxs = jnp.arange(n_blocks)
    dq_b, dk_b, dv_b = jax.lax.map(one_block, (qb, dob, deltab, idxs))
    dq = dq_b.transpose(1, 0, 2, 3).reshape(bh, s, d)
    dk = dk_b.sum(axis=0)
    dv = dv_b.sum(axis=0)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, scale=None, causal=False, block_q=128,
                    block_k=128, interpret=None):
    """softmax(scale * Q K^T [, causal]) V without materialising S×S.

    q, k, v: (B*H, S, D).  ``interpret=None`` auto-selects the Pallas
    interpreter off-TPU (tests on the CPU mesh) and the compiled kernel on
    TPU."""
    out, _ = _flash_fwd_rule(q, k, v, scale, causal, block_q, block_k,
                             interpret)
    return out


def _resolve(scale, d, interpret):
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return scale, interpret


def _flash_fwd_rule(q, k, v, scale, causal, block_q, block_k, interpret):
    scale, interpret = _resolve(scale, q.shape[-1], interpret)
    out = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return out, (q, k, v, out)


def _flash_bwd_rule(scale, causal, block_q, block_k, interpret, res, do):
    q, k, v, o = res
    scale, _ = _resolve(scale, q.shape[-1], interpret)
    bq = min(block_q, q.shape[1])
    return _dense_block_bwd(q, k, v, o, do, scale, causal, bq)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)
