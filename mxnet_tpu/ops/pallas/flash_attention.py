"""Flash attention Pallas kernels — the long-context hot path.

The reference's attention is two cuBLAS strided-batched matmuls with the
full (B*H, S, S) score matrix materialised (ref: src/operator/contrib/
transformer.cc).  On TPU that matrix is the HBM wall at long sequence; these
kernels compute softmax(QK^T)V blockwise with the online-softmax recurrence
(SURVEY §7.0.2 names this kernel).

v2 design (round-3: VERDICT weak #6):
- K/V are **streamed block-by-block through the grid** — the kernel never
  holds a whole (S, D) K or V in VMEM, so sequence length is bounded by HBM,
  not VMEM.  Grid (B·H, S/bq, S/bk); accumulators (acc, m, l) live in VMEM
  scratch carried across the k-dimension of the grid.
- The forward also emits the per-row log-sum-exp, and the **backward is two
  Pallas kernels** (dq, then dk/dv) using the standard recompute-from-lse
  formulation — O(S·D) memory end to end.
- **Attention-probability dropout runs inside the kernel**: a counter-based
  integer hash (SplitMix32 finaliser) of (head, q-pos, k-pos, seed) drawn
  identically in forward and backward, so no mask is ever materialised.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _uniform01(h_idx, q_pos, k_pos, seed):
    """Deterministic U[0,1) per (head, q, k) via a SplitMix32-style hash.
    Counter-based, so forward and backward regenerate the same draw without
    storing any mask.  (Statistical-quality RNG, not crypto — exactly what
    dropout needs.)"""
    x = (q_pos.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
         + k_pos.astype(jnp.uint32) * jnp.uint32(0x85EBCA6B)
         + jnp.uint32(h_idx) * jnp.uint32(0xC2B2AE35)
         + jnp.uint32(seed))
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return (x >> 8).astype(jnp.float32) * (1.0 / 16777216.0)


def _positions(bq, bk, qi, kj, block_q, block_k):
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return q_pos, k_pos


# ------------------------------------------------------------- forward ------
def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref,
                m_ref, l_ref, *, scale, causal, block_q, block_k, n_k,
                dropout):
    b = pl.program_id(0)
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale      # (bq, D)
        k = k_ref[0].astype(jnp.float32)              # (bk, D)
        v = v_ref[0].astype(jnp.float32)
        s = q @ k.T                                   # (bq, bk)
        q_pos, k_pos = _positions(s.shape[0], s.shape[1], qi, kj,
                                  block_q, block_k)
        if causal:
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)

        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        # l tracks the TRUE softmax normaliser (pre-dropout), so lse is exact
        l_new = l_prev * alpha + p.sum(axis=-1)
        if dropout > 0.0:
            keep = _uniform01(b, q_pos, k_pos, seed_ref[0]) >= dropout
            p = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - dropout))
        acc_ref[...] = acc_ref[...] * alpha[:, None] + p @ v
        m_ref[...] = m_new
        l_ref[...] = l_new

    if causal:
        # skip fully-masked future blocks: ~2x fewer matmuls at long S
        pl.when(kj * block_k <= qi * block_q + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(kj == n_k - 1)
    def _finish():
        l = l_ref[...]
        o_ref[0] = (acc_ref[...] / l[:, None]).astype(o_ref.dtype)
        lse_ref[0] = m_ref[...] + jnp.log(l)


def _flash_fwd(q, k, v, seed, scale, causal, block_q, block_k, interpret,
               dropout):
    bh, s, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    assert s % block_q == 0 and s % block_k == 0, (s, block_q, block_k)
    n_k = s // block_k
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, n_k=n_k, dropout=dropout)
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, s // block_q, n_k),
        in_specs=[
            pl.BlockSpec((1,), lambda b, i, j: (0,)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, s), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q,), jnp.float32),
        ],
        interpret=interpret,
    )(seed, q, k, v)
    return out, lse


# ------------------------------------------------------------ backward ------
def _recompute_p(q_ref, k_ref, lse_ref, b, qi, kj, scale, causal,
                 block_q, block_k):
    q = q_ref[0].astype(jnp.float32) * scale
    k = k_ref[0].astype(jnp.float32)
    s = q @ k.T
    q_pos, k_pos = _positions(s.shape[0], s.shape[1], qi, kj,
                              block_q, block_k)
    if causal:
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
    p = jnp.exp(s - lse_ref[0][:, None])   # true softmax probs (pre-dropout)
    return p, q_pos, k_pos


def _dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_acc, *, scale, causal, block_q, block_k, n_k,
               dropout):
    b = pl.program_id(0)
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def _compute():
        p, q_pos, k_pos = _recompute_p(q_ref, k_ref, lse_ref, b, qi, kj,
                                       scale, causal, block_q, block_k)
        do = do_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        dp = do @ v.T                                 # (bq, bk)
        if dropout > 0.0:
            keep = _uniform01(b, q_pos, k_pos, seed_ref[0]) >= dropout
            dp = jnp.where(keep, dp, 0.0) * (1.0 / (1.0 - dropout))
        ds = p * (dp - delta_ref[0][:, None])
        dq_acc[...] += (ds @ k_ref[0].astype(jnp.float32)) * scale

    if causal:
        pl.when(kj * block_k <= qi * block_q + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(kj == n_k - 1)
    def _finish():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal, block_q,
                block_k, n_q, dropout):
    b = pl.program_id(0)
    kj = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _compute():
        p, q_pos, k_pos = _recompute_p(q_ref, k_ref, lse_ref, b, qi, kj,
                                       scale, causal, block_q, block_k)
        do = do_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        if dropout > 0.0:
            keep = _uniform01(b, q_pos, k_pos, seed_ref[0]) >= dropout
            pd = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - dropout))
        else:
            pd = p
        dv_acc[...] += pd.T @ do
        dp = do @ v.T
        if dropout > 0.0:
            dp = jnp.where(keep, dp, 0.0) * (1.0 / (1.0 - dropout))
        ds = p * (dp - delta_ref[0][:, None])
        dk_acc[...] += (ds.T @ (q_ref[0].astype(jnp.float32))) * scale

    if causal:
        pl.when(kj * block_k <= qi * block_q + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(qi == n_q - 1)
    def _finish():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, seed, o, lse, do, scale, causal, block_q, block_k,
               interpret, dropout):
    bh, s, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    n_q, n_k = s // block_q, s // block_k
    delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32), axis=-1)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, n_k=n_k,
                          dropout=dropout),
        grid=(bh, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1,), lambda b, i, j: (0,)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)),
            pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(seed, q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, n_q=n_q,
                          dropout=dropout),
        grid=(bh, n_k, n_q),
        in_specs=[
            pl.BlockSpec((1,), lambda b, j, i: (0,)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, j, i: (b, i)),
            pl.BlockSpec((1, block_q), lambda b, j, i: (b, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(seed, q, k, v, do, lse, delta)
    return dq, dk, dv


# ----------------------------------------------------------- public api -----
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash_attention_core(q, k, v, seed, scale, causal, block_q, block_k,
                          interpret, dropout):
    out, _ = _flash_fwd_rule(q, k, v, seed, scale, causal, block_q, block_k,
                             interpret, dropout)
    return out


def flash_attention(q, k, v, scale=None, causal=False, block_q=128,
                    block_k=128, interpret=None, dropout=0.0, seed=None):
    """softmax(scale · Q Kᵀ [, causal]) V without materialising S×S.

    q, k, v: (B*H, S, D).  ``dropout`` applies attention-probability dropout
    inside the kernel (the mask is regenerated from a counter-based hash in
    forward AND backward — never stored).  ``seed`` may be a traced int32
    scalar so each training step draws a fresh mask without retracing.
    ``interpret=None`` auto-selects the Pallas interpreter off-TPU (CPU-mesh
    tests) and the compiled kernel on TPU."""
    if seed is None:
        seed = jnp.zeros((1,), jnp.int32)
    else:
        seed = jnp.asarray(seed, jnp.int32).reshape((1,))
    return _flash_attention_core(q, k, v, seed, scale, causal, block_q,
                                 block_k, interpret, dropout)


def _resolve(scale, d, interpret):
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return scale, interpret


def _flash_fwd_rule(q, k, v, seed, scale, causal, block_q, block_k,
                    interpret, dropout):
    scale, interpret = _resolve(scale, q.shape[-1], interpret)
    out, lse = _flash_fwd(q, k, v, seed, scale, causal, block_q, block_k,
                          interpret, float(dropout))
    return out, (q, k, v, seed, out, lse)


def _flash_bwd_rule(scale, causal, block_q, block_k, interpret, dropout,
                    res, do):
    q, k, v, seed, o, lse = res
    scale, interpret = _resolve(scale, q.shape[-1], interpret)
    dq, dk, dv = _flash_bwd(q, k, v, seed, o, lse, do, scale, causal,
                            block_q, block_k, interpret, float(dropout))
    return dq, dk, dv, None


_flash_attention_core.defvjp(_flash_fwd_rule, _flash_bwd_rule)
