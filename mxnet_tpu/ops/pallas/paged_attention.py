"""Ragged paged decode attention — Pallas TPU kernel.

One query token per decode slot attends over that slot's paged KV
context (PAPERS.md: *Ragged Paged Attention*, arXiv:2604.15464).  The
page table and per-slot lengths ride as **scalar-prefetch** operands
(``pltpu.PrefetchScalarGridSpec``), so the K/V block index maps resolve
each grid step's page id *before* the body runs: pages stream
HBM→VMEM one at a time, the kernel never materialises a slot's dense
``[max_ctx, H, D]`` context, and — the ragged part — a slot's grid
steps past its own length are skipped entirely (``pl.when``), so a
batch mixing 3-token and 3000-token sequences pays each slot only its
own pages.  Shapes are configuration constants (pool, table, slot
count), so every traffic mix runs this ONE program.

Accumulation is the online-softmax recurrence across a slot's pages
(same scheme as ``flash_attention.py``'s k-axis), carried in VMEM
scratch across the page axis of the grid.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30


def _kernel(tables_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
            acc_ref, m_ref, l_ref, *, page_size, pages_per_seq):
    s = pl.program_id(0)          # decode slot
    j = pl.program_id(1)          # page index within the slot's table
    length = len_ref[s]

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(j * page_size < length)
    def _page():
        q = q_ref[0].astype(jnp.float32)            # (H, D)
        k = k_ref[0].astype(jnp.float32)            # (page, H, D)
        v = v_ref[0].astype(jnp.float32)
        heads = q.shape[0]
        scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
        qs = q * scale
        # Mosaic only lowers 2D dots, so the batched ``hd,phd->hp``
        # einsum is unrolled into one (1,D)·(page,D) contraction per
        # head (H is a small compile-time constant)
        sc = jnp.concatenate([
            jax.lax.dot_general(
                qs[h][None, :], k[:, h, :],
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            for h in range(heads)
        ], axis=0)                                   # (H, page)
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, sc.shape, 1)                  # (H, page)
        sc = jnp.where(pos < length, sc, _NEG)
        m_prev, l_prev = m_ref[...], l_ref[...]
        m_new = jnp.maximum(m_prev, sc.max(axis=-1))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(sc - m_new[:, None])
        l_ref[...] = l_prev * alpha + p.sum(axis=-1)
        pv = jnp.concatenate([
            jax.lax.dot_general(
                p[h][None, :], v[:, h, :],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            for h in range(heads)
        ], axis=0)                                   # (H, D)
        acc_ref[...] = acc_ref[...] * alpha[:, None] + pv
        m_ref[...] = m_new

    @pl.when(j == pages_per_seq - 1)
    def _finish():
        l = l_ref[...]
        # an inactive slot (length 0) never ran a page: l stays 0 and the
        # output row is zeros, mirroring the jnp path's "garbage, never
        # NaN" contract
        norm = jnp.where(l > 0.0, l, 1.0)
        o_ref[0] = (acc_ref[...] / norm[:, None]).astype(o_ref.dtype)


def paged_decode_attention_pallas(q, k_pages, v_pages, page_tables,
                                  lengths, interpret=None):
    """Pallas path of ``ops.paged_attention.paged_decode_attention``
    (same argument contract).  ``interpret=None`` auto-selects the
    Pallas interpreter off-TPU so parity tests run anywhere."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n_pages, page_size, heads, head_dim = k_pages.shape
    slots, pages_per_seq = page_tables.shape
    kernel = functools.partial(_kernel, page_size=page_size,
                               pages_per_seq=pages_per_seq)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(slots, pages_per_seq),
        in_specs=[
            pl.BlockSpec((1, heads, head_dim), lambda s, j, t, ln: (s, 0, 0)),
            # the scalar-prefetched page table drives the DMA: grid step
            # (s, j) pulls page t[s, j] of the pool into VMEM
            pl.BlockSpec((1, page_size, heads, head_dim),
                         lambda s, j, t, ln: (t[s, j], 0, 0, 0)),
            pl.BlockSpec((1, page_size, heads, head_dim),
                         lambda s, j, t, ln: (t[s, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, heads, head_dim),
                               lambda s, j, t, ln: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((heads, head_dim), jnp.float32),
            pltpu.VMEM((heads,), jnp.float32),
            pltpu.VMEM((heads,), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((slots, heads, head_dim), q.dtype),
        interpret=interpret,
    )(page_tables, lengths, q, k_pages, v_pages)
