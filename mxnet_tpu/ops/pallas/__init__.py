"""Pallas TPU kernels for ops XLA won't fuse well (SURVEY.md §7.0.2)."""
from .flash_attention import flash_attention

__all__ = ["flash_attention"]
