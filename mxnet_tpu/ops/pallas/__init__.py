"""Pallas TPU kernels for ops XLA won't fuse well (SURVEY.md §7.0.2)."""
from .flash_attention import flash_attention
from .paged_attention import paged_decode_attention_pallas

__all__ = ["flash_attention", "paged_decode_attention_pallas"]
