"""Fused norm→relu→conv Pallas kernel — the ResNet HBM-floor breaker.

PERF.md's round-3 accounting: the ResNet-50 step is HBM-bound at 44 GB,
of which ~12 GB is BN/relu/residual kLoop fusions.  XLA cannot fuse
elementwise producers INTO a convolution custom-call, so every
``relu(bn(y) [+res])`` materialises a full activation tensor that the next
conv immediately re-reads.  These kernels apply the normalize(+residual)
+relu prologue ON LOAD inside the conv itself — the normalized activation
never exists in HBM, in forward OR backward (both backward kernels
recompute the prologue from the raw input, flash-attention style).

Scope (the ResNet residual-block hot path, SURVEY §7.0.2):
  * NHWC, HWIO weights, kernel 1×1 or 3×3, stride 1 or 2, SAME
    padding, groups=1.  The 7×7 stem stays on the XLA conv.
  * ``scale``/``shift`` are per-channel affine terms ALREADY folded from
    BN statistics (gamma/sqrt(var+eps), beta-mean*scale).  They stay in
    the autograd graph, so the batch-statistics paths of BN gradients
    flow through d(scale)/d(shift) automatically.

Why block-INTERNAL fusion only (analysis, round 4): folding a block's
tail (bn3+residual+relu) into the NEXT block's 1×1 looks tempting, but
ResNet v1 reuses that tail output as the next block's residual — it must
materialise regardless, and the folded prologue would then read BOTH the
wide y3 (C channels) and the previous activation instead of one C/4
tensor, i.e. MORE traffic.  The winnable reads are exactly the two
block-internal ones (bn1+relu into the 3×3, bn2+relu into the closing
1×1), which is what this kernel family covers.

ref: src/operator/nn/convolution.cc + batch_norm.cc — the reference runs
these as separate cuDNN calls with the same materialisation; no
counterpart kernel exists there.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["norm_relu_conv", "norm_relu_conv_reference", "supports"]


def supports(kh, kw, stride, groups=1):
    """True when the fused kernel covers this conv configuration."""
    return (kh, kw) in ((1, 1), (3, 3)) and stride in (1, 2) and groups == 1


def _out_dim(n, stride):
    """SAME-padding output extent."""
    return -(-n // stride)


def _same_pads(n, k, stride):
    """(pad_lo, pad_hi) of SAME padding along one spatial dim."""
    total = max((_out_dim(n, stride) - 1) * stride + k - n, 0)
    return total // 2, total - total // 2


def _prologue(x, scale, shift, res, relu):
    """X = relu(x*scale + shift [+ res]) in f32 — shared by all 3 kernels."""
    pre = x.astype(jnp.float32) * scale + shift
    if res is not None:
        pre = pre + res.astype(jnp.float32)
    return jnp.maximum(pre, 0.0) if relu else pre


# ------------------------------------------------------------- forward ------
def _taps(Xp, h, w_dim, ci, k, stride):
    """Yield (ky, kx, patch) with patch = the (Ho, Wo, Ci) strided window
    of the padded input under tap (ky, kx) — the 9 shifted views whose
    matmuls sum to the convolution.

    Mosaic rejects strided vector slices (`vector.extract_strided_slice`
    requires unit strides — see TPU_FUSED_COMPILE_r05.md), so for
    stride > 1 the decimation is a contiguous slice + reshape + static
    index, all of which lower to unit-stride ops.  Callers must pad Xp
    with `stride - 1` extra rows/cols (see ``_pad_guard``) so the
    contiguous slice extent ``stride * ho`` stays in bounds."""
    ho, wo = _out_dim(h, stride), _out_dim(w_dim, stride)
    for ky in range(k):
        for kx in range(k):
            if stride == 1:
                patch = lax.slice(Xp, (ky, kx, 0), (ky + ho, kx + wo, ci))
            else:
                full = lax.slice(Xp, (ky, kx, 0),
                                 (ky + stride * ho, kx + stride * wo, ci))
                patch = full.reshape(ho, stride, wo, stride,
                                     ci)[:, 0, :, 0, :]
            yield ky, kx, patch


def _pad_guard(stride):
    """Extra high-side padding so stride>1 taps can slice contiguously."""
    return stride - 1


def _fwd_kernel(x_ref, scale_ref, shift_ref, w_ref, *rest, k, stride, relu,
                has_res):
    if has_res:
        r_ref, o_ref = rest
    else:
        (o_ref,) = rest
        r_ref = None
    h, w_dim, ci = x_ref.shape[1], x_ref.shape[2], x_ref.shape[3]
    ho, wo = _out_dim(h, stride), _out_dim(w_dim, stride)
    X = _prologue(x_ref[0], scale_ref[0], shift_ref[0],
                  r_ref[0] if has_res else None, relu)
    if k == 1 and stride == 1:
        acc = X.reshape(h * w_dim, ci) @ w_ref[0, 0].astype(jnp.float32)
    else:
        py, _py2 = _same_pads(h, k, stride)
        px, _px2 = _same_pads(w_dim, k, stride)
        g = _pad_guard(stride)
        Xp = jnp.pad(X, ((py, _py2 + g), (px, _px2 + g), (0, 0)))
        acc = None
        for ky, kx, patch in _taps(Xp, h, w_dim, ci, k, stride):
            term = patch.reshape(ho * wo, ci) @ \
                w_ref[ky, kx].astype(jnp.float32)
            acc = term if acc is None else acc + term
    o_ref[0] = acc.reshape(ho, wo, -1).astype(o_ref.dtype)


def _pick_block_co(co, want):
    """Largest divisor of co that is <= want (grid tiles must cover co
    exactly — a non-dividing block would leave tail channels unwritten)."""
    for d in range(min(want, co), 0, -1):
        if co % d == 0:
            return d
    return 1


def _fwd(x, scale, shift, w, res, relu, stride, block_co, interpret):
    n, h, wd, ci = x.shape
    k, _, _, co = w.shape
    ho, wo = _out_dim(h, stride), _out_dim(wd, stride)
    block_co = _pick_block_co(co, block_co)
    inputs = [x, scale.reshape(1, ci), shift.reshape(1, ci), w]
    in_specs = [
        pl.BlockSpec((1, h, wd, ci), lambda nb, cb: (nb, 0, 0, 0)),
        pl.BlockSpec((1, ci), lambda nb, cb: (0, 0)),
        pl.BlockSpec((1, ci), lambda nb, cb: (0, 0)),
        pl.BlockSpec((k, k, ci, block_co), lambda nb, cb: (0, 0, 0, cb)),
    ]
    if res is not None:
        inputs.append(res)
        in_specs.append(
            pl.BlockSpec((1, h, wd, ci), lambda nb, cb: (nb, 0, 0, 0)))
    return pl.pallas_call(
        functools.partial(_fwd_kernel, k=k, stride=stride, relu=relu,
                          has_res=res is not None),
        grid=(n, co // block_co),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, ho, wo, block_co),
                               lambda nb, cb: (nb, 0, 0, cb)),
        out_shape=jax.ShapeDtypeStruct((n, ho, wo, co), x.dtype),
        interpret=interpret,
    )(*inputs)


# ---------------------------------------------------------- backward dX -----
def _dx_kernel(x_ref, scale_ref, shift_ref, w_ref, do_ref, *rest, k, stride,
               relu, has_res):
    """dx (+dres) for one sample; also per-sample dscale/dshift partials.

    G = dO ⋆ flip(W) (the full correlation); the relu mask and the affine
    chain rule are the epilogue: dx = G·mask·scale, dres = G·mask,
    dscale_n = Σ G·mask·x, dshift_n = Σ G·mask.
    """
    if has_res:
        r_ref, dx_ref, dres_ref, dsc_ref, dsh_ref = rest
    else:
        dx_ref, dsc_ref, dsh_ref = rest
        r_ref = dres_ref = None
    h, wd, ci = x_ref.shape[1], x_ref.shape[2], x_ref.shape[3]
    co = do_ref.shape[3]
    ho, wo = _out_dim(h, stride), _out_dim(wd, stride)
    do = do_ref[0].astype(jnp.float32)
    if k == 1 and stride == 1:
        G = do.reshape(h * wd, co) @ \
            w_ref[0, 0].astype(jnp.float32).T
    else:
        if stride == 1:
            dod = do
        else:
            # transposed conv: dilate dO by the stride (zeros between
            # output positions), then full-correlate with flipped taps.
            # Strided scatter (`.at[::s, ::s]`) doesn't lower on Mosaic;
            # interleave zeros via pad + reshape (unit-stride ops), then
            # trim the trailing `stride - 1` zeros to the dilated extent.
            dod = jnp.pad(do.reshape(ho, 1, wo, 1, co),
                          ((0, 0), (0, stride - 1),
                           (0, 0), (0, stride - 1), (0, 0)))
            dod = dod.reshape(stride * ho, stride * wo, co)
            dod = lax.slice(dod, (0, 0, 0),
                            (stride * (ho - 1) + 1,
                             stride * (wo - 1) + 1, co))
        py, _ = _same_pads(h, k, stride)
        px, _ = _same_pads(wd, k, stride)
        ply = k - 1 - py
        plx = k - 1 - px
        pry = h + k - 1 - dod.shape[0] - ply
        prx = wd + k - 1 - dod.shape[1] - plx
        dop = jnp.pad(dod, ((ply, pry), (plx, prx), (0, 0)))
        G = None
        for ky in range(k):
            for kx in range(k):
                patch = lax.slice(dop, (ky, kx, 0), (ky + h, kx + wd, co))
                # correlate with the 180°-flipped tap
                term = patch.reshape(h * wd, co) @ \
                    w_ref[k - 1 - ky, k - 1 - kx].astype(jnp.float32).T
                G = term if G is None else G + term
    G = G.reshape(h, wd, ci)
    x = x_ref[0].astype(jnp.float32)
    scale = scale_ref[0]
    if relu:
        pre = x * scale + shift_ref[0]
        if has_res:
            pre = pre + r_ref[0].astype(jnp.float32)
        Gm = jnp.where(pre > 0.0, G, 0.0)
    else:
        Gm = G
    dx_ref[0] = (Gm * scale).astype(dx_ref.dtype)
    if has_res:
        dres_ref[0] = Gm.astype(dres_ref.dtype)
    # rank-3 (N, 1, Ci) partials: a (1, Ci) block over an (N, Ci) array
    # violates Mosaic's last-two-dims rule (1 ∤ 8 and 1 != N); the extra
    # unit axis makes the block's trailing dims equal the array's.
    dsc_ref[0, 0] = jnp.sum(Gm * x, axis=(0, 1))
    dsh_ref[0, 0] = jnp.sum(Gm, axis=(0, 1))


def _dx(x, scale, shift, w, res, do, relu, stride, interpret):
    n, h, wd, ci = x.shape
    k = w.shape[0]
    has_res = res is not None
    inputs = [x, scale.reshape(1, ci), shift.reshape(1, ci), w, do]
    in_specs = [
        pl.BlockSpec((1, h, wd, ci), lambda nb: (nb, 0, 0, 0)),
        pl.BlockSpec((1, ci), lambda nb: (0, 0)),
        pl.BlockSpec((1, ci), lambda nb: (0, 0)),
        pl.BlockSpec(w.shape, lambda nb: (0, 0, 0, 0)),
        pl.BlockSpec((1, do.shape[1], do.shape[2], do.shape[3]),
                     lambda nb: (nb, 0, 0, 0)),
    ]
    if has_res:
        inputs.append(res)
        in_specs.append(
            pl.BlockSpec((1, h, wd, ci), lambda nb: (nb, 0, 0, 0)))
    out_specs = [pl.BlockSpec((1, h, wd, ci), lambda nb: (nb, 0, 0, 0))]
    out_shape = [jax.ShapeDtypeStruct(x.shape, x.dtype)]
    if has_res:
        out_specs.append(
            pl.BlockSpec((1, h, wd, ci), lambda nb: (nb, 0, 0, 0)))
        out_shape.append(jax.ShapeDtypeStruct(x.shape, res.dtype))
    out_specs += [pl.BlockSpec((1, 1, ci), lambda nb: (nb, 0, 0)),
                  pl.BlockSpec((1, 1, ci), lambda nb: (nb, 0, 0))]
    out_shape += [jax.ShapeDtypeStruct((n, 1, ci), jnp.float32),
                  jax.ShapeDtypeStruct((n, 1, ci), jnp.float32)]
    outs = pl.pallas_call(
        functools.partial(_dx_kernel, k=k, stride=stride, relu=relu,
                          has_res=has_res),
        grid=(n,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(*inputs)
    if has_res:
        dx, dres, dsc, dsh = outs
    else:
        dx, dsc, dsh = outs
        dres = None
    # per-sample partials -> channel totals (tiny (N, 1, Ci) reduce in XLA)
    return dx, dres, dsc.sum(axis=(0, 1)), dsh.sum(axis=(0, 1))


# ---------------------------------------------------------- backward dW -----
def _dw_kernel(x_ref, scale_ref, shift_ref, do_ref, *rest, k, stride,
               relu, has_res, n):
    """dW accumulated over samples: grid (co_tiles, N), acc in VMEM."""
    if has_res:
        r_ref, dw_ref, acc_ref = rest
    else:
        dw_ref, acc_ref = rest
        r_ref = None
    nb = pl.program_id(1)

    @pl.when(nb == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    h, wd, ci = x_ref.shape[1], x_ref.shape[2], x_ref.shape[3]
    tco = do_ref.shape[3]
    ho, wo = _out_dim(h, stride), _out_dim(wd, stride)
    X = _prologue(x_ref[0], scale_ref[0], shift_ref[0],
                  r_ref[0] if has_res else None, relu)
    do = do_ref[0].astype(jnp.float32).reshape(ho * wo, tco)
    if k == 1 and stride == 1:
        acc_ref[0, 0] += X.reshape(h * wd, ci).T @ do
    else:
        py, py2 = _same_pads(h, k, stride)
        px, px2 = _same_pads(wd, k, stride)
        g = _pad_guard(stride)
        Xp = jnp.pad(X, ((py, py2 + g), (px, px2 + g), (0, 0)))
        for ky, kx, patch in _taps(Xp, h, wd, ci, k, stride):
            acc_ref[ky, kx] += patch.reshape(ho * wo, ci).T @ do

    @pl.when(nb == n - 1)
    def _finish():
        dw_ref[...] = acc_ref[...].astype(dw_ref.dtype)


def _dw(x, scale, shift, res, do, k, co, relu, stride, block_co,
        interpret):
    n, h, wd, ci = x.shape
    block_co = _pick_block_co(co, block_co)
    has_res = res is not None
    inputs = [x, scale.reshape(1, ci), shift.reshape(1, ci), do]
    in_specs = [
        pl.BlockSpec((1, h, wd, ci), lambda cb, nb: (nb, 0, 0, 0)),
        pl.BlockSpec((1, ci), lambda cb, nb: (0, 0)),
        pl.BlockSpec((1, ci), lambda cb, nb: (0, 0)),
        pl.BlockSpec((1, do.shape[1], do.shape[2], block_co),
                     lambda cb, nb: (nb, 0, 0, cb)),
    ]
    if has_res:
        inputs.append(res)
        in_specs.append(
            pl.BlockSpec((1, h, wd, ci), lambda cb, nb: (nb, 0, 0, 0)))
    return pl.pallas_call(
        functools.partial(_dw_kernel, k=k, stride=stride, relu=relu,
                          has_res=has_res, n=n),
        grid=(co // block_co, n),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((k, k, ci, block_co),
                               lambda cb, nb: (0, 0, 0, cb)),
        out_shape=jax.ShapeDtypeStruct((k, k, ci, co), jnp.float32),
        scratch_shapes=[pltpu.VMEM((k, k, ci, block_co), jnp.float32)],
        interpret=interpret,
    )(*inputs)


# ----------------------------------------------------------- public api -----
def norm_relu_conv_reference(x, scale, shift, w, residual=None, relu=True,
                             stride=1):
    """XLA twin of the fused kernel (test oracle + fallback path)."""
    pre = x.astype(jnp.float32) * scale + shift
    if residual is not None:
        pre = pre + residual.astype(jnp.float32)
    X = jnp.maximum(pre, 0.0) if relu else pre
    out = lax.conv_general_dilated(
        X.astype(x.dtype), w, (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32)
    return out.astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _core(x, scale, shift, w, relu, stride, block_co, interpret):
    out, _ = _fwd_rule(x, scale, shift, w, relu, stride, block_co,
                       interpret)
    return out


def _fwd_rule(x, scale, shift, w, relu, stride, block_co, interpret):
    out = _fwd(x, scale.astype(jnp.float32), shift.astype(jnp.float32), w,
               None, relu, stride, block_co, interpret)
    return out, (x, scale, shift, w)


def _bwd_rule(relu, stride, block_co, interpret, resd, do):
    x, scale, shift, w = resd
    s32 = scale.astype(jnp.float32)
    h32 = shift.astype(jnp.float32)
    dx, _, dsc, dsh = _dx(x, s32, h32, w, None, do, relu, stride, interpret)
    dw = _dw(x, s32, h32, None, do, w.shape[0], w.shape[3], relu, stride,
             block_co, interpret)
    return (dx, dsc.astype(scale.dtype), dsh.astype(shift.dtype),
            dw.astype(w.dtype))


_core.defvjp(_fwd_rule, _bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _core_res(x, scale, shift, w, residual, relu, stride, block_co,
              interpret):
    out, _ = _fwd_res_rule(x, scale, shift, w, residual, relu, stride,
                           block_co, interpret)
    return out


def _fwd_res_rule(x, scale, shift, w, residual, relu, stride, block_co,
                  interpret):
    out = _fwd(x, scale.astype(jnp.float32), shift.astype(jnp.float32), w,
               residual, relu, stride, block_co, interpret)
    return out, (x, scale, shift, w, residual)


def _bwd_res_rule(relu, stride, block_co, interpret, resd, do):
    x, scale, shift, w, residual = resd
    s32 = scale.astype(jnp.float32)
    h32 = shift.astype(jnp.float32)
    dx, dres, dsc, dsh = _dx(x, s32, h32, w, residual, do, relu, stride,
                             interpret)
    dw = _dw(x, s32, h32, residual, do, w.shape[0], w.shape[3], relu,
             stride, block_co, interpret)
    return (dx, dsc.astype(scale.dtype), dsh.astype(shift.dtype),
            dw.astype(w.dtype), dres)


_core_res.defvjp(_fwd_res_rule, _bwd_res_rule)


def norm_relu_conv(x, scale, shift, w, residual=None, relu=True, stride=1,
                   block_co=128, interpret=None):
    """conv(relu(x·scale + shift [+ residual]), w) without materialising
    the normalized activation (forward or backward).

    x: (N, H, W, Ci) raw pre-norm activations; scale/shift: (Ci,) affine
    folded from BN stats (keep them in the traced graph so stat gradients
    flow); w: (k, k, Ci, Co) HWIO with k in {1, 3}; stride 1 or 2, SAME.
    ``interpret=None`` auto-selects the Pallas interpreter off-TPU.
    """
    k = w.shape[0]
    if not supports(k, w.shape[1], stride):
        raise ValueError(f"fused kernel supports 1x1/3x3 stride 1/2; got "
                         f"{w.shape[:2]} stride {stride}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if residual is None:
        return _core(x, scale, shift, w, relu, stride, block_co, interpret)
    return _core_res(x, scale, shift, w, residual, relu, stride, block_co,
                     interpret)
