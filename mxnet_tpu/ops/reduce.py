"""Reduction / sorting / cumulative ops.

Re-emission of (ref: src/operator/tensor/broadcast_reduce_op*.{h,cc,cu},
ordering_op*.{h,cc,cu}).  XLA lowers these onto the VPU/MXU natively; the
reference's hand-tiled reduce kernels are unnecessary.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


def _norm_axis(axis):
    if axis is None or isinstance(axis, (int, tuple)):
        return axis
    if isinstance(axis, list):
        return tuple(axis)
    return int(axis)


def _reduce(fn):
    def op(x, axis=None, keepdims=False, exclude=False):
        axis = _norm_axis(axis)
        if exclude and axis is not None:
            ax = (axis,) if isinstance(axis, int) else tuple(axis)
            axis = tuple(i for i in range(x.ndim) if i not in ax and i - x.ndim not in ax)
        return fn(x, axis=axis, keepdims=keepdims)

    return op


register_op("sum", _reduce(jnp.sum), aliases=("sum_axis",))
register_op("mean", _reduce(jnp.mean))
register_op("prod", _reduce(jnp.prod))
register_op("max", _reduce(jnp.max), aliases=("max_axis",))
register_op("min", _reduce(jnp.min), aliases=("min_axis",))
register_op("nansum", _reduce(jnp.nansum))
register_op("nanprod", _reduce(jnp.nanprod))


@register_op("norm")
def _norm(x, ord=2, axis=None, keepdims=False):
    axis = _norm_axis(axis)
    if ord == 1:
        return jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdims))


@register_op("argmax")
def _argmax(x, axis=None, keepdims=False):
    out = jnp.argmax(x, axis=axis, keepdims=keepdims)
    return out.astype(jnp.float32)  # reference returns float indices


@register_op("argmin")
def _argmin(x, axis=None, keepdims=False):
    return jnp.argmin(x, axis=axis, keepdims=keepdims).astype(jnp.float32)


@register_op("argmax_channel")
def _argmax_channel(x):
    return jnp.argmax(x, axis=-1).astype(jnp.float32)


@register_op("topk")
def _topk(x, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    """ref: src/operator/tensor/ordering_op-inl.h — TopKImpl."""
    from ..base import dtype_np

    xm = jnp.moveaxis(x, axis, -1)
    neg = xm if is_ascend else -xm
    vals, idx = jax.lax.top_k(-neg, k) if is_ascend else jax.lax.top_k(xm, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis)
    idxc = idx.astype(dtype_np(dtype))
    if ret_typ == "indices":
        return idxc
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return vals, idxc
    if ret_typ == "mask":
        xm_shape = jnp.moveaxis(x, axis, -1).shape
        mask = jnp.zeros(xm_shape, dtype=x.dtype)
        mask = jax.vmap(lambda m, i: m.at[i].set(1), in_axes=(0, 0))(
            mask.reshape(-1, xm_shape[-1]), idx.reshape(-1, idx.shape[-1])
        ).reshape(xm_shape)
        return jnp.moveaxis(mask, -1, axis)
    raise ValueError(f"unknown ret_typ {ret_typ}")


@register_op("sort")
def _sort(x, axis=-1, is_ascend=True):
    out = jnp.sort(x, axis=axis)
    return out if is_ascend else jnp.flip(out, axis=axis)


@register_op("argsort")
def _argsort(x, axis=-1, is_ascend=True, dtype="float32"):
    from ..base import dtype_np

    idx = jnp.argsort(x, axis=axis)
    if not is_ascend:
        idx = jnp.flip(idx, axis=axis)
    return idx.astype(dtype_np(dtype))


@register_op("cumsum")
def _cumsum(x, axis=None, dtype=None):
    from ..base import dtype_np

    if axis is None:
        x = x.reshape(-1)
        axis = 0
    out = jnp.cumsum(x, axis=axis)
    return out.astype(dtype_np(dtype)) if dtype is not None else out


@register_op("cumprod")
def _cumprod(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jnp.cumprod(x, axis=axis)


@register_op("L2Normalization", aliases=("l2_normalization",))
def _l2norm(x, eps=1e-10, mode="instance"):
    """ref: src/operator/l2_normalization-inl.h."""
    if mode == "instance":
        axes = tuple(range(1, x.ndim))
    elif mode == "channel":
        axes = (1,)
    else:  # spatial
        axes = tuple(range(2, x.ndim))
    denom = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True) + eps)
    return x / denom
