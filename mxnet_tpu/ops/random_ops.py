"""Random sampling operators, registered in the op registry.

ref: src/operator/random/sample_op.cc — the reference registers its sampler
family (`_random_uniform`, `_random_normal`, ...) as first-class NNVM ops so
every frontend (Python, C API via MXImperativeInvokeEx, Scala, ...) draws
through one dispatch path.  Here the same names are registry ops over
`jax.random`: the registry's `needs_rng` machinery threads a fresh traced
PRNG key into the jitted closure (see ops/registry.py::compiled), so samples
are reproducible under `mx.random.seed` and never constant-folded by XLA.

`mx.nd.random.uniform` (module-style API) and `mx.nd.uniform` (generated op
wrapper, matching the reference's `mx.nd.uniform`) both exist; this module
provides the latter and the C ABI's `mxtpu_invoke("_random_uniform", ...)`.

The `_sample_*` variants (ref: src/operator/random/multisample_op.cc) draw
per-row: parameter arrays of shape (B,) produce output (B, *shape).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op
from .. import random as _random
from ..base import dtype_np


def _norm_shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s) for s in shape)


@register_op("_random_uniform", aliases=("uniform", "random_uniform"),
             needs_rng=True)
def _random_uniform(low=0.0, high=1.0, shape=(1,), dtype="float32", ctx=None):
    """ref: sample_op.cc — _random_uniform (SampleUniform)."""
    return jax.random.uniform(_random.next_key(), _norm_shape(shape),
                              dtype_np(dtype), minval=low, maxval=high)


@register_op("_random_normal", aliases=("normal", "random_normal"),
             needs_rng=True)
def _random_normal(loc=0.0, scale=1.0, shape=(1,), dtype="float32", ctx=None):
    """ref: sample_op.cc — _random_normal (SampleNormal)."""
    dt = dtype_np(dtype)
    return loc + scale * jax.random.normal(_random.next_key(),
                                           _norm_shape(shape), dt)


@register_op("_random_gamma", aliases=("random_gamma",), needs_rng=True)
def _random_gamma(alpha=1.0, beta=1.0, shape=(1,), dtype="float32", ctx=None):
    """ref: sample_op.cc — _random_gamma; beta is the SCALE parameter
    (matching the reference's alpha/beta = shape/scale convention)."""
    dt = dtype_np(dtype)
    return beta * jax.random.gamma(_random.next_key(), alpha,
                                   _norm_shape(shape), dt)


@register_op("_random_exponential", aliases=("random_exponential",),
             needs_rng=True)
def _random_exponential(lam=1.0, shape=(1,), dtype="float32", ctx=None):
    """ref: sample_op.cc — _random_exponential (rate parameter lam)."""
    dt = dtype_np(dtype)
    return jax.random.exponential(_random.next_key(),
                                  _norm_shape(shape), dt) / lam


@register_op("_random_poisson", aliases=("random_poisson",), needs_rng=True)
def _random_poisson(lam=1.0, shape=(1,), dtype="float32", ctx=None):
    """ref: sample_op.cc — _random_poisson.  Counts are produced in the
    requested dtype (the reference defaults to float32 too)."""
    out = jax.random.poisson(_random.next_key(), lam, _norm_shape(shape))
    return out.astype(dtype_np(dtype))


@register_op("_random_negative_binomial",
             aliases=("random_negative_binomial",), needs_rng=True)
def _random_negative_binomial(k=1, p=0.5, shape=(1,), dtype="float32",
                              ctx=None):
    """ref: sample_op.cc — _random_negative_binomial: failures before the
    k-th success at success probability p.  Drawn as the standard
    gamma-Poisson mixture: lam ~ Gamma(k, (1-p)/p), out ~ Poisson(lam)."""
    kg, kp = jax.random.split(_random.next_key())
    shp = _norm_shape(shape)
    lam = jax.random.gamma(kg, float(k), shp) * ((1.0 - p) / p)
    return jax.random.poisson(kp, lam, shp).astype(dtype_np(dtype))


@register_op("_random_generalized_negative_binomial",
             aliases=("random_generalized_negative_binomial",),
             needs_rng=True)
def _random_generalized_negative_binomial(mu=1.0, alpha=1.0, shape=(1,),
                                          dtype="float32", ctx=None):
    """ref: sample_op.cc — the (mu, alpha) mean/dispersion parameterisation:
    Gamma(1/alpha, mu*alpha) mixed through Poisson."""
    kg, kp = jax.random.split(_random.next_key())
    shp = _norm_shape(shape)
    lam = jax.random.gamma(kg, 1.0 / alpha, shp) * (mu * alpha)
    return jax.random.poisson(kp, lam, shp).astype(dtype_np(dtype))


@register_op("_random_randint", aliases=("random_randint", "randint"),
             needs_rng=True)
def _random_randint(low=0, high=2, shape=(1,), dtype="int32", ctx=None):
    """ref: sample_op.cc — _random_randint over [low, high)."""
    return jax.random.randint(_random.next_key(), _norm_shape(shape),
                              int(low), int(high), dtype_np(dtype))


# ---- per-row parameterised sampling (ref: multisample_op.cc) --------------

def _rows(param, shape):
    """Broadcast a (B,)-shaped parameter against per-row draw shape."""
    extra = _norm_shape(shape)
    return param.reshape(param.shape + (1,) * len(extra)), extra


@register_op("_sample_uniform", aliases=("sample_uniform",), needs_rng=True)
def _sample_uniform(low, high, shape=(), dtype="float32"):
    """ref: multisample_op.cc — _sample_uniform: low/high of shape (B,)
    produce (B, *shape) draws, row i from [low[i], high[i])."""
    lo, extra = _rows(low, shape)
    hi, _ = _rows(high, shape)
    u = jax.random.uniform(_random.next_key(), low.shape + extra,
                           dtype_np(dtype))
    return lo + u * (hi - lo)


@register_op("_sample_normal", aliases=("sample_normal",), needs_rng=True)
def _sample_normal(mu, sigma, shape=(), dtype="float32"):
    """ref: multisample_op.cc — _sample_normal."""
    m, extra = _rows(mu, shape)
    s, _ = _rows(sigma, shape)
    z = jax.random.normal(_random.next_key(), mu.shape + extra,
                          dtype_np(dtype))
    return m + s * z


@register_op("_sample_gamma", aliases=("sample_gamma",), needs_rng=True)
def _sample_gamma(alpha, beta, shape=(), dtype="float32"):
    """ref: multisample_op.cc — _sample_gamma (alpha shape, beta scale)."""
    a, extra = _rows(alpha, shape)
    b, _ = _rows(beta, shape)
    g = jax.random.gamma(_random.next_key(), a.astype(dtype_np(dtype)),
                         alpha.shape + extra)
    return b * g


@register_op("_sample_exponential", aliases=("sample_exponential",),
             needs_rng=True)
def _sample_exponential(lam, shape=(), dtype="float32"):
    """ref: multisample_op.cc — _sample_exponential."""
    l, extra = _rows(lam, shape)
    e = jax.random.exponential(_random.next_key(), lam.shape + extra,
                               dtype_np(dtype))
    return e / l


@register_op("_sample_poisson", aliases=("sample_poisson",), needs_rng=True)
def _sample_poisson(lam, shape=(), dtype="float32"):
    """ref: multisample_op.cc — _sample_poisson."""
    l, extra = _rows(lam, shape)
    out = jax.random.poisson(_random.next_key(),
                             jnp.broadcast_to(l, lam.shape + extra),
                             lam.shape + extra)
    return out.astype(dtype_np(dtype))


@register_op("_sample_multinomial", aliases=("sample_multinomial",),
             needs_rng=True)
def _sample_multinomial(data, shape=None, get_prob=False, dtype="int32"):
    """ref: src/operator/random/sample_multinomial_op.cc — categorical draws
    from probability rows (..., K).  Output is batch_shape + shape (the
    reference's per-distribution draw shape); the UNSPECIFIED default is a
    single draw squeezed to batch_shape (the reference's shape=_Null), while
    an explicit shape=1 keeps the trailing axis: batch_shape + (1,).
    get_prob=True additionally returns the log-prob of each draw (the
    REINFORCE helper, matching the reference's two-output form).

    `mx.nd.random.multinomial` is this op (one implementation; the module
    wrapper delegates here)."""
    if shape is None or shape == ():
        extra = ()
    elif isinstance(shape, int):
        extra = (shape,)
    else:
        extra = tuple(int(s) for s in shape)
    n = 1
    for s in extra:
        n *= s
    batch = data.shape[:-1]
    logp = jnp.log(jnp.maximum(data, 1e-30))
    idx = jax.random.categorical(_random.next_key(), logp, axis=-1,
                                 shape=(n,) + batch)
    idx = jnp.moveaxis(idx, 0, -1)              # batch + (n,)
    out = idx.reshape(batch + extra).astype(dtype_np(dtype))
    if get_prob:
        lp = jnp.take_along_axis(logp, idx, axis=-1)
        return out, lp.reshape(batch + extra).astype(jnp.float32)
    return out


@register_op("_shuffle", aliases=("shuffle",), needs_rng=True)
def _shuffle(data):
    """ref: src/operator/random/shuffle_op.cc — permute along axis 0."""
    return jax.random.permutation(_random.next_key(), data, axis=0)
