"""Attention ops — the BERT hot path.

ref: src/operator/contrib/transformer.{cc,cu} —
``_contrib_interleaved_matmul_selfatt_qk`` / ``_contrib_interleaved_matmul_selfatt_valatt``
(cuBLAS strided-batched matmuls over head-interleaved QKV projections).
TPU-native: the same interleaved layout (seq, batch, heads*3*head_dim) feeds
lax.dot_general batched matmuls the MXU eats directly; a fused
``multi_head_attention`` op additionally keeps softmax(QK^T)V in one XLA
fusion.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


def _split_interleaved(qkv, heads):
    """(S, B, H*3*D) -> three (B*H, S, D) tensors, reference layout."""
    s, b, hd3 = qkv.shape
    d = hd3 // (heads * 3)
    x = qkv.reshape(s, b, heads, 3, d)
    # -> (B, H, S, D) per projection, flattened to (B*H, S, D)
    def pick(i):
        t = x[:, :, :, i, :]  # (S, B, H, D)
        return jnp.transpose(t, (1, 2, 0, 3)).reshape(b * heads, s, d)
    return pick(0), pick(1), pick(2)


@register_op("interleaved_matmul_selfatt_qk",
             aliases=("_contrib_interleaved_matmul_selfatt_qk",))
def _selfatt_qk(queries_keys_values, heads=1):
    """scores = (1/sqrt(d)) Q K^T, output (B*H, S, S) like the reference."""
    q, k, _ = _split_interleaved(queries_keys_values, heads)
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], q.dtype))
    return jnp.matmul(q * scale, jnp.swapaxes(k, -1, -2))


@register_op("interleaved_matmul_selfatt_valatt",
             aliases=("_contrib_interleaved_matmul_selfatt_valatt",))
def _selfatt_valatt(queries_keys_values, attention, heads=1):
    """out = attn @ V, back to (S, B, H*D)."""
    _, _, v = _split_interleaved(queries_keys_values, heads)
    s, b = queries_keys_values.shape[0], queries_keys_values.shape[1]
    d = v.shape[-1]
    out = jnp.matmul(attention, v)  # (B*H, S, D)
    out = out.reshape(b, heads, s, d)
    return jnp.transpose(out, (2, 0, 1, 3)).reshape(s, b, heads * d)


@register_op("multi_head_attention", needs_rng=True)
def _multi_head_attention(q, k, v, mask=None, heads=1, dropout=0.0,
                          causal=False, training=None):
    """Fused MHA on (B, S, H*D)-shaped projections; XLA fuses scale+softmax.

    No reference analogue as a single op (GluonNLP composes the two contrib
    ops); provided because one fused op is the idiomatic TPU formulation.
    ``dropout`` drops attention probabilities (the reference cell's
    _attention_dropout), train-mode only.
    """
    from .. import autograd as _autograd
    from .. import random as _random
    if training is None:
        training = _autograd.is_training()
    b, sq, hd = q.shape
    d = hd // heads
    def to_bhsd(x):
        return jnp.transpose(x.reshape(b, -1, heads, d), (0, 2, 1, 3))
    qh, kh, vh = to_bhsd(q), to_bhsd(k), to_bhsd(v)
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, q.dtype))
    scores = jnp.einsum("bhqd,bhkd->bhqk", qh * scale, kh)
    if causal:
        sk = kh.shape[2]
        cm = jnp.tril(jnp.ones((sq, sk), bool))
        scores = jnp.where(cm, scores, jnp.asarray(-1e30, scores.dtype))
    if mask is not None:
        scores = jnp.where(mask.astype(bool), scores, jnp.asarray(-1e30, scores.dtype))
    attn = jax.nn.softmax(scores, axis=-1)
    if dropout > 0.0 and training:
        keep = jax.random.bernoulli(_random.next_key(), 1.0 - dropout,
                                    shape=attn.shape)
        attn = jnp.where(keep, attn / (1.0 - dropout),
                         jnp.zeros((), attn.dtype))
    out = jnp.einsum("bhqk,bhkd->bhqd", attn, vh)
    return jnp.transpose(out, (0, 2, 1, 3)).reshape(b, sq, hd)


@register_op("flash_attention")
def _flash_attention_op(q, k, v, heads=1, causal=False, block_q=128,
                        block_k=128, dropout=0.0, training=None):
    """Flash MHA on (B, S, H*D) projections via the Pallas kernel
    (ops/pallas/flash_attention.py) — O(S·D) memory instead of the dense
    op's O(S^2) scores; the long-context single-chip path.  ``dropout``
    applies attention-probability dropout inside the kernel (training only),
    seeded from the framework RNG stream each call."""
    from .. import autograd as _autograd
    from .. import random as _random
    from .pallas import flash_attention
    if training is None:
        training = _autograd.is_training()
    b, sq, hd = q.shape
    d = hd // heads
    def to_bhsd(x):
        return jnp.transpose(x.reshape(b, -1, heads, d),
                             (0, 2, 1, 3)).reshape(b * heads, -1, d)
    drop = float(dropout) if training else 0.0
    seed = None
    if drop > 0.0:
        seed = jax.random.randint(_random.next_key(), (1,), 0, 2 ** 31 - 1)
    out = flash_attention(to_bhsd(q), to_bhsd(k), to_bhsd(v), None, causal,
                          block_q, block_k, None, drop, seed)
    out = out.reshape(b, heads, sq, d)
    return jnp.transpose(out, (0, 2, 1, 3)).reshape(b, sq, hd)


@register_op("div_sqrt_dim", aliases=("_contrib_div_sqrt_dim",))
def _div_sqrt_dim(x):
    return x / jnp.sqrt(jnp.asarray(x.shape[-1], x.dtype))


@register_op("ring_attention", mesh_aware=True)
def _ring_attention(q, k, v, heads=1, causal=False, axis="sp",
                    batch_axis="dp", dropout=0.0, training=None):
    """Sequence-parallel attention over the active mesh's ``sp`` axis
    (no reference analogue — SURVEY.md §5.7 gap, first-class here).
    Requires a parallel.MeshScope (or TrainStep/EvalStep, which provide one)."""
    from .. import autograd as _autograd
    from ..parallel.sequence import ring_attention
    if training is None:
        training = _autograd.is_training()
    return ring_attention(q, k, v, heads, axis=axis, batch_axis=batch_axis,
                          causal=causal, dropout=dropout, training=training)


@register_op("ulysses_attention", mesh_aware=True)
def _ulysses_attention(q, k, v, heads=1, causal=False, axis="sp",
                       batch_axis="dp", dropout=0.0, training=None):
    """Ulysses head-sharded attention over the active mesh (see above)."""
    from .. import autograd as _autograd
    from ..parallel.sequence import ulysses_attention
    if training is None:
        training = _autograd.is_training()
    return ulysses_attention(q, k, v, heads, axis=axis, batch_axis=batch_axis,
                             causal=causal, dropout=dropout, training=training)
