"""Sequence ops (ref: src/operator/sequence_mask-inl.h, sequence_last-inl.h,
sequence_reverse-inl.h).  Layout matches the reference: time-major (T, N, ...)
with optional per-batch lengths."""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register_op


def _time_mask(x, sequence_length):
    t = x.shape[0]
    steps = jnp.arange(t).reshape((t,) + (1,) * (x.ndim - 1))
    lens = sequence_length.astype(jnp.int32).reshape((1, -1) + (1,) * (x.ndim - 2))
    return steps < lens


@register_op("SequenceMask", aliases=("sequence_mask",))
def _sequence_mask(data, sequence_length=None, use_sequence_length=False, value=0.0, axis=0):
    if sequence_length is None or not use_sequence_length:
        return data
    x = jnp.swapaxes(data, 0, axis) if axis != 0 else data
    mask = _time_mask(x, sequence_length)
    out = jnp.where(mask, x, jnp.asarray(value, x.dtype))
    return jnp.swapaxes(out, 0, axis) if axis != 0 else out


@register_op("SequenceLast", aliases=("sequence_last",))
def _sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0):
    x = jnp.swapaxes(data, 0, axis) if axis != 0 else data
    if sequence_length is None or not use_sequence_length:
        return x[-1]
    idx = jnp.clip(sequence_length.astype(jnp.int32) - 1, 0, x.shape[0] - 1)
    return jnp.take_along_axis(
        x, idx.reshape((1, -1) + (1,) * (x.ndim - 2)), axis=0
    )[0]


@register_op("SequenceReverse", aliases=("sequence_reverse",))
def _sequence_reverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    if sequence_length is None or not use_sequence_length:
        return jnp.flip(data, axis=0)
    t = data.shape[0]
    steps = jnp.arange(t).reshape((t,) + (1,) * (data.ndim - 1))
    lens = sequence_length.astype(jnp.int32).reshape((1, -1) + (1,) * (data.ndim - 2))
    # position i maps to (len-1-i) inside the valid prefix, identity elsewhere
    src = jnp.where(steps < lens, lens - 1 - steps, steps)
    return jnp.take_along_axis(data, jnp.broadcast_to(src, data.shape), axis=0)
