"""INT8 quantization ops (ref: src/operator/quantization/*.{h,cc,cu} —
quantize_v2.cc, dequantize.cc, quantized_fully_connected.cc, calibrate.cc).
TPU-native: int8 matmuls go through lax.dot_general with int32 accumulation,
which XLA maps onto the MXU's int8 path."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


def _range_for(x, min_calib, max_calib):
    if min_calib is not None and max_calib is not None:
        return jnp.asarray(min_calib, jnp.float32), jnp.asarray(max_calib, jnp.float32)
    return jnp.min(x).astype(jnp.float32), jnp.max(x).astype(jnp.float32)


@register_op("quantize_v2")
def _quantize_v2(data, out_type="int8", min_calib_range=None, max_calib_range=None):
    mn, mx = _range_for(data, min_calib_range, max_calib_range)
    amax = jnp.maximum(jnp.abs(mn), jnp.abs(mx))
    scale = 127.0 / jnp.maximum(amax, 1e-10)
    q = jnp.clip(jnp.round(data.astype(jnp.float32) * scale), -127, 127).astype(jnp.int8)
    return q, -amax, amax


@register_op("dequantize")
def _dequantize(data, min_range, max_range, out_type="float32"):
    amax = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))
    return data.astype(jnp.float32) * (amax / 127.0)


@register_op("quantized_fully_connected")
def _quantized_fc(data, weight, bias, min_data, max_data, min_weight, max_weight,
                  min_bias=None, max_bias=None, num_hidden=None, no_bias=False,
                  flatten=True):
    x = data.reshape(data.shape[0], -1) if flatten else data
    acc = jax.lax.dot_general(
        x, weight.T, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    sx = jnp.maximum(jnp.abs(min_data), jnp.abs(max_data)) / 127.0
    sw = jnp.maximum(jnp.abs(min_weight), jnp.abs(max_weight)) / 127.0
    out = acc.astype(jnp.float32) * (sx * sw)
    if bias is not None and not no_bias:
        if min_bias is None or max_bias is None:
            # float bias path (ref: quantized_fully_connected accepts fp32
            # bias when no bias calibration ranges are given)
            out = out + bias.astype(jnp.float32)
        else:
            sb = jnp.maximum(jnp.abs(min_bias), jnp.abs(max_bias)) / 127.0
            out = out + bias.astype(jnp.float32) * sb
    return out


@register_op("quantized_matmul")
def _quantized_matmul(a, b, scale_a, scale_b):
    acc = jax.lax.dot_general(
        a, b, (((a.ndim - 1,), (b.ndim - 2,)), ((), ())),
        preferred_element_type=jnp.int32)
    return acc.astype(jnp.float32) * (scale_a * scale_b)


@register_op("quantized_conv")
def _quantized_conv(data, weight, bias, min_data, max_data, min_weight,
                    max_weight, kernel=None, stride=None, pad=None,
                    num_filter=None, num_group=1, no_bias=True, layout=None,
                    dilate=None):
    """int8 convolution with int32 accumulation (ref: src/operator/
    quantization/quantized_conv.cc).  Same layout contract as Convolution;
    output is dequantised fp32 (the reference emits int32 + ranges — the
    fp32 form composes with the rest of this frontend and XLA fuses the
    rescale into the conv epilogue)."""
    from .nn import _conv_layout, _tup
    nd_ = data.ndim - 2
    kernel = _tup(kernel, nd_)
    stride = _tup(stride, nd_) if stride else (1,) * nd_
    pad = _tup(pad, nd_) if pad else (0,) * nd_
    dilate = _tup(dilate, nd_) if dilate else (1,) * nd_
    _, dnl, chan_last = _conv_layout(layout, nd_)
    dn = jax.lax.conv_dimension_numbers(data.shape, weight.shape, dnl)
    acc = jax.lax.conv_general_dilated(
        data.astype(jnp.int8), weight.astype(jnp.int8),
        window_strides=stride, padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    sx = jnp.maximum(jnp.abs(min_data), jnp.abs(max_data)) / 127.0
    sw = jnp.maximum(jnp.abs(min_weight), jnp.abs(max_weight)) / 127.0
    out = acc.astype(jnp.float32) * (sx * sw)
    if bias is not None and not no_bias:
        bshape = ((1,) * (nd_ + 1) + (-1,)) if chan_last \
            else ((1, -1) + (1,) * nd_)
        out = out + bias.astype(jnp.float32).reshape(bshape)
    return out
