"""Control-flow ops (ref: src/operator/control_flow.{h,cc} — foreach,
while_loop, cond as subgraph-executing ops).  TPU-native: these ARE the lax
primitives; the wrappers adapt the reference's calling convention (NDArray
lists in/out) for gluon.contrib use."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


def _unwrap(x):
    from ..ndarray import NDArray
    return x._data if isinstance(x, NDArray) else x


def foreach(body, data, init_states):
    """ref: foreach op — scan `body(x_t, states) -> (out_t, new_states)` over
    axis 0 of `data`.  Accepts NDArrays (the ``mx.nd.contrib.foreach``
    calling convention, including multi-output bodies) or raw jax arrays;
    the body sees the same kind."""
    from ..ndarray import NDArray
    tree = jax.tree_util.tree_map
    nd_mode = any(
        isinstance(x, NDArray)
        for x in jax.tree_util.tree_leaves(data) +
        jax.tree_util.tree_leaves(init_states))

    def step(states, x):
        if nd_mode:
            out, new_states = body(tree(NDArray, x), tree(NDArray, states))
            return tree(_unwrap, new_states), tree(_unwrap, out)
        out, new_states = body(x, states)
        return new_states, out

    final_states, outs = jax.lax.scan(
        step, tree(_unwrap, init_states), tree(_unwrap, data))
    if nd_mode:
        return tree(NDArray, outs), tree(NDArray, final_states)
    return outs, final_states


def while_loop(cond, func, loop_vars, max_iterations=None):
    """ref: while_loop op. Fixed upper bound keeps shapes static on TPU."""
    from ..ndarray import NDArray
    nd_mode = any(isinstance(v, NDArray) for v in loop_vars)
    if nd_mode:
        # NDArray comparisons return float 0/1 (reference semantics);
        # lax.while_loop needs a bool predicate
        wrap = lambda vs: [NDArray(v) for v in vs]
        cond_j = lambda *vs: jnp.asarray(
            _unwrap(cond(*wrap(vs)))).astype(jnp.bool_)
        func_j = lambda *vs: [_unwrap(o) for o in func(*wrap(vs))]
        loop_vars = [_unwrap(v) for v in loop_vars]
    else:
        cond_j, func_j = cond, func
    if max_iterations is None:
        final = jax.lax.while_loop(lambda v: cond_j(*v),
                                   lambda v: tuple(func_j(*v)),
                                   tuple(loop_vars))
        return [NDArray(v) for v in final] if nd_mode else final
    def body(i_and_vars):
        i, v = i_and_vars
        v = jax.lax.cond(cond_j(*v), lambda vv: tuple(func_j(*vv)),
                         lambda vv: vv, v)
        return i + 1, v
    def keep_going(i_and_vars):
        i, v = i_and_vars
        return (i < max_iterations) & cond_j(*v)
    _, final = jax.lax.while_loop(keep_going, body,
                                  (jnp.int32(0), tuple(loop_vars)))
    return [NDArray(v) for v in final] if nd_mode else final


def cond(pred, then_func, else_func, inputs=()):
    """ref: cond op."""
    from ..ndarray import NDArray
    nd_mode = isinstance(pred, NDArray) or any(
        isinstance(x, NDArray) for x in inputs)
    if nd_mode:
        wrap = lambda xs: tuple(NDArray(x) for x in xs)
        out = jax.lax.cond(
            _unwrap(pred),
            lambda xs: jax.tree_util.tree_map(
                _unwrap, then_func(*wrap(xs))),
            lambda xs: jax.tree_util.tree_map(
                _unwrap, else_func(*wrap(xs))),
            tuple(_unwrap(x) for x in inputs))
        return jax.tree_util.tree_map(NDArray, out)
    return jax.lax.cond(pred, lambda xs: then_func(*xs),
                        lambda xs: else_func(*xs), tuple(inputs))


register_op("_foreach_marker", lambda x: x)  # registry placeholder; python-level API above
