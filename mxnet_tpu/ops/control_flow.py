"""Control-flow ops (ref: src/operator/control_flow.{h,cc} — foreach,
while_loop, cond as subgraph-executing ops).  TPU-native: these ARE the lax
primitives; the wrappers adapt the reference's calling convention (NDArray
lists in/out) for gluon.contrib use."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


def foreach(body, data, init_states):
    """ref: foreach op — scan `body(x_t, states) -> (out_t, new_states)` over
    axis 0 of `data`.  Works on jax arrays; gluon.contrib wraps NDArrays."""
    def step(states, x):
        out, new_states = body(x, states)
        return new_states, out

    final_states, outs = jax.lax.scan(step, init_states, data)
    return outs, final_states


def while_loop(cond, func, loop_vars, max_iterations=None):
    """ref: while_loop op. Fixed upper bound keeps shapes static on TPU."""
    if max_iterations is None:
        final = jax.lax.while_loop(lambda v: cond(*v), lambda v: tuple(func(*v)), tuple(loop_vars))
        return final
    def body(i_and_vars):
        i, v = i_and_vars
        v = jax.lax.cond(cond(*v), lambda vv: tuple(func(*vv)), lambda vv: vv, v)
        return i + 1, v
    def keep_going(i_and_vars):
        i, v = i_and_vars
        return (i < max_iterations) & cond(*v)
    _, final = jax.lax.while_loop(keep_going, body, (jnp.int32(0), tuple(loop_vars)))
    return final


def cond(pred, then_func, else_func, inputs=()):
    """ref: cond op."""
    return jax.lax.cond(pred, lambda xs: then_func(*xs), lambda xs: else_func(*xs), tuple(inputs))


register_op("_foreach_marker", lambda x: x)  # registry placeholder; python-level API above
