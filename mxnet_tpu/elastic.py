"""Elastic training runtime: heartbeats, hang watchdog, gang supervisor.

ref: the reference's failure story ends at the dmlc tracker relaunching a
dead worker; SURVEY §5.3 names cluster-scale failure recovery as the gap
to exceed.  TensorFlow (arXiv:1605.08695) treats runtime health checks +
user-level checkpoints as a design axis, and on Cloud TPU slices
preemption is the *normal* lifecycle event (arXiv:2605.25645).  This
module is both sides of that contract:

- **Worker side** — ``Heartbeat``: each rank atomically writes
  ``{rank, attempt, global_step, monotonic_stamp, phase}`` to a per-rank
  file on a step cadence (wired into ``Module.fit`` via the
  ``MXTPU_HEARTBEAT_DIR`` env contract and into ``parallel.TrainStep``
  via ``heartbeat=``), plus distinguishable exit statuses
  (``EXIT_PREEMPTED`` for the snapshot-then-exit path,
  ``EXIT_NONFINITE`` for the non-finite abort) so preemption, numeric
  abort, and crash are classifiable from outside the process.
- **Supervisor side** — ``Supervisor``: spawns the gang under the DMLC_*
  env contract (``tools/launch.py`` is now a thin CLI over it), a
  watchdog thread declares a worker hung when its heartbeat stamp goes
  stale past ``watchdog_secs``, any failure (crash / hang / nonfinite /
  preempted worker) tears down the WHOLE gang (SIGTERM first so healthy
  workers snapshot, SIGKILL after ``graceful_secs``) and relaunches with
  ``fault.backoff_delay`` between attempts.  The restart budget is
  **progress-aware**: an attempt that advanced the latest committed
  checkpoint step (``progress_dir``) refills the budget, so a long job
  survives many spread-out faults while a crash-loop pinned at one step
  exhausts it fast and exits with a post-mortem.  Supervisor-level
  SIGTERM forwards to the workers, waits for their snapshots, and exits
  cleanly.  Everything lands in a JSONL event log.

Observability fault points (registered in ``fault.py``):
``supervisor.spawn`` / ``supervisor.heartbeat`` / ``supervisor.watchdog``
/ ``supervisor.restart``.  ``tools/chaos_check.py --mode elastic`` is the
acceptance smoke (SIGKILL + SIGSTOP-hang + supervisor-SIGTERM legs over a
real 2-worker CPU gang).

Like ``fault.py`` this module imports ONLY the standard library, and it
is loadable by file path outside the package: the supervisor process must
stay jax-free (importing the package would pull the backend into the
launcher — on a TPU host that can wedge device ownership away from the
very workers it launches).  ``tools/launch.py`` loads it that way.
"""
from __future__ import annotations

import json
import os
import queue
import re
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

try:  # normal package import (worker side, tests)
    from . import fault as _fault
    from . import telemetry as _telemetry
except ImportError:  # pragma: no cover — loaded by file path (tools/launch.py)
    import importlib.util as _ilu

    def _load_standalone(stem):
        spec = _ilu.spec_from_file_location(
            f"_mxtpu_{stem}_standalone",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         f"{stem}.py"))
        mod = _ilu.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    _fault = _load_standalone("fault")
    _telemetry = _load_standalone("telemetry")

__all__ = ["EXIT_OK", "EXIT_PREEMPTED", "EXIT_NONFINITE", "HEARTBEAT_ENV",
           "NonFiniteAbortError", "classify_exit", "Heartbeat",
           "read_heartbeats", "scan_checkpoints", "latest_checkpoint",
           "latest_committed_step", "EventLog", "Supervisor"]

# ------------------------------------------------------------ exit status --
# The worker→supervisor status channel is the process exit code (the only
# channel that survives SIGKILL of everything else).  Codes 43/44 sit
# outside the shell/python conventional range (0/1/2, 126+) so a plain
# `sys.exit(1)` crash can never masquerade as a classified status.
EXIT_OK = 0
EXIT_PREEMPTED = 43       # snapshot-then-exit (the GracefulExit path)
EXIT_NONFINITE = 44       # non-finite abort (TrainStep nonfinite_budget)

HEARTBEAT_ENV = "MXTPU_HEARTBEAT_DIR"


class NonFiniteAbortError(RuntimeError):
    """TrainStep exhausted its non-finite budget.  A ``RuntimeError``
    subclass so pre-existing handlers keep matching; supervised workers
    catch it specifically and exit ``EXIT_NONFINITE`` so the supervisor
    can classify the failure from outside."""


def classify_exit(returncode):
    """Map a worker's exit code to a status string: ``ok`` /
    ``preempted`` (snapshot-then-exit) / ``nonfinite`` (numeric abort) /
    ``killed:<SIG>`` (died on a signal) / ``crash`` (anything else) /
    ``unreaped`` (``None`` — the process outlived even SIGKILL, e.g.
    wedged in uninterruptible I/O; the supervisor reports it instead of
    crashing mid-drain)."""
    if returncode is None:
        return "unreaped"
    rc = int(returncode)
    if rc == EXIT_OK:
        return "ok"
    if rc == EXIT_PREEMPTED:
        return "preempted"
    if rc == EXIT_NONFINITE:
        return "nonfinite"
    if rc < 0:
        try:
            return f"killed:{signal.Signals(-rc).name}"
        except ValueError:
            return f"killed:{-rc}"
    return "crash"


# -------------------------------------------------------------- heartbeat --
class Heartbeat:
    """Per-rank liveness stamp, written atomically on a step cadence.

    ``beat(global_step, phase)`` writes ``heartbeat-r<rank>.json`` under
    ``directory`` via tmp + ``os.replace`` — a reader never sees a torn
    record.  ``monotonic_stamp`` is ``time.monotonic()``, which on Linux
    is the boot-based system-wide clock, so the supervisor on the same
    host compares it against its own monotonic reading (the local
    launcher contract; multi-host supervisors would use file mtimes on
    the shared filesystem instead).

    The first beat always writes (it is what engages the watchdog for
    this attempt — construction deliberately does NOT write, so a slow
    first compile cannot trip a short watchdog before step 1 exists);
    after that, ``train``-phase beats are thinned to every
    ``every_n_steps``-th CALL (not step value — a pinned step counter,
    e.g. ``skip_nonfinite`` riding out corrupt batches, must still
    refresh the stamp), and phase transitions always write.

    Wiring: ``Heartbeat.from_env()`` builds one from the supervisor's
    env contract (``MXTPU_HEARTBEAT_DIR`` + ``DMLC_WORKER_ID`` +
    ``DMLC_ATTEMPT``), ``Module.fit`` calls it automatically when the
    env is armed, ``parallel.TrainStep(heartbeat=hb)`` beats after every
    completed step, and the instance is itself a batch-end callback
    (``callback.do_heartbeat`` is the explicit spelling).
    """

    PHASES = ("init", "train", "eval", "snapshot", "exit")

    def __init__(self, directory, rank, attempt=0, every_n_steps=1):
        self.directory = str(directory)
        self.rank = int(rank)
        self.attempt = int(attempt)
        self.every_n_steps = max(1, int(every_n_steps))
        self.path = os.path.join(self.directory,
                                 f"heartbeat-r{self.rank}.json")
        self._auto_step = 0
        self._calls = 0
        self._last_written = None
        self._last_phase = None
        self._last_compiling = False
        os.makedirs(self.directory, exist_ok=True)

    @classmethod
    def from_env(cls, environ=None):
        """Build from the supervisor's env contract, or None when this
        process is not supervised (``MXTPU_HEARTBEAT_DIR`` unset) — so
        training loops can wire heartbeats unconditionally."""
        env = os.environ if environ is None else environ
        directory = env.get(HEARTBEAT_ENV)
        if not directory:
            return None
        return cls(directory,
                   rank=int(env.get("DMLC_WORKER_ID", "0") or 0),
                   attempt=int(env.get("DMLC_ATTEMPT", "0") or 0),
                   every_n_steps=int(env.get("MXTPU_HEARTBEAT_EVERY", "1")
                                     or 1))

    def beat(self, global_step=None, phase="train", last_step_ms=None,
             compile_in_progress=False):
        """Stamp liveness; returns the record written, or None when the
        cadence thinned this step out.  ``global_step=None`` auto-counts
        calls (the batch-end-callback form).

        ``last_step_ms`` is the wall time of the just-completed step —
        the supervisor summarizes these into its fleet-wide ``step_ms``
        histogram (ISSUE 15).  ``compile_in_progress=True`` marks a
        stamp written right BEFORE a compiling call: the watchdog grants
        such a worker the startup grace instead of the steady-state
        staleness bound, so a long first compile is distinguishable from
        a hung step.  A change in the flag always writes (the watchdog
        must see it flip regardless of the cadence)."""
        if global_step is None:
            self._auto_step += 1
            global_step = self._auto_step
        else:
            global_step = int(global_step)
            self._auto_step = global_step
        # thin by CALL count, not step value: a live worker whose step
        # counter is pinned (skip_nonfinite riding out corrupt batches)
        # must still refresh its stamp, or the watchdog would declare a
        # healthy, actively-stepping worker hung.  Phase TRANSITIONS
        # always write; repeated same-phase beats (train steps, eval
        # batches) follow the cadence — the env knob exists to throttle
        # per-batch write+rename I/O, whatever the phase
        self._calls += 1
        compiling = bool(compile_in_progress)
        if (phase == self._last_phase and self._last_written is not None
                and compiling == self._last_compiling
                and self._calls % self.every_n_steps != 0):
            return None
        rec = {"rank": self.rank, "attempt": self.attempt,
               "global_step": global_step,
               "monotonic_stamp": time.monotonic(),
               "phase": str(phase), "pid": os.getpid(),
               "wall_time": time.time(),
               "last_step_ms": None if last_step_ms is None
               else round(float(last_step_ms), 3),
               "compile_in_progress": compiling}
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, self.path)
        self._last_written = global_step
        self._last_phase = str(phase)
        self._last_compiling = compiling
        return rec

    def __call__(self, param=None):
        """Batch-end-callback form (``Module.fit(batch_end_callback=hb)``)."""
        self.beat(phase="train")


def read_heartbeats(directory):
    """``{rank: record}`` for every parseable ``heartbeat-r<N>.json`` in
    ``directory``.  A record mid-replace or damaged is skipped for this
    scan (atomic writes make that a transient, not a corruption)."""
    out = {}
    pat = re.compile(r"heartbeat-r(\d+)\.json$")
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        m = pat.fullmatch(name)
        if not m:
            continue
        try:
            with open(os.path.join(directory, name)) as f:
                out[int(m.group(1))] = json.load(f)
        except (OSError, ValueError):
            continue
    return out


# --------------------------------------------------------- progress scan --
# The one committed-checkpoint filename parser in the stack:
# parallel/checkpoint.py delegates list_checkpoints here, so the
# supervisor's progress accounting and the training-side retention /
# resume discovery can never disagree about what "committed" means.

def scan_checkpoints(directory, prefix="ckpt"):
    """``(num_update, path)`` pairs for every ``<prefix>-<n>.npz`` in
    ``directory``, ascending by step.  Orphan ``.tmp`` files (a crash
    mid-write) are ignored — they were never committed."""
    pat = re.compile(re.escape(prefix) + r"-(\d+)\.npz$")
    out = []
    if os.path.isdir(directory):
        for name in os.listdir(directory):
            m = pat.fullmatch(name)
            if m:
                out.append((int(m.group(1)), os.path.join(directory, name)))
    return sorted(out)


def latest_checkpoint(directory, prefix="ckpt"):
    """Newest committed ``(num_update, path)``, or None when empty."""
    cks = scan_checkpoints(directory, prefix)
    return cks[-1] if cks else None


def latest_committed_step(directory, prefix="ckpt"):
    """The newest committed snapshot's step, or None when the directory
    holds none — the supervisor's progress probe (stdlib-only; the
    jax-side spelling is ``CheckpointManager.latest_step()``)."""
    ck = latest_checkpoint(directory, prefix)
    return ck[0] if ck else None


# ---------------------------------------------------------------- events --
class EventLog:
    """Append-only JSONL event stream + in-memory record list.

    One line per event: ``{"ts": ..., "mono": ..., "kind": "event",
    "name"/"event": ..., **fields}`` — the machine-readable supervision
    history (``tools/chaos_check.py --mode elastic`` parses it back).
    ISSUE 13: hosted on ``telemetry.JsonlSink``, the ONE JSONL stream
    implementation of the stack (supervisor log, autoscaler log, and
    trace export all ride it) — atomic line writes, size rotation, and
    the shared ``ts``/``mono``/``kind``/``name`` schema, which also
    gives every event the monotonic stamp autoscale records previously
    lacked.  The legacy ``event`` key stays on every record so existing
    parsers keep working.  ``echo`` mirrors a one-line human form to a
    stream (the supervisor uses stderr).  Emit only from the owning
    thread; worker threads hand verdicts to the owner instead."""

    def __init__(self, path=None, echo=None, max_bytes=None):
        self.path = str(path) if path else None
        self.records = []
        self._sink = _telemetry.JsonlSink(self.path, max_bytes=max_bytes)
        self._echo = echo

    def emit(self, event, **fields):
        payload = dict(fields)
        payload.setdefault("event", str(event))
        if "name" in payload:          # caller-owned name field wins
            rec = self._sink.write("event", **payload)
        else:
            rec = self._sink.write("event", str(event), **payload)
        self.records.append(rec)
        if self._echo is not None:
            kv = " ".join(f"{k}={fields[k]}" for k in sorted(fields))
            print(f"[supervisor] {event} {kv}".rstrip(),
                  file=self._echo, flush=True)
        return rec

    def close(self):
        self._sink.close()


def _free_port(host="127.0.0.1"):
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def _pump_lines(pipe, tag, stream):
    """Forward one worker pipe line-by-line with a ``[r<rank>]`` tag so
    interleaved gang output stays attributable.  Runs on a daemon thread
    per pipe; exits when the worker closes its end."""
    with pipe:
        for line in iter(pipe.readline, b""):
            try:
                stream.write(tag + line.decode("utf-8", "replace"))
                stream.flush()
            except ValueError:        # stream closed at interpreter exit
                return


def _stop_procs(procs, grace):
    """Gang teardown: SIGTERM (+SIGCONT — a SIGSTOPped worker, the hang
    the watchdog catches, must be resumed to run its snapshot-then-exit
    handler), wait up to ``grace`` seconds, then SIGKILL stragglers and
    reap everything — the no-leaked-worker guarantee."""
    for p in procs:
        if p.poll() is None:
            try:
                p.terminate()
                if hasattr(signal, "SIGCONT"):
                    p.send_signal(signal.SIGCONT)
            except OSError:
                pass
    deadline = time.monotonic() + max(0.0, float(grace))
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(timeout=max(0.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                pass
    for p in procs:
        if p.poll() is None:
            try:
                p.kill()
            except OSError:
                pass
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:  # pragma: no cover
                pass


# ------------------------------------------------------------ supervisor --
class Supervisor:
    """Elastic gang supervisor (the engine under ``tools/launch.py``).

    ``run()`` (one-shot; call from the main thread so the SIGTERM latch
    binds — ``request_stop()`` is the programmatic equivalent from any
    thread) spawns ``num_workers`` copies of ``command`` under the
    DMLC_* env contract with a fresh coordinator port per attempt, and
    supervises:

    - any worker exiting nonzero (crash / ``EXIT_PREEMPTED`` /
      ``EXIT_NONFINITE``) or going heartbeat-stale past
      ``watchdog_secs`` tears down the whole gang (a partial gang
      deadlocks in collectives) and relaunches after
      ``fault.backoff_delay``;
    - the restart budget (``max_restarts``) is progress-aware when
      ``progress_dir`` is set: an attempt that advanced the latest
      committed checkpoint step refills it, a no-progress crash-loop
      exhausts it and exits with a ``giveup`` post-mortem;
    - supervisor SIGTERM/SIGINT (or ``request_stop()``) forwards SIGTERM
      to the workers, waits ``graceful_secs`` for their snapshots, and
      returns 0.

    Worker stdout/stderr is prefixed ``[r<rank>]`` line-by-line (or teed
    to ``r<rank>.log`` under ``log_dir``); every lifecycle transition
    lands in the JSONL ``event_log``.
    """

    def __init__(self, command, num_workers, *, platform=None,
                 devices_per_worker=0, max_restarts=0, watchdog_secs=0.0,
                 startup_grace_secs=None, graceful_secs=10.0,
                 backoff_base=0.5, backoff_max=8.0, heartbeat_dir=None,
                 log_dir=None, event_log=None, progress_dir=None,
                 progress_prefix="ckpt", extra_env=None, prefix_output=True,
                 poll=0.05, coordinator_host="127.0.0.1"):
        self.command = list(command)
        self.num_workers = int(num_workers)
        self.platform = platform
        self.devices_per_worker = int(devices_per_worker or 0)
        self.max_restarts = int(max_restarts)
        self.watchdog_secs = float(watchdog_secs or 0.0)
        if startup_grace_secs is not None:
            self.startup_grace_secs = float(startup_grace_secs)
        elif self.watchdog_secs > 0:
            # an armed watchdog must also catch a worker that wedges
            # BEFORE its first beat (stuck import/compile/handshake) or
            # the hang it exists to kill survives bring-up; default the
            # grace to 10x the steady-state staleness bound (floor 60s —
            # bring-up is legitimately much slower than a step)
            self.startup_grace_secs = max(60.0, 10.0 * self.watchdog_secs)
        else:
            self.startup_grace_secs = None
        self.graceful_secs = float(graceful_secs)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self._hb_dir_owned = heartbeat_dir is None
        self.heartbeat_dir = heartbeat_dir or tempfile.mkdtemp(
            prefix="mxtpu_hb_")
        os.makedirs(self.heartbeat_dir, exist_ok=True)
        self.log_dir = log_dir
        self.event_log = event_log
        self.progress_dir = progress_dir
        self.progress_prefix = progress_prefix
        self.extra_env = dict(extra_env or {})
        self.prefix_output = bool(prefix_output)
        self.poll = float(poll)
        self.coordinator_host = coordinator_host
        self.restarts = 0
        self.log = None
        self._procs = []
        self._watchdog = None
        self._verdicts = queue.Queue()
        self._stop = threading.Event()
        # step-time visibility (ISSUE 15): last global_step seen per
        # rank, so each heartbeat's last_step_ms is observed into the
        # fleet-wide step_ms histogram exactly once
        self._step_seen = {}

    # ---- public observability ----
    def worker_pids(self):
        """PIDs of the current attempt's live workers (chaos harnesses
        aim their SIGKILL/SIGSTOP here; the spawn event carries the same
        list)."""
        return [p.pid for p in self._procs if p.poll() is None]

    def request_stop(self):
        """Programmatic supervisor-SIGTERM: the next loop tick forwards
        SIGTERM to the gang, waits for snapshots, and run() returns 0.
        (Signal latches only bind on the main thread; this works from
        any.)"""
        self._stop.set()

    def telemetry(self, fmt="json"):
        """The unified metrics exposition (ISSUE 13): the SAME
        ``telemetry.exposition`` key schema the serving runtimes serve
        (one scraper reads the whole stack), with the supervisor's gang
        counters and worker gauges.  ISSUE 15 adds the fleet-wide
        ``step_ms`` histogram (each rank's heartbeat ``last_step_ms``,
        observed by the watchdog scan once per step) plus the
        ``compiling_workers`` gauge and the uniform ``compile_*`` /
        ``mem_*`` families, so the elastic gang's step-time visibility
        sits next to its heartbeats.  ``fmt="prom"`` renders the
        Prometheus-style text form.  Works in standalone (file-path)
        mode — the telemetry twin loads the same way ``fault`` does."""
        counters = {"restarts": self.restarts,
                    "events": 0 if self.log is None
                    else len(self.log.records)}
        beats = read_heartbeats(self.heartbeat_dir)
        gauges = {"workers": self.num_workers,
                  "live_workers": len(self.worker_pids()),
                  "max_restarts": self.max_restarts,
                  "watchdog_secs": self.watchdog_secs,
                  "compiling_workers": sum(
                      1 for rec in beats.values()
                      if rec.get("compile_in_progress"))}
        gauges.update(_telemetry.compile_gauges("Supervisor"))
        gauges.update(_telemetry.memory_gauges(None))
        # snapshot-stream health (ISSUE 17): the supervisor's progress
        # accounting rides the checkpoint directory, so its exposition
        # carries the ckpt_* family too
        gauges.update(_telemetry.ckpt_gauges())
        hists = _telemetry.registry().snapshot(
            prefix="Supervisor::")["histograms"]
        payload = _telemetry.exposition("supervisor", "Supervisor",
                                        counters, gauges, hists)
        return _telemetry.render(payload, fmt)

    def _note_heartbeat(self, rank, rec):
        """Fold one heartbeat record into the supervisor's step-time
        telemetry: each NEW (rank, global_step) stamp's ``last_step_ms``
        lands in the ``Supervisor::step_ms`` histogram once.  Called
        from the watchdog scan; never raises (observability must not
        un-guard the gang)."""
        try:
            ms = rec.get("last_step_ms")
            step = rec.get("global_step")
            if ms is None or self._step_seen.get(rank) == step:
                return
            self._step_seen[rank] = step
            _telemetry.registry().histogram(
                "Supervisor::step_ms",
                _telemetry.SPAN_MS_BUCKETS).observe(float(ms))
        except Exception:  # noqa: BLE001
            pass

    # ---- the run loop ----
    def run(self):
        budget = self.max_restarts
        consecutive = 0          # no-progress failures in a row → backoff
        attempt = 0
        self.log = EventLog(self.event_log, echo=sys.stderr)
        try:
            with _fault.GracefulExit() as gexit:
                while True:
                    start_step = self._progress()
                    outcome = self._run_gang(attempt, gexit)
                    end_step = self._progress()
                    if outcome["kind"] == "stopped":
                        self.log.emit("preempted", attempt=attempt,
                                      progress=end_step,
                                      statuses=outcome["statuses"])
                        return 0
                    if outcome["kind"] == "ok":
                        self.log.emit("done", attempt=attempt,
                                      progress=end_step,
                                      restarts=self.restarts)
                        return 0
                    progressed = end_step is not None and (
                        start_step is None or end_step > start_step)
                    if progressed:
                        if budget < self.max_restarts:
                            self.log.emit("budget-refill", attempt=attempt,
                                          progress=end_step,
                                          budget=self.max_restarts)
                        budget = self.max_restarts
                        consecutive = 0
                    if budget <= 0:
                        self.log.emit(
                            "giveup", attempt=attempt, rc=outcome["rc"],
                            reason=outcome["reason"],
                            post_mortem=self._post_mortem(
                                attempt, outcome, start_step, end_step))
                        return outcome["rc"] or 1
                    budget -= 1
                    consecutive += 1
                    self.restarts += 1
                    attempt += 1
                    delay = _fault.backoff_delay(
                        consecutive, self.backoff_base, self.backoff_max)
                    self.log.emit("restart", attempt=attempt,
                                  reason=outcome["reason"],
                                  delay=round(delay, 3), budget_left=budget,
                                  progress=end_step)
                    print(f"[launch] job failed ({outcome['reason']}); "
                          f"restart {self.restarts}/{self.max_restarts} "
                          f"in {delay:.1f}s", file=sys.stderr, flush=True)
                    _fault.fire("supervisor.restart")
                    if self._sleep(delay, gexit):
                        self.log.emit("preempted", attempt=attempt,
                                      progress=end_step, statuses={})
                        return 0
        finally:
            self.log.close()
            if self._hb_dir_owned:
                # the auto-created temp dir is ours to remove (repeated
                # launches must not accumulate /tmp orphans); a
                # user-supplied --heartbeat-dir is left alone
                shutil.rmtree(self.heartbeat_dir, ignore_errors=True)

    # ---- internals ----
    def _progress(self):
        if not self.progress_dir:
            return None
        return latest_committed_step(self.progress_dir, self.progress_prefix)

    def _sleep(self, delay, gexit):
        """Backoff sleep, interruptible by stop/SIGTERM; True if stopped."""
        deadline = time.monotonic() + delay
        while time.monotonic() < deadline:
            if gexit.requested or self._stop.is_set():
                return True
            time.sleep(min(0.05, max(0.0, deadline - time.monotonic())))
        return gexit.requested or self._stop.is_set()

    def _worker_env(self, rank, attempt, port):
        env = dict(os.environ)
        env.update(self.extra_env)
        env.update({
            "DMLC_ROLE": "worker",
            "DMLC_PS_ROOT_URI": self.coordinator_host,
            "DMLC_PS_ROOT_PORT": str(port),
            "DMLC_NUM_WORKER": str(self.num_workers),
            "DMLC_WORKER_ID": str(rank),
            "DMLC_ATTEMPT": str(attempt),
            HEARTBEAT_ENV: self.heartbeat_dir,
        })
        if self.event_log:
            # per-rank flight-recorder bundles (ISSUE 15) land next to
            # the supervisor's own event log: workers arm via
            # telemetry.flight_from_env and dump on their death paths
            # (GracefulExit from the teardown SIGTERM, non-finite abort,
            # unhandled exception) — collection is the shared directory
            env[_telemetry.FLIGHT_ENV] = os.path.join(
                os.path.dirname(os.path.abspath(self.event_log)),
                "flight")
        if self.log_dir or self.prefix_output:
            # redirected stdio makes python block-buffer: progress lines
            # would lag by kilobytes and a SIGKILLed worker's final
            # output — the crash context the prefixing exists to
            # attribute — would vanish with its buffer
            env["PYTHONUNBUFFERED"] = "1"
        if self.platform:
            env["JAX_PLATFORMS"] = self.platform
            if self.platform == "cpu":
                # keep the axon/TPU plugin out of CPU rehearsal workers:
                # sitecustomize registers it at interpreter startup
                env.pop("PALLAS_AXON_POOL_IPS", None)
        if self.devices_per_worker:
            # REPLACE any inherited device-count flag (the launching
            # process often runs its own 8-device virtual mesh)
            flags = [f for f in env.get("XLA_FLAGS", "").split()
                     if "xla_force_host_platform_device_count" not in f]
            flags.append(f"--xla_force_host_platform_device_count="
                         f"{self.devices_per_worker}")
            env["XLA_FLAGS"] = " ".join(flags)
        return env

    def _run_gang(self, attempt, gexit):
        """One attempt: spawn all workers, supervise until success,
        failure (then tear the whole gang down), or stop."""
        _fault.fire("supervisor.spawn")
        port = _free_port(self.coordinator_host)
        # stale stamps in a reused --heartbeat-dir (a previous run's
        # attempt-0 files carry the SAME attempt number with an ancient
        # monotonic stamp) would trip the watchdog before the new
        # workers' first beat: every attempt spawns into a clean slate
        for name in os.listdir(self.heartbeat_dir):
            if re.fullmatch(r"heartbeat-r\d+\.json(\.tmp)?", name):
                try:
                    os.remove(os.path.join(self.heartbeat_dir, name))
                except OSError:
                    pass
        procs, pumps, logfiles = [], [], []
        stop_watch = threading.Event()
        try:
            for rank in range(self.num_workers):
                env = self._worker_env(rank, attempt, port)
                stdout = stderr = None
                if self.log_dir:
                    os.makedirs(self.log_dir, exist_ok=True)
                    lf = open(os.path.join(self.log_dir, f"r{rank}.log"),
                              "ab", buffering=0)
                    logfiles.append(lf)
                    stdout, stderr = lf, subprocess.STDOUT
                elif self.prefix_output:
                    stdout = stderr = subprocess.PIPE
                proc = subprocess.Popen(self.command, env=env,
                                        stdout=stdout, stderr=stderr)
                procs.append(proc)
                if stdout is subprocess.PIPE:
                    for pipe, stream in ((proc.stdout, sys.stdout),
                                         (proc.stderr, sys.stderr)):
                        t = threading.Thread(
                            target=_pump_lines,
                            args=(pipe, f"[r{rank}] ", stream), daemon=True)
                        t.start()
                        pumps.append(t)
            self._procs = procs
            self.log.emit("spawn", attempt=attempt, port=port,
                          pids=[p.pid for p in procs],
                          progress=self._progress())
            if self.watchdog_secs > 0 or self.startup_grace_secs:
                watchdog = threading.Thread(
                    target=self._watchdog_loop,
                    args=(attempt, procs, stop_watch), daemon=True)
                watchdog.start()
                self._watchdog = watchdog     # owned per attempt; joined
                try:                          # in the finally below
                    return self._wait_gang(procs, attempt, gexit)
                finally:
                    stop_watch.set()
                    watchdog.join(timeout=5)
            return self._wait_gang(procs, attempt, gexit)
        finally:
            stop_watch.set()
            _stop_procs(procs, self.graceful_secs)
            for t in pumps:
                t.join(timeout=5)
            for lf in logfiles:
                lf.close()
            self._procs = []
            self._drain_verdicts()

    def _reap_remaining(self, procs, pending, attempt, statuses):
        """Tear down the still-running workers and account for every one
        of them: each surviving rank gets a worker-exit event with its
        REAL post-teardown status (a SIGCONT+SIGTERM-recovered hang often
        exits ``preempted``), so the event log and the giveup post-mortem
        never under-report the gang."""
        _stop_procs(procs, self.graceful_secs)
        for i in sorted(pending):
            rc = procs[i].returncode
            statuses[i] = classify_exit(rc)
            self.log.emit("worker-exit", attempt=attempt, rank=i,
                          rc=rc, status=statuses[i])

    def _wait_gang(self, procs, attempt, gexit):
        statuses = {}
        pending = set(range(len(procs)))
        while True:
            if gexit.requested or self._stop.is_set():
                self.log.emit("forward-sigterm", attempt=attempt,
                              pids=[procs[i].pid for i in sorted(pending)])
                self._reap_remaining(procs, pending, attempt, statuses)
                return {"kind": "stopped", "rc": 0,
                        "reason": "supervisor-stop", "statuses": statuses}
            for i in sorted(pending):
                rc = procs[i].poll()
                if rc is None:
                    continue
                pending.discard(i)
                statuses[i] = classify_exit(rc)
                self.log.emit("worker-exit", attempt=attempt, rank=i,
                              rc=rc, status=statuses[i])
                if rc != 0:
                    reason = f"worker {i} {statuses[i]} (rc={rc})"
                    self.log.emit("teardown", attempt=attempt, rank=i,
                                  reason=reason)
                    self._reap_remaining(procs, pending, attempt, statuses)
                    return {"kind": "failed", "rc": rc, "reason": reason,
                            "statuses": statuses}
            if not pending:
                return {"kind": "ok", "rc": 0, "reason": "",
                        "statuses": statuses}
            verdict = self._next_verdict(self.poll)
            if verdict is None:
                continue
            kind = verdict[0]
            if kind == "error":
                raise verdict[1]
            _, rank, age = verdict
            if rank in pending:
                if kind == "no-heartbeat":
                    self.log.emit("no-heartbeat", attempt=attempt,
                                  rank=rank, waited_secs=round(age, 2),
                                  startup_grace_secs=self.startup_grace_secs)
                    reason = (f"worker {rank} hung (no heartbeat within "
                              f"{self.startup_grace_secs:.1f}s startup "
                              f"grace)")
                else:
                    self.log.emit("heartbeat-stale", attempt=attempt,
                                  rank=rank, stale_secs=round(age, 2),
                                  watchdog_secs=self.watchdog_secs)
                    reason = (f"worker {rank} hung (heartbeat stale "
                              f"{age:.1f}s > {self.watchdog_secs:.1f}s)")
                self.log.emit("teardown", attempt=attempt, rank=rank,
                              reason=reason)
                self._reap_remaining(procs, pending, attempt, statuses)
                return {"kind": "failed", "rc": 1, "reason": reason,
                        "statuses": statuses}

    def _next_verdict(self, timeout):
        try:
            return self._verdicts.get(timeout=timeout)
        except queue.Empty:
            return None

    def _drain_verdicts(self):
        while True:
            try:
                self._verdicts.get_nowait()
            except queue.Empty:
                return

    def _watchdog_loop(self, attempt, procs, stop_evt):
        """Watchdog thread: scan heartbeat files, declare a live worker
        hung when its current-attempt stamp is stale past
        ``watchdog_secs`` (or, with ``startup_grace_secs``, when it
        never produced one).  Verdicts go to the owner thread through a
        queue; an exception here is forwarded the same way (the producer
        convention — a silently dead watchdog would un-guard the gang)."""
        stale_after = self.watchdog_secs
        tick = max(0.05, min((stale_after or 1.0) / 4.0, 1.0))
        t0 = time.monotonic()
        while not stop_evt.wait(tick):
            try:
                _fault.fire("supervisor.heartbeat")
                beats = read_heartbeats(self.heartbeat_dir)
                now = time.monotonic()
                for rank in range(self.num_workers):
                    if procs[rank].poll() is not None:
                        continue          # exit classification owns it
                    rec = beats.get(rank)
                    if rec is None or int(rec.get("attempt", -1)) != attempt:
                        grace = self.startup_grace_secs
                        if grace and now - t0 > grace:
                            _fault.fire("supervisor.watchdog")
                            # keep scanning after posting: the owner may
                            # discard a verdict whose rank exited in the
                            # meantime, and a watchdog that retired on
                            # the first post would leave the REST of the
                            # gang unguarded for the attempt
                            self._verdicts.put(("no-heartbeat", rank,
                                                now - t0))
                        continue
                    self._note_heartbeat(rank, rec)
                    # NB an "exit"-phase record gets no exemption: a
                    # worker that wedges AFTER its exit beat (shutdown
                    # stuck on the coordination service) is exactly the
                    # unbounded hang this watchdog exists to kill; a
                    # clean exit leaves the stale check via poll() above
                    # long before the stamp ages out
                    if stale_after > 0:
                        age = now - float(rec.get("monotonic_stamp", now))
                        limit = stale_after
                        if rec.get("compile_in_progress"):
                            # the stamp says a compile is in flight: a
                            # long first compile is bring-up, not a hang
                            # — grant the startup grace instead of the
                            # steady-state bound (ISSUE 15; the next
                            # completed step clears the flag)
                            limit = max(stale_after,
                                        self.startup_grace_secs
                                        or 10.0 * stale_after)
                        if age > limit:
                            _fault.fire("supervisor.watchdog")
                            self._verdicts.put(("hang", rank, age))
            except Exception as exc:
                self._verdicts.put(("error", exc))
                return

    def _post_mortem(self, attempt, outcome, start_step, end_step):
        """The giveup diagnostic: what the job died of, where progress
        stalled, and each rank's last recorded heartbeat."""
        beats = {}
        now = time.monotonic()
        for rank, rec in sorted(read_heartbeats(self.heartbeat_dir).items()):
            beats[str(rank)] = {
                "global_step": rec.get("global_step"),
                "phase": rec.get("phase"),
                "attempt": rec.get("attempt"),
                "stale_secs": round(
                    now - float(rec.get("monotonic_stamp", now)), 2),
            }
        return {"attempts": attempt + 1, "restarts": self.restarts,
                "last_reason": outcome["reason"],
                "statuses": outcome["statuses"],
                "progress_at_spawn": start_step, "progress_now": end_step,
                "heartbeats": beats}
