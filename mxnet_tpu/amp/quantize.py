"""Post-training int8 weight quantization for serving.

ref: the reference ships ``mxnet.contrib.quantization`` (calibrated
int8 *op* rewriting for MKLDNN/TensorRT); here the serving bottleneck
is different — PERF.md measures the hot paths HBM-bandwidth-bound, so
the lever is the **weight buffer**: int8 payloads + per-channel f32
scales quarter the bytes a compiled serving program holds and streams
per step, which multiplies serving capacity per chip (the
Gemma-on-TPU serving comparison, arXiv:2605.25645; ROADMAP item 2).

The scheme is symmetric per-channel PTQ, deterministic round-to-nearest
(stochastic rounding is for *gradients* — ``parallel.quantize`` — where
bias accumulates over steps; a weight is quantized once):

- ``quantize_weight`` / ``dequantize_weight``: one f32 scale per output
  channel (``amax / 127`` along ``axis``), int8 payload.
- ``Int8Quantizer``: the serving-container form.  ``quantize()`` maps a
  params pytree (list or dict, the ``fleet.HotSwapApply`` currency)
  into its int8 representation — every float leaf with
  ``ndim >= min_ndim`` becomes a payload/scale *pair* of leaves
  (``k`` + ``k::scale`` for dicts, adjacent entries for sequences);
  1-D leaves (bias, norm stats) stay full precision, where they are
  numerically load-bearing and byte-wise irrelevant.  ``wrap()`` turns
  an ``fn(params, *batch_leaves)`` into the int8-consuming form with
  the **dequant folded inside** — jit ``wrap(fn)`` and the compiled
  program's weight arguments are int8 (the committed
  ``serving_mlp_grid_int8`` budget golden measures exactly this).

Because ``quantize()`` is deterministic and shape/dtype-stable, it is
also the fleet's snapshot-ingest transform: ``WeightUpdater`` runs an
f32 training snapshot through the fleet's quantizer before
``validate_params``, so rolling updates from an f32 training job stream
into an int8 fleet without a recompile or a dtype-drift rejection.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

__all__ = ["quantize_weight", "dequantize_weight", "Int8Quantizer"]

#: dict-container key suffix pairing a scale leaf with its payload
SCALE_SUFFIX = "::scale"


def quantize_weight(w, axis=0):
    """Symmetric per-channel int8 quantization of one weight.

    Returns ``(q, scales)``: ``q`` int8 with ``w``'s shape, ``scales``
    f32 of shape ``(w.shape[axis],)`` (``amax / 127`` per channel; an
    all-zero channel gets scale 1 so dequantization is exact).
    Deterministic round-to-nearest."""
    x = jnp.asarray(w, jnp.float32)
    reduce_axes = tuple(i for i in range(x.ndim) if i != axis % x.ndim)
    amax = jnp.max(jnp.abs(x), axis=reduce_axes)
    # != 0, not > 0: a NaN channel must keep its NaN scale so the
    # quantized leaf dequantizes non-finite and the fleet's
    # validate_params all-finite gate rejects the snapshot — `> 0`
    # would launder the NaN into a finite zeroed weight
    scales = jnp.where(amax != 0, amax / 127.0, 1.0)
    q = jnp.round(x / _channel_view(scales, x.ndim, axis))
    return jnp.clip(q, -127.0, 127.0).astype(jnp.int8), scales


def dequantize_weight(q, scales, axis=0, dtype=jnp.float32):
    """Inverse of ``quantize_weight`` (jit-safe: this is the fold-in
    the compiled serving apply runs per step)."""
    return (q.astype(jnp.float32)
            * _channel_view(scales, q.ndim, axis)).astype(dtype)


def _channel_view(scales, ndim, axis):
    shape = [1] * ndim
    shape[axis % ndim] = -1
    return jnp.reshape(scales, shape)


def _is_quantized_payload(leaf):
    return getattr(leaf, "dtype", None) == jnp.int8


class Int8Quantizer:
    """Container-level int8 PTQ for serving params (see module doc).

    ``axis`` is the per-channel scale axis of the quantized weights —
    0 for MXNet-layout ``(units, in_units)`` Dense kernels, the last
    axis for ``x @ w`` math-layout kernels.  Leaves with fewer than
    ``min_ndim`` dims (or non-float dtypes) pass through unquantized.
    """

    def __init__(self, axis=0, min_ndim=2):
        self.axis = int(axis)
        self.min_ndim = int(min_ndim)

    def _quantizes(self, leaf):
        arr = np.asarray(leaf) if not hasattr(leaf, "dtype") else leaf
        return (np.issubdtype(np.dtype(str(arr.dtype)), np.floating)
                and arr.ndim >= self.min_ndim)

    def quantize(self, params):
        """f32 container → int8 container (payload/scale leaf pairs).

        Deterministic, so re-quantizing the same snapshot always yields
        the same leaves — the property ``validate_params`` relies on
        when a rolling update re-ingests f32 training snapshots."""
        if isinstance(params, dict):
            out = {}
            for k, v in params.items():
                if str(k).endswith(SCALE_SUFFIX) or _is_quantized_payload(v):
                    raise ValueError(
                        f"Int8Quantizer.quantize: leaf {k!r} already "
                        f"looks quantized — quantize() ingests "
                        f"full-precision containers only")
                if self._quantizes(v):
                    q, s = quantize_weight(v, self.axis)
                    out[k] = q
                    out[f"{k}{SCALE_SUFFIX}"] = s
                else:
                    out[k] = jnp.asarray(v)
            return out
        out = []
        for v in params:
            if _is_quantized_payload(v):
                raise ValueError(
                    "Int8Quantizer.quantize: int8 leaf in input — "
                    "quantize() ingests full-precision containers only")
            if self._quantizes(v):
                q, s = quantize_weight(v, self.axis)
                out.extend((q, s))
            else:
                out.append(jnp.asarray(v))
        return out

    def dequantize(self, qparams, dtype=jnp.float32):
        """int8 container → full-precision container in the ORIGINAL
        layout (payload/scale pairs collapse back to one leaf).
        jit-safe — ``wrap`` runs it inside the compiled apply."""
        if isinstance(qparams, dict):
            out = {}
            for k, v in qparams.items():
                if str(k).endswith(SCALE_SUFFIX):
                    continue
                if _is_quantized_payload(v):
                    out[k] = dequantize_weight(
                        v, qparams[f"{k}{SCALE_SUFFIX}"], self.axis, dtype)
                else:
                    out[k] = v
            return out
        out, i = [], 0
        while i < len(qparams):
            v = qparams[i]
            if _is_quantized_payload(v):
                out.append(dequantize_weight(v, qparams[i + 1], self.axis,
                                             dtype))
                i += 2
            else:
                out.append(v)
                i += 1
        return out

    def wrap(self, fn, dtype=jnp.float32):
        """``fn(params, *leaves)`` → ``qfn(qparams, *leaves)`` with the
        dequant folded in.  jit the result and the compiled program's
        weight arguments are the int8 payloads + f32 scales — the
        quartered weight buffer the serving budget golden commits."""
        def qfn(qparams, *leaves):
            return fn(self.dequantize(qparams, dtype), *leaves)
        return qfn
