"""AMP cast lists (ref: python/mxnet/amp/lists/symbol_fp16.py —
FP16_FUNCS / FP16_FP32_FUNCS / FP32_FUNCS / WIDEST_TYPE_CASTS).

Categories:
- TARGET_DTYPE_OPS: run in the low-precision target (MXU-bound matmul/conv
  families — the reference's FP16_FUNCS).
- FP32_OPS: numerically sensitive, forced to float32 (softmax/norm/exp/...).
- WIDEST_OPS: elementwise ops cast to the widest input dtype so mixed
  operands don't silently truncate.
Everything else runs in whatever dtype its inputs already have.
"""

TARGET_DTYPE_OPS = [
    "Convolution", "Deconvolution", "FullyConnected", "dot", "batch_dot",
    "linalg_gemm", "linalg_gemm2", "RNN",
    "_contrib_interleaved_matmul_selfatt_qk",
    "_contrib_interleaved_matmul_selfatt_valatt",
    "multi_head_attention", "flash_attention",
    "quantized_matmul", "quantized_fully_connected",
]

FP32_OPS = [
    "softmax", "log_softmax", "softmin", "SoftmaxOutput",
    "softmax_cross_entropy", "CTCLoss", "smooth_l1",
    "BatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm", "RMSNorm",
    "L2Normalization", "norm", "exp", "expm1", "log", "log2", "log10",
    "log1p", "rsqrt", "sqrt", "square", "reciprocal", "rcbrt", "cbrt",
    "pow", "power", "gamma", "gammaln", "erf", "erfinv", "sum", "mean",
    "nansum", "prod", "nanprod", "cumsum", "cumprod", "sin", "cos", "tan",
    "arcsin", "arccos", "arctan", "sinh", "cosh", "tanh_fp32_guard",
]

WIDEST_OPS = [
    "add", "subtract", "multiply", "divide", "maximum", "minimum", "mod",
    "hypot", "broadcast_add", "broadcast_sub", "broadcast_mul",
    "broadcast_div", "Concat", "stack", "where", "clip",
]
