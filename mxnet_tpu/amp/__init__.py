"""``mx.amp`` — automatic mixed precision.

ref: python/mxnet/amp/amp.py — amp.init() (list-driven op-level cast
rewriting), amp.init_trainer() + amp.scale_loss() (dynamic loss scaling),
amp/lists/* (op categories).

TPU-native mapping: the default target is **bfloat16** — same exponent
range as f32, so loss scaling is unnecessary and `amp.init()` alone gives
the MXU its native dtype.  float16 is supported for parity and uses the
reference's dynamic loss scaler (scale up every ``scale_window`` clean
steps, halve and skip the update on overflow).  The cast rewriting hooks
the single op-dispatch point (``nd.invoke``) instead of rewriting a symbol
graph: every TARGET_DTYPE op's float inputs are cast down, every FP32 op's
inputs are cast up, and WIDEST ops unify mixed operands — the same
semantics as the reference's symbolic pass, applied at the only place ops
enter the runtime.
"""
from __future__ import annotations

import contextlib
import sys

import numpy as np
import jax.numpy as jnp

from ..ndarray.ndarray import NDArray
from ..ndarray import ndarray as _nd_mod
from . import lists
from .quantize import Int8Quantizer, dequantize_weight, quantize_weight

__all__ = ["init", "init_trainer", "scale_loss", "unscale",
           "convert_model", "LossScaler",
           "Int8Quantizer", "quantize_weight", "dequantize_weight"]

_FLOATS = (jnp.float16, jnp.bfloat16, jnp.float32)


class _AmpState:
    def __init__(self, target_dtype):
        self.declared = str(target_dtype)
        # fp16 requests compute in bf16 on TPU (amp_cast maps them; same
        # mantissa economics, f32-range exponent) — the declared dtype only
        # decides whether the loss scaler is installed, for API parity
        self.target = jnp.dtype(jnp.bfloat16) \
            if target_dtype in ("float16", "bfloat16") else jnp.dtype(target_dtype)
        self.target_ops = set(lists.TARGET_DTYPE_OPS)
        self.fp32_ops = set(lists.FP32_OPS)
        self.widest_ops = set(lists.WIDEST_OPS)


_state = None


def _is_float(a):
    return isinstance(a, NDArray) and a._data.dtype in _FLOATS


def _cast_args(op_name, args):
    """Apply the list-driven dtype policy to one op call's array inputs.

    Casts go through the ``amp_cast`` op (recursion-guarded) so they are
    recorded on the autograd tape — a raw buffer cast would disconnect the
    original parameter from the gradient graph."""
    s = _state
    if op_name in ("amp_cast", "amp_multicast", "Cast", "stop_gradient"):
        return args
    if op_name in s.target_ops:
        want = s.target
    elif op_name in s.fp32_ops:
        want = jnp.dtype(jnp.float32)
    elif op_name in s.widest_ops:
        dts = [a._data.dtype for a in args if _is_float(a)]
        if not dts:
            return args
        want = max(dts, key=lambda d: jnp.dtype(d).itemsize)
        if len(set(dts)) == 1:
            return args
    else:
        return args
    want_s = str(want)
    return tuple(
        _nd_mod.invoke("amp_cast", a, dtype=want_s)
        if _is_float(a) and a._data.dtype != want else a
        for a in args)


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Enable AMP process-wide (ref: amp.init).  Idempotent."""
    global _state
    target_dtype = str(jnp.dtype(target_dtype))
    assert target_dtype in ("float16", "bfloat16")
    fresh = _state is None
    if fresh:
        _state = _AmpState(target_dtype)
    else:
        # re-init: keep previously registered custom lists, retarget dtype
        prev_t, prev_32 = _state.target_ops, _state.fp32_ops
        _state.__init__(target_dtype)
        _state.target_ops |= prev_t
        _state.fp32_ops |= prev_32
    if target_precision_ops:
        _state.target_ops.update(target_precision_ops)
    if fp32_ops:
        _state.fp32_ops.update(fp32_ops)
    if conditional_fp32_ops:
        # reference semantics: run these ops in fp32 when the named attr
        # matches; conservatively force fp32 always (safe direction)
        _state.fp32_ops.update(
            name if isinstance(name, str) else name[0]
            for name in conditional_fp32_ops)
    if not fresh:
        return
    # splice into the dispatch point (profiler-hook pattern: one global
    # read per dispatch when off, applied inside invoke itself so every
    # caller — including from-imports of invoke — goes through the policy)
    _nd_mod._AMP = sys.modules[__name__]


def _deinit_for_tests():
    """Undo init() (test isolation only; the reference has no amp.off)."""
    global _state
    if _state is None:
        return
    _nd_mod._AMP = None
    _state = None


class LossScaler:
    """Dynamic loss scaler (ref: amp/loss_scaler.py — class LossScaler)."""

    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000):
        self.loss_scale = float(init_scale)
        self._factor = scale_factor
        self._window = scale_window
        self._unskipped = 0

    def has_overflow(self, params):
        """True if any gradient is non-finite (checked on device, one small
        host sync per step — the fp16 tax; bf16 AMP never needs this)."""
        for p in params:
            g = p.data().grad
            if g is None:
                continue
            if not bool(jnp.isfinite(g._data).all()):
                return True
        return False

    def update_scale(self, overflow):
        if overflow:
            self.loss_scale = max(1.0, self.loss_scale / self._factor)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._window:
                self.loss_scale *= self._factor
                self._unskipped = 0


def init_trainer(trainer):
    """Attach dynamic loss scaling to a gluon Trainer (ref: amp.init_trainer).

    bfloat16 targets skip scaling entirely (range matches f32)."""
    if _state is None:
        raise RuntimeError("call amp.init() before amp.init_trainer()")
    if _state.declared == "bfloat16":
        trainer._amp_loss_scaler = None
        return
    trainer._amp_loss_scaler = LossScaler()
    trainer._amp_original_step = trainer.step

    def _amp_step(batch_size, ignore_stale_grad=False, _t=trainer):
        scaler = _t._amp_loss_scaler
        overflow = scaler.has_overflow(_t._params)
        if overflow:
            scaler.update_scale(True)
            _t.zero_grad()
            return  # skip the update, like the reference
        # grads were produced under the CURRENT scale: unscale with it,
        # then let the scaler grow (growth applies to the NEXT backward)
        eff = 1.0 if getattr(_t, "_amp_unscaled", False) \
            else scaler.loss_scale
        _t._amp_unscaled = False
        _t._amp_original_step(batch_size * eff, ignore_stale_grad)
        scaler.update_scale(False)

    trainer.step = _amp_step


@contextlib.contextmanager
def scale_loss(loss, trainer):
    """``with amp.scale_loss(loss, trainer) as l: l.backward()``
    (ref: amp.scale_loss).  Scaling is folded into the rescale_grad of the
    trainer's next step, so gradients are unscaled exactly once."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        yield loss
        return
    if isinstance(loss, (list, tuple)):
        yield type(loss)(l * scaler.loss_scale for l in loss)
    else:
        yield loss * scaler.loss_scale


def unscale(trainer):
    """Manually unscale accumulated grads (for gradient clipping between
    backward and step; ref: amp.unscale).  The scaler keeps its scale for
    the next iteration — only THIS step's grads are marked pre-unscaled."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        return
    inv = 1.0 / scaler.loss_scale
    for p in trainer._params:
        g = p.data().grad
        if g is not None:
            g._data = g._data * inv
    trainer._amp_unscaled = True


def convert_model(net, target_dtype="bfloat16"):
    """Cast a gluon block's parameters to the target dtype
    (ref: amp.convert_model for the symbolic path; gluon uses net.cast)."""
    net.cast(str(jnp.dtype(target_dtype)))
    return net
