"""Symbolic executor (ref: src/executor/graph_executor.cc — GraphExecutor).

The reference's executor plans memory, schedules kernels through the
dependency engine, and hand-wires every op's FGradient into a backward
graph.  TPU-native substitution: the Symbol DAG traces into ONE pure jax
function; `jax.jit` is the memory planner + scheduler (XLA buffer
assignment and fusion), and `jax.grad` over the traced function IS the
backward graph.  MXNet's output-op semantics (SoftmaxOutput & friends carry
their loss gradient implicitly) live in `_HEAD_LOSSES`, so
`executor.backward()` reproduces the reference's training contract without
a per-op FGradient registry.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from . import random as _random
from .context import current_context, Context
from .ndarray import NDArray
from .ops.registry import OP_META, get_op
from .symbol import (LAYERS, Symbol, infer_arg_shapes, node_threads_aux,
                     observe_n_out)


# ---------------------------------------------------------------------------
# tracing the DAG into a pure function
# ---------------------------------------------------------------------------

def walk_graph(sym: Symbol, leaf, apply_op, aux_update):
    """THE DAG-evaluation algorithm, shared by the executor (jax values,
    registry fns) and gluon.SymbolBlock (NDArrays, nd.invoke).

    ``leaf(node) -> value`` resolves a variable; ``apply_op(node, ins,
    attrs) -> value|tuple`` applies one op; ``aux_update(name, value)``
    receives the functional aux-state outputs (BatchNorm moving stats)
    threading back into their variables.  A whole multi-output head yields
    EVERY output, like the reference's executor."""
    memo: Dict[int, object] = {}

    def value(s: Symbol):
        node = s._node
        key = id(node)
        if key not in memo:
            if node.op is None:
                memo[key] = leaf(node)
            else:
                ins = [value(i) for i in node.inputs]
                attrs = {k: v for k, v in node.attrs.items()
                         if not k.startswith("__")}
                res = apply_op(node, ins, attrs)
                if node_threads_aux(node) and isinstance(res, tuple):
                    out, new_aux = res[0], res[1:]
                    aux_syms = [i for i in node.inputs if i._node.is_aux]
                    for s_aux, v_new in zip(aux_syms, new_aux):
                        aux_update(s_aux._node.name, v_new)
                    res = out
                memo[key] = res
        res = memo[key]
        if isinstance(res, tuple):
            # arity is static (symbol._static_n_out): for ruled ops the
            # trace only CHECKS it (a mismatch raises — list_outputs
            # must agree before and after the first eval); custom ops
            # the probe couldn't evaluate reconcile to the traced arity
            observe_n_out(node, len(res))
            return res[s._index]
        return res

    outs = []
    for s in sym._outputs_list():
        first = value(s)
        res = memo[id(s._node)]
        if s._whole and isinstance(res, tuple):
            outs.extend(res)
        else:
            outs.append(first)
    return outs


def _trace(sym: Symbol, arg_vals: Dict, aux_vals: Dict, training: bool):
    """Evaluate the DAG on jax values.  Returns (outputs, aux_updates)."""
    aux_updates: Dict[str, object] = {}

    def leaf(node):
        store = aux_vals if node.is_aux else arg_vals
        if node.name not in store:
            kind = "auxiliary state" if node.is_aux else "argument"
            raise ValueError(f"executor: unbound {kind} {node.name!r}")
        return store[node.name]

    def apply_op(node, ins, kwargs):
        if OP_META.get(node.op, {}).get("has_training"):
            kwargs.setdefault("training", training)
        return get_op(node.op)(*ins, **kwargs)

    outs = walk_graph(sym, leaf, apply_op, aux_updates.__setitem__)
    return outs, aux_updates


def _fwd_fn(sym: Symbol, training: bool):
    def fwd(arg_vals, aux_vals, key):
        with _random.RandomScope(key):
            return _trace(sym, dict(arg_vals), dict(aux_vals), training)

    return fwd


# ---------------------------------------------------------------------------
# implicit losses of the reference's output ops
# (ref: src/operator/softmax_output-inl.h Backward, regression_output-inl.h)
# ---------------------------------------------------------------------------

def _softmax_output_loss(out, label, attrs):
    axis = 1 if attrs.get("multi_output", False) else -1
    scale = float(attrs.get("grad_scale", 1.0))
    logp = jnp.log(jnp.maximum(out, 1e-37))
    lab = label.astype(jnp.int32)
    picked = jnp.take_along_axis(logp, jnp.expand_dims(lab, axis), axis)
    picked = jnp.squeeze(picked, axis)
    valid = jnp.ones_like(picked, bool)
    if attrs.get("use_ignore", False):
        valid = lab != int(attrs.get("ignore_label", -1))
        picked = jnp.where(valid, picked, 0.0)
    norm = attrs.get("normalization", "null")
    total = -jnp.sum(picked)
    if norm == "batch":
        total = total / out.shape[0]
    elif norm == "valid":
        total = total / jnp.maximum(jnp.sum(valid), 1)
    return total * scale


def _linear_regression_loss(out, label, attrs):
    scale = float(attrs.get("grad_scale", 1.0))
    return 0.5 * jnp.sum((out - label) ** 2) * scale


def _mae_regression_loss(out, label, attrs):
    scale = float(attrs.get("grad_scale", 1.0))
    return jnp.sum(jnp.abs(out - label)) * scale


def _logistic_regression_loss(out, label, attrs):
    # BCE on the sigmoid OUTPUT: d/dz = sigmoid(z) - label, the reference's
    # gradient (regression_output-inl.h LogisticRegressionOutput)
    scale = float(attrs.get("grad_scale", 1.0))
    p = jnp.clip(out, 1e-7, 1.0 - 1e-7)
    return -jnp.sum(label * jnp.log(p) + (1 - label) * jnp.log(1 - p)) * scale


def _make_loss_loss(out, label, attrs):
    return jnp.sum(out) * float(attrs.get("grad_scale", 1.0))


_HEAD_LOSSES = {
    "SoftmaxOutput": _softmax_output_loss,
    "LinearRegressionOutput": _linear_regression_loss,
    "MAERegressionOutput": _mae_regression_loss,
    "LogisticRegressionOutput": _logistic_regression_loss,
    "make_loss": _make_loss_loss,
    "MakeLoss": _make_loss_loss,
}


def _head_label_name(node) -> Optional[str]:
    """Slot-based (any variable name), like symbol.label_variables."""
    spec = LAYERS.get(node.op or "")
    if spec and spec.labels:
        slots = spec.inputs(node.attrs)
        for slot, s in zip(slots, node.inputs):
            if slot in spec.labels and s._node.op is None:
                return s._node.name
    return None


# ---------------------------------------------------------------------------
# Executor
# ---------------------------------------------------------------------------

def _as_nd(v, ctx):
    if isinstance(v, NDArray):
        return v
    return NDArray(jnp.asarray(v), ctx=ctx)


class Executor:
    """ref: mx.executor.Executor — forward/backward over bound arrays."""

    def __init__(self, symbol: Symbol, ctx=None, args=None, args_grad=None,
                 grad_req="write", aux_states=None):
        from .symbol import check_unique_variables

        check_unique_variables(symbol)
        self._symbol = symbol
        self._ctx = ctx if isinstance(ctx, Context) else current_context()
        self._arg_names = symbol.list_arguments()
        self._aux_names = symbol.list_auxiliary_states()

        def normalize(vals, names, what):
            if vals is None:
                return {}
            if isinstance(vals, dict):
                return {k: _as_nd(v, self._ctx) for k, v in vals.items()}
            vals = list(vals)
            if len(vals) != len(names):
                raise ValueError(f"{what}: expected {len(names)} entries "
                                 f"({names}), got {len(vals)}")
            return {n: _as_nd(v, self._ctx) for n, v in zip(names, vals)}

        self.arg_dict: Dict[str, NDArray] = normalize(args, self._arg_names,
                                                      "args")
        self.aux_dict: Dict[str, NDArray] = normalize(aux_states,
                                                      self._aux_names, "aux")
        self.grad_dict: Dict[str, NDArray] = normalize(args_grad,
                                                       self._arg_names,
                                                       "args_grad")
        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in self._arg_names}
        else:
            self._grad_req = {n: grad_req.get(n, "null")
                              for n in self._arg_names}
        self.outputs: List[NDArray] = []
        self._jit_cache = {}
        self._last_train = False

    # ---- array-list views (reference API) ----
    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self._arg_names]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n) for n in self._arg_names]

    @property
    def aux_arrays(self):
        return [self.aux_dict[n] for n in self._aux_names]

    # ---- forward ----
    def _vals(self, d):
        return {k: v._data for k, v in d.items()}

    def forward(self, is_train=False, **kwargs):
        for k, v in kwargs.items():
            self.arg_dict[k] = _as_nd(v, self._ctx)
        key = ("fwd", bool(is_train))
        if key not in self._jit_cache:
            self._jit_cache[key] = jax.jit(_fwd_fn(self._symbol,
                                                   bool(is_train)))
        outs, aux_updates = self._jit_cache[key](
            self._vals(self.arg_dict), self._vals(self.aux_dict),
            _random.next_key())
        self.outputs = [NDArray(o, ctx=self._ctx) for o in outs]
        if is_train:
            for k, v in aux_updates.items():
                self.aux_dict[k]._data = v
        self._last_train = bool(is_train)
        return self.outputs

    # ---- backward ----
    def _loss_fn(self):
        sym = self._symbol
        heads = sym._outputs_list()

        def loss(diff_vals, fixed_vals, aux_vals, key, out_grads):
            arg_vals = dict(fixed_vals)
            arg_vals.update(diff_vals)
            with _random.RandomScope(key):
                outs, _ = _trace(sym, arg_vals, dict(aux_vals), True)
            total = jnp.zeros((), jnp.float32)
            pos = 0
            for h in heads:
                # whole multi-output heads were expanded by _trace (n_out
                # was discovered during this very trace); keep indices
                # aligned with the user-visible outputs list
                n = h._node.n_out if (h._whole and h._node.n_out > 1) else 1
                for _ in range(n):
                    out, i = outs[pos], pos
                    pos += 1
                    op = h._node.op
                    if op in _HEAD_LOSSES and out_grads.get(i) is None:
                        lname = _head_label_name(h._node)
                        lab = arg_vals.get(lname) if lname else None
                        total = total + _HEAD_LOSSES[op](
                            out, lab, h._node.attrs).astype(jnp.float32)
                    elif out_grads.get(i) is not None:
                        total = total + jnp.sum(
                            out.astype(jnp.float32) *
                            out_grads[i].astype(jnp.float32))
                    # heads with neither implicit loss nor a cotangent
                    # contribute nothing (detached outputs)
            return total

        return loss

    def backward(self, out_grads=None):
        """Fill grad arrays (ref: Executor::Backward).  For loss-op heads the
        implicit gradient is used; other heads need `out_grads` entries."""
        if out_grads is not None and not isinstance(out_grads, (list, tuple)):
            out_grads = [out_grads]
        og = {}
        heads = self._symbol._outputs_list()
        if out_grads is not None:
            for i, g in enumerate(out_grads):
                if g is not None:
                    og[i] = g._data if isinstance(g, NDArray) else jnp.asarray(g)
        else:
            missing = [h._node.op for h in heads
                       if h._node.op not in _HEAD_LOSSES]
            if missing:
                raise ValueError(
                    f"backward(): heads {missing} carry no implicit loss; "
                    f"pass out_grads")
        diff_names = tuple(sorted(n for n, r in self._grad_req.items()
                                  if r != "null"))
        key = ("bwd", diff_names, tuple(sorted(og)))
        if key not in self._jit_cache:
            loss = self._loss_fn()
            self._jit_cache[key] = jax.jit(jax.grad(loss, argnums=0))
        diff_vals = {n: self.arg_dict[n]._data for n in diff_names}
        fixed_vals = {n: v._data for n, v in self.arg_dict.items()
                      if n not in diff_vals}
        grads = self._jit_cache[key](diff_vals, fixed_vals,
                                     self._vals(self.aux_dict),
                                     _random.next_key(), og)
        for n, g in grads.items():
            req = self._grad_req[n]
            if n in self.grad_dict:
                if req == "add":
                    self.grad_dict[n]._data = self.grad_dict[n]._data + g
                else:
                    self.grad_dict[n]._data = g
            else:
                self.grad_dict[n] = NDArray(g, ctx=self._ctx)
        return self.grad_arrays


# ---------------------------------------------------------------------------
# binding helpers
# ---------------------------------------------------------------------------

def simple_bind(sym: Symbol, ctx, grad_req, shapes):
    """ref: Symbol.simple_bind — infer every shape, allocate args/grads/aux."""
    ctx = ctx if isinstance(ctx, Context) else current_context()
    arg_shapes = infer_arg_shapes(sym, shapes)
    args, grads, aux = {}, {}, {}
    for n in sym.list_arguments():
        args[n] = NDArray(jnp.zeros(arg_shapes[n], jnp.float32), ctx=ctx)
        req = grad_req.get(n, "null") if isinstance(grad_req, dict) \
            else grad_req
        if req != "null":
            grads[n] = NDArray(jnp.zeros(arg_shapes[n], jnp.float32), ctx=ctx)
    for n in sym.list_auxiliary_states():
        aux[n] = NDArray(jnp.zeros(arg_shapes[n], jnp.float32), ctx=ctx)
    return Executor(sym, ctx, args, grads, grad_req, aux)


def eval_symbol(sym: Symbol, ctx, bindings):
    """Symbol.eval — one-shot forward with everything bound by name."""
    ex = Executor(sym, ctx, bindings, None, "null",
                  {n: bindings[n] for n in sym.list_auxiliary_states()
                   if n in bindings})
    return ex.forward(is_train=False)


def abstract_eval(sym: Symbol, arg_shapes: Dict[str, tuple]):
    """Output + aux shapes via jax.eval_shape (the NNVM InferShape pass)."""
    arg_names = sym.list_arguments()
    aux_names = sym.list_auxiliary_states()
    argv = {n: jax.ShapeDtypeStruct(tuple(arg_shapes[n]), jnp.float32)
            for n in arg_names}
    auxv = {n: jax.ShapeDtypeStruct(tuple(arg_shapes[n]), jnp.float32)
            for n in aux_names}

    outs, aux_updates = jax.eval_shape(_fwd_fn(sym, False), argv, auxv,
                                       jax.random.key(0))
    aux_shapes = {n: tuple(arg_shapes[n]) for n in aux_names}
    return outs, aux_shapes


def abstract_eval_prefix(s: Symbol, shapes: Dict[str, tuple]):
    """Shape of one intermediate symbol given variable shapes, or None when
    some variable below it has no known shape yet (infer_shape walks layers
    in topo order, so earlier layers' params are already inferred)."""
    for n in s._topo_nodes():
        if n.op is None and n.name not in shapes:
            return None
    argv = {n.name: jax.ShapeDtypeStruct(tuple(shapes[n.name]), jnp.float32)
            for n in s._topo_nodes() if n.op is None and not n.is_aux}
    auxv = {n.name: jax.ShapeDtypeStruct(tuple(shapes[n.name]), jnp.float32)
            for n in s._topo_nodes() if n.op is None and n.is_aux}
    outs, _ = jax.eval_shape(_fwd_fn(s, False), argv, auxv,
                             jax.random.key(0))
    return tuple(outs[0].shape)
