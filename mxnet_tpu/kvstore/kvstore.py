"""KVStore implementation.

ref: src/kvstore/kvstore.cc — KVStore::Create dispatching on type name;
kvstore_local.h — KVStoreLocal::{Init,Push,Pull} with per-key merge buffers
(CommCPU/CommDevice::Reduce); kvstore_dist_server.h — server-side optimizer
(set_updater / DataHandleEx).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..ndarray import NDArray

__all__ = ["KVStore", "create"]

_KNOWN_TYPES = ("local", "device", "nccl", "dist_sync", "dist_async",
                "dist_sync_device", "dist_async_device", "horovod", "byteps")


def create(name="local"):
    """ref: kvstore.cc — KVStore::Create."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    base = name.lower()
    if base not in _KNOWN_TYPES:
        raise ValueError(f"unknown KVStore type '{name}'")
    if base in ("horovod", "byteps"):
        raise NotImplementedError(
            f"KVStore type '{base}' is an external-integration escape hatch "
            "in the reference; the TPU build's multi-process path is the "
            "dist_* types over jax.distributed (mxnet_tpu.distributed)")
    if base.startswith("dist_") and jax.process_count() == 1:
        raise RuntimeError(
            f"KVStore type '{base}' needs a multi-process run: initialize "
            "with mxnet_tpu.distributed.init() (or launch via "
            "tools/launch.py) so jax.process_count() > 1; for single-process "
            "multi-device use 'device'")
    return KVStore(base)


def _as_list(v):
    return v if isinstance(v, (list, tuple)) else [v]


@jax.jit
def _sum_arrays(arrs):
    out = arrs[0]
    for a in arrs[1:]:
        out = out + a
    return out


@jax.jit
def _quant_2bit(grad, residual, threshold):
    """ref: gradient_compression.cc — 2-bit quantization with error feedback:
    values beyond ±threshold become ±threshold, the rest 0; the quantization
    error accumulates in the residual."""
    acc = grad + residual
    q = jnp.where(acc >= threshold, threshold,
                  jnp.where(acc <= -threshold, -threshold, 0.0)).astype(acc.dtype)
    return q, acc - q


@jax.jit
def _pack_2bit(q):
    """Pack quantized ±t/0 values into the 2-bit wire format (4 values per
    byte; codes 0→0, +t→1, −t→2 — ref: gradient_compression.cc Quantize2Bit
    packs the same way into uint32 words).  This is what actually crosses
    the network in dist mode: 16× smaller than f32."""
    flat = q.ravel()
    n = flat.shape[0]
    pad = (-n) % 4
    codes = jnp.where(flat > 0, 1, jnp.where(flat < 0, 2, 0)).astype(jnp.uint8)
    codes = jnp.pad(codes, (0, pad))
    c = codes.reshape(-1, 4)
    return (c[:, 0] | (c[:, 1] << 2) | (c[:, 2] << 4) | (c[:, 3] << 6))


@functools.partial(jax.jit, static_argnums=(2, 3))
def _unpack_sum_2bit(gathered, threshold, shape, dtype):
    """Decode every peer's packed payload and sum — ONE fused dispatch for
    the whole (P, nbytes) gathered array (the hot dist-gradient path)."""
    n = 1
    for s in shape:
        n *= s
    b = gathered  # (P, nbytes) uint8
    codes = jnp.stack([b & 3, (b >> 2) & 3, (b >> 4) & 3, (b >> 6) & 3],
                      axis=-1).reshape(b.shape[0], -1)[:, :n]
    vals = jnp.where(codes == 1, threshold,
                     jnp.where(codes == 2, -threshold, 0.0))
    return vals.sum(axis=0).astype(dtype).reshape(shape)


class KVStore:
    """Single-controller KVStore (ref: class KVStoreLocal / KVStoreDist).

    Each key holds one logical array (possibly sharded over a mesh — sharding
    survives push/pull untouched).  Pushing a list of values merges them by
    summation, the reference's CommDevice::Reduce; in a `jax.distributed`
    multi-process run the arrays are global and the jitted sum lowers to an
    ICI/DCN collective.
    """

    def __init__(self, kv_type="local"):
        self._type = kv_type
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._opt_states = {}
        self._compression = None   # (type, threshold)
        self._residuals = {}
        self._key_index = {}       # key -> dense optimizer index

    # -------------------------------------------------------------- basics --
    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        return jax.process_index()

    @property
    def num_workers(self):
        return jax.process_count()

    @property
    def _is_dist(self):
        return self._type.startswith("dist_") and jax.process_count() > 1

    def init(self, key, value):
        """ref: KVStore::Init — one-time per-key allocation; in dist mode
        rank 0's value is broadcast so every worker starts identically
        (ref: kvstore_dist.h InitImpl pushes only from rank 0)."""
        from .. import distributed
        for k, v in zip(_as_list(key), _as_list(value)):
            k = str(k)
            if k in self._store:
                continue
            arr = jnp.asarray(v._data if isinstance(v, NDArray) else v)
            if self._is_dist:
                arr = distributed.broadcast(arr, root=0)
            self._store[k] = NDArray(arr)

    # ---------------------------------------------------------------- push --
    def push(self, key, value, priority=0):
        """ref: KVStore::Push — merge pushed values into the store; with an
        optimizer attached (update_on_kvstore), run the update server-side.
        row_sparse values take the lazy path: only pushed rows are merged
        and updated (ref: kvstore_dist_server.h DataHandleRowSparse)."""
        from ..sparse import RowSparseNDArray
        keys, vals = self._key_value_lists(key, value)
        for k, vlist in zip(keys, vals):
            if k not in self._store:
                raise KeyError(f"key '{k}' was not init()ed")
            if all(isinstance(v, RowSparseNDArray) for v in vlist):
                self._push_rsp(k, vlist)
                continue
            # mixed dense+rsp lists ride the dense wire (a partial rsp merge
            # has no well-defined row set)
            arrs = [v.tostype("default")._data
                    if isinstance(v, RowSparseNDArray)
                    else (v._data if isinstance(v, NDArray)
                          else jnp.asarray(v))
                    for v in vlist]
            merged = arrs[0] if len(arrs) == 1 else _sum_arrays(arrs)
            if self._compression is not None:
                thr = self._compression[1]
                res = self._residuals.get(k)
                if res is None:
                    res = jnp.zeros_like(merged)
                merged, res = _quant_2bit(merged, res, thr)
                self._residuals[k] = res
            if self._is_dist:
                # dist_sync merge: sum each worker's (compressed) push across
                # processes — the server-side reduce of kvstore_dist_server.h
                from .. import distributed
                if self._compression is not None:
                    # ship the 2-bit wire format (16× less DCN traffic),
                    # decode + sum all peers in one fused dispatch
                    thr = self._compression[1]
                    gathered = distributed.all_gather(_pack_2bit(merged))
                    merged = _unpack_sum_2bit(
                        gathered, jnp.asarray(thr, merged.dtype),
                        tuple(merged.shape), str(merged.dtype))
                else:
                    merged = distributed.all_sum(merged)
            stored = self._store[k]
            if self._optimizer is not None:
                self._server_update(k, stored, NDArray(merged))
            elif self._updater is not None:
                self._updater(k, NDArray(merged), stored)
            else:
                stored._data = merged

    def _server_update(self, k, stored, grad):
        """Apply the attached optimizer server-side (ref:
        kvstore_dist_server.h DataHandleEx).  Dense per-key optimizer index
        so string keys get distinct update counts / state slots: digit keys
        keep their value; string keys get negative indices, a namespace no
        digit key can collide with."""
        idx = self._key_index.setdefault(
            k, int(k) if k.isdigit() else -(len(self._key_index) + 1))
        if k not in self._opt_states:
            self._opt_states[k] = \
                self._optimizer.create_state_multi_precision(idx, stored)
        self._optimizer.update_multi_precision(
            idx, stored, grad, self._opt_states[k])

    def _push_rsp(self, k, vlist):
        """row_sparse push: union-merge pushed row sets, then lazy-update or
        store only those rows (ref: kvstore_dist_server.h
        DataHandleRowSparse; comm.h CommCPU::ReduceRowSparse)."""
        from .. import sparse as _sp
        if self._compression is not None:
            raise ValueError(
                "gradient compression does not support row_sparse push "
                "(the reference restricts 2bit to dense too)")
        merged = vlist[0]
        for v in vlist[1:]:
            merged = _sp.add(merged, v)
        if self._is_dist:
            # cross-process reduce rides the dense wire format (row sets
            # differ per worker; variable-length allgather would fight XLA's
            # static shapes — SURVEY §7.0's "let the compiler schedule it")
            from .. import distributed
            dense = distributed.all_sum(merged.tostype("default")._data)
            merged = _sp.cast_storage(NDArray(dense), "row_sparse")
        stored = self._store[k]
        if self._optimizer is not None:
            self._server_update(k, stored, merged)
        elif self._updater is not None:
            self._updater(k, merged, stored)
        else:
            # merge ONLY the pushed rows (DataHandleRowSparse semantics);
            # densifying here would zero every absent row of the store
            stored._data = stored._data.at[merged._indices].set(
                merged._data.astype(stored._data.dtype))

    # ---------------------------------------------------------------- pull --
    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """ref: KVStore::Pull."""
        keys = [str(k) for k in _as_list(key)]
        results = []
        for k in keys:
            if k not in self._store:
                raise KeyError(f"key '{k}' was not init()ed")
            results.append(self._store[k])
        if out is not None:
            outs = _as_list(out)
            # broadcast each key's value into its output(s): either 1:1, or
            # an equal number of device-replica outputs per key
            if len(outs) % len(results) != 0:
                raise ValueError(
                    f"pull: {len(outs)} outputs for {len(results)} keys")
            per_key = len(outs) // len(results)
            for i, o in enumerate(outs):
                o._data = results[i // per_key]._data
            return None
        return results if len(results) > 1 else results[0]

    def pushpull(self, key, value, out=None, priority=0):
        """ref: KVStore::PushPull (fused, the dist_sync_device fast path)."""
        from ..sparse import RowSparseNDArray
        if out is None and any(isinstance(v, RowSparseNDArray)
                               for v in _as_list(value)):
            raise ValueError(
                "pushpull with a row_sparse value needs an explicit dense "
                "out= (a dense pull cannot land in sparse storage); or use "
                "push + row_sparse_pull(row_ids=...)")
        self.push(key, value, priority)
        self.pull(key, out=out if out is not None else value, priority=priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the requested rows as row_sparse (ref:
        KVStoreLocal::PullRowSparse) — the communication-shaped pull a
        sparse-embedding Trainer issues after each push.  Without row_ids
        the pull degenerates to dense."""
        from ..sparse import RowSparseNDArray
        if row_ids is None:
            return self.pull(key, out=out, priority=priority)
        keys = [str(k) for k in _as_list(key)]
        rids = _as_list(row_ids)
        if len(rids) == 1 and len(keys) > 1:
            rids = rids * len(keys)
        if len(rids) != len(keys):
            raise ValueError(
                f"row_sparse_pull: {len(rids)} row_id lists for "
                f"{len(keys)} keys")
        results = []
        for k, rid in zip(keys, rids):
            if k not in self._store:
                raise KeyError(f"key '{k}' was not init()ed")
            ridx = jnp.unique(jnp.asarray(
                rid._data if isinstance(rid, NDArray) else rid, jnp.int32))
            stored = self._store[k]
            results.append(RowSparseNDArray(
                stored._data[ridx], ridx, tuple(stored.shape)))
        if out is not None:
            outs = _as_list(out)
            if len(outs) % len(results) != 0:
                raise ValueError(
                    f"row_sparse_pull: {len(outs)} outputs for "
                    f"{len(results)} keys")
            per_key = len(outs) // len(results)
            for i, o in enumerate(outs):
                r = results[i // per_key]
                if isinstance(o, RowSparseNDArray):
                    o._data, o._indices = r._data, r._indices
                    o.shape = r.shape
                else:  # dense target: overwrite just the pulled rows
                    o._data = o._data.at[r._indices].set(
                        r._data.astype(o._data.dtype))
            return None
        return results if len(results) > 1 else results[0]

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out=out, priority=priority)

    # ----------------------------------------------------------- optimizer --
    def set_optimizer(self, optimizer):
        """ref: KVStore::SetOptimizer → server-side updates
        (kvstore_dist_server.h DataHandleEx)."""
        self._optimizer = optimizer

    def is_capable(self, capability):
        return {"optimizer": True}.get(capability, False)

    def _set_updater(self, updater):
        """ref: KVStore::set_updater — python updater fn(key, recv, stored)."""
        self._updater = updater

    def set_gradient_compression(self, compression_params):
        """ref: KVStore::SetGradientCompression — {'type': '2bit',
        'threshold': t}."""
        ctype = compression_params.get("type", "2bit")
        if ctype != "2bit":
            raise ValueError(f"unsupported compression '{ctype}'")
        thr = float(compression_params.get("threshold", 0.5))
        self._compression = (ctype, thr)

    # ------------------------------------------------------------ plumbing --
    def _key_value_lists(self, key, value):
        keys = [str(k) for k in _as_list(key)]
        if len(keys) == 1:
            return keys, [_as_list(value)]
        vals = []
        for k, v in zip(keys, _as_list(value)):
            vals.append(_as_list(v))
        return keys, vals

    def save_optimizer_states(self, fname, dump_optimizer=False):
        from .. import ndarray as nd
        d = {}
        for k, st in self._opt_states.items():
            for j, arr in enumerate(_flatten(st)):
                d[f"{k}.{j}"] = arr
        nd.save(fname, d)

    def load_optimizer_states(self, fname):
        from .. import ndarray as nd
        loaded = nd.load(fname)
        for k, st in self._opt_states.items():
            for j, arr in enumerate(_flatten(st)):
                kk = f"{k}.{j}"
                if kk in loaded:
                    arr._data = loaded[kk]._data.astype(arr._data.dtype)

    def __repr__(self):
        return f"KVStore(type={self._type}, keys={len(self._store)})"


def _flatten(state):
    if state is None:
        return []
    if isinstance(state, NDArray):
        return [state]
    out = []
    for s in state:
        out.extend(_flatten(s))
    return out
