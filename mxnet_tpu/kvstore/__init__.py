"""KVStore — the reference's distributed parameter store, TPU-native.

ref: src/kvstore/kvstore_local.{h,cc} (types "local"/"device"),
kvstore_nccl.h ("nccl"), kvstore_dist.h ("dist_sync"/"dist_async"/
"dist_sync_device" over ps-lite), comm.h (CommCPU/CommDevice reduce),
gradient_compression.{h,cc} (2-bit stochastic quantization).

TPU-native mapping (SURVEY.md §5.8): the push/pull/pushpull *semantics* are
preserved — per-key init, aggregation of pushed values, optional server-side
optimizer update (`update_on_kvstore`), gradient compression — but the
*mechanism* is jax: aggregation is a jitted sum (XLA collective when values
live on a mesh), there are no server processes, and the multi-worker case
rides `jax.distributed` + global arrays rather than ZeroMQ.  The heavy-duty
data-parallel path is mxnet_tpu.parallel.TrainStep, which fuses what
KVStore+optimizer do into the training program; KVStore remains for API
parity and for update_on_kvstore workflows.
"""
from .kvstore import KVStore, create

__all__ = ["KVStore", "create"]
