"""Continuous-batching LLM serving: paged KV cache + one-executable decode.

Every other serving surface in the stack batches *requests*; an
autoregressive LM needs token-level batching — sequences join and leave
the in-flight batch at every decode step.  Done naively (one jitted call
per sequence, a dense ``[max_len]`` cache per sequence) that is the worst
possible shape for a bandwidth-bound chip: recompiles keyed on traffic,
and HBM reserved for contexts that mostly aren't there.  This module is
the PAPERS.md *Ragged Paged Attention* / Gemma-serving design
(arXiv:2604.15464, 2605.25645) on top of the PR 4 serving substrate:

- **Paged KV cache** — one fixed pool ``[n_layers, n_pages, page_size,
  heads, head_dim]`` per K and V; sequences hold *pages* through a page
  table and a host-side free list (``PageAllocator``).  HBM cost is the
  pool, a configuration constant sized for expected concurrency — not
  ``n_slots × max_len`` dense stripes (the costguard
  ``llm_decode_step`` vs ``llm_decode_step_dense`` golden pair commits
  the ≥ 40% argument-bytes win in tier-1).
- **One pinned decode executable** — every decode step, whatever the
  in-flight mix of sequence lengths/ages/sampling modes, runs the SAME
  jitted program over a fixed slot grid: slot-mask + page-table + length
  arrays are the arguments, shapes are constants.  Traffic can never
  recompile; the executable census is ``len(batch buckets) ×
  len(length buckets) + 1`` (prefill grid + decode), asserted against
  the runtime jit-cache count in tests.
- **Continuous-batching scheduler** (``GenerationServer``) — prompts
  prefill through the existing ``BucketSpec`` length buckets (each
  bucket warmup-compiled before readiness), sequences are admitted into
  fixed decode slots, retire per-step on EOS/max-tokens/deadline (pages
  freed and queued sequences admitted the *same* step), and pool
  exhaustion preempts the youngest sequence back onto the queue instead
  of deadlocking.  Admission control (bounded queue, token bucket,
  deadlines, ``Request`` futures), the circuit breaker, ``healthz`` and
  ``drain()``/SIGTERM semantics are all the PR 4 pieces reused: an
  accepted sequence ALWAYS resolves to tokens or an explicit error.

Sampling is greedy or temperature/top-k per request, drawn from a
PER-POSITION PRNG schedule inside the compiled program: every sequence
carries its own sampling seed (derived from the server seed and its
admission ordinal, or set explicitly at ``submit``) and the key for the
token at absolute position ``p`` of prompt+output is
``fold_in(PRNGKey(seed), p)`` — a pure function of (sequence, position),
never of the step counter or slot index.  That is what makes generation
RESUMABLE token-exact (ISSUE 19): a sequence preempted, salvaged off a
failed step, handed to another replica, or restored from the decode
journal after kill -9 re-prefills its prompt + generated-so-far through
the existing bucket grid and then samples the IDENTICAL future tokens
the uninterrupted run would have (greedy and seeded sampling alike).
``SequenceSnapshot`` is the portable resume state; ``drain(handoff=
True)`` exports it instead of finishing, and ``restore_journal``
re-imports a crashed sibling's in-flight set.

``tp_shards=N`` shards the whole stack tensor-parallel over an N-way
``tp`` mesh (``parallel.mesh``): head-parallel paged attention (each
device owns a head shard of the page pools), Megatron column/row
sharded projections/FFN, and per-layer activation all-reduces on the
decode path in f32 or chunked-int8 wire format
(``tp_collectives=``, ``parallel.quantize.all_reduce_activations``).
The census, scheduler, and failure semantics are shard-count
invariant — see the ``GenerationServer`` docstring.

Failure paths are deterministic tests via the ``generate.prefill`` /
``generate.decode`` / ``generate.evict`` fault points
(``tools/chaos_check.py --mode llm`` drives all of them plus SIGTERM).
"""
from __future__ import annotations

import collections
import threading
import time

import numpy as np

import queue

from .. import fault as _fault
from .. import profiler as _profiler
from .. import telemetry as _telemetry
from .admission import (CircuitOpenError, DeadlineExceededError,
                        RejectedError, Request, ServerClosedError,
                        TenantQoS, TokenBucket)
from .batcher import BucketSpec
from .breaker import CircuitBreaker

__all__ = ["PageAllocator", "PoolExhaustedError", "GenerationServer",
           "SequenceSnapshot", "build_decode_step", "build_prefill_step",
           "build_prefill_kv_step", "build_handoff_step",
           "build_dense_decode_step", "build_verify_step",
           "prefix_admission_plan"]


class PoolExhaustedError(RuntimeError):
    """The page pool has no free page.  Internal scheduler signal — the
    decode loop preempts a sequence and retries; it never reaches a
    client, who instead sees either admission-time ``RejectedError``
    (a request whose worst case could never fit) or a later result."""


class PageAllocator:
    """Host-side REFCOUNTED free list over the fixed page pool.

    Page 0 is reserved as the *write sink*: masked/inactive lanes of the
    compiled programs scatter their K/V there, so the executables never
    branch on occupancy.  Pages ``1..n_pages-1`` are allocatable.  All
    methods are thread-safe (one lock, no blocking under it); the free
    list is LIFO, so a freed sequence's pages are immediately reused —
    fragmentation cannot accrete by construction (any free page serves
    any sequence; there is nothing contiguous to fragment).

    **Prefix sharing (ISSUE 16).**  Every live page carries a refcount:
    ``alloc`` hands out pages at refcount 1, ``share`` maps additional
    holders onto already-resident pages (a prompt whose leading blocks
    are already cached pays NOTHING for them), and ``free`` decrements —
    a page returns to the free list only when its LAST holder lets go.
    The allocator stays layout-free (a page id addresses every tp
    shard's stripe of that page at once), so sharing composes with
    head-sharded pools with no extra bookkeeping.  ``free`` on a page
    this allocator does not consider live (double-free, or an id that
    was never allocated) raises ``ValueError`` instead of silently
    corrupting the free list — load-bearing once refcounts arbitrate
    page lifetime across sequences."""

    def __init__(self, n_pages, page_size):
        if n_pages < 2:
            raise ValueError("PageAllocator: need >= 2 pages (page 0 is "
                             "the reserved write sink)")
        if page_size < 1:
            raise ValueError("PageAllocator: page_size must be >= 1")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self._lock = threading.Lock()
        self._free = list(range(1, self.n_pages))   # LIFO tail = next out
        self._refs = {}                             # page -> live refcount

    @property
    def allocatable(self):
        """Pages a sequence can ever hold (pool minus the sink)."""
        return self.n_pages - 1

    def free_count(self):
        with self._lock:
            return len(self._free)

    def pages_for(self, n_tokens):
        """Pages needed to hold ``n_tokens`` cache entries."""
        return -(-int(n_tokens) // self.page_size)

    def alloc(self, n_pages):
        """Take ``n_pages`` pages or raise ``PoolExhaustedError`` (taking
        nothing — allocation is all-or-nothing so a half-admitted
        sequence can never strand pages).  Fresh pages start at
        refcount 1."""
        n = int(n_pages)
        if n <= 0:
            return []     # a fully shared prompt allocates nothing
        with self._lock:
            if n > len(self._free):
                raise PoolExhaustedError(
                    f"need {n} pages, {len(self._free)} free "
                    f"(pool {self.allocatable})")
            taken, self._free[-n:] = self._free[-n:], []
            for p in taken:
                self._refs[p] = 1
            return taken

    def share(self, pages):
        """Add one holder to each of ``pages`` (all must be live) —
        the prefix-sharing mapping: the new sequence holds the SAME
        resident pages instead of allocating copies.  Raises
        ``ValueError`` on a page that is not live (the prefix index
        may only hand out pages somebody still holds)."""
        with self._lock:
            for p in pages:
                if p not in self._refs:
                    raise ValueError(
                        f"PageAllocator.share: page {p} is not live — "
                        f"the prefix index handed out a freed page")
            for p in pages:
                self._refs[p] += 1
        return list(pages)

    def refcount(self, page):
        """Live holders of ``page`` (0 when free/unknown)."""
        with self._lock:
            return self._refs.get(int(page), 0)

    def shared_pages(self):
        """Pages currently held by MORE than one sequence."""
        with self._lock:
            return sum(1 for c in self._refs.values() if c > 1)

    def extra_refs(self):
        """Total holders beyond the first, over all live pages — the
        number of page copies prefix sharing made unnecessary
        (``bytes_saved_by_sharing`` = this x page bytes)."""
        with self._lock:
            return sum(c - 1 for c in self._refs.values() if c > 1)

    def live_pages(self):
        """Count of live (allocated, refcount >= 1) pages."""
        with self._lock:
            return len(self._refs)

    def free(self, pages):
        """Drop one holder from each of ``pages``; a page whose LAST
        holder lets go returns to the LIFO free list.  Returns the list
        of pages actually released (the caller's prefix index drops
        exactly those).  A page with no live refcount — a double free,
        or an id never allocated — raises ``ValueError`` with nothing
        freed: silently extending the free list would hand the same
        page to two sequences and corrupt both caches."""
        with self._lock:
            drops = {}
            for p in pages:
                drops[p] = drops.get(p, 0) + 1
            for p, n in drops.items():
                if self._refs.get(p, 0) < n:
                    raise ValueError(
                        f"PageAllocator.free: page {p} is not live "
                        f"(double free, or never allocated) — refusing "
                        f"to corrupt the free list")
            released = []
            for p in pages:
                self._refs[p] -= 1
                if self._refs[p] == 0:
                    del self._refs[p]
                    self._free.append(p)
                    released.append(p)
            return released


# --------------------------------------------------------------- samplers --
def _scaled_masked(logits, temps, topks):
    """Temperature-scaled, top-k-masked logits — the SHARED sampling
    transform: ``softmax`` of this is each row's sampling distribution.
    Factored out of ``_sample_tokens`` because the speculative verify
    step must evaluate the SAME distribution twice (the draft's ``q``
    and the target's ``p``) for the acceptance ratio to be exact."""
    import jax.numpy as jnp

    vocab = logits.shape[-1]
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    order = jnp.sort(scaled, axis=-1)[:, ::-1]          # descending
    kidx = jnp.clip(topks - 1, 0, vocab - 1)
    thr = jnp.take_along_axis(order, kidx[:, None], axis=1)
    cut = (topks[:, None] > 0) & (scaled < thr)
    return jnp.where(cut, jnp.asarray(-1e30, scaled.dtype), scaled)


def _position_keys(seeds, positions, domain=None):
    """The per-position PRNG schedule (ISSUE 19): the key for row ``i``
    is ``fold_in(PRNGKey(seeds[i]), positions[i])`` — a pure function
    of (sequence seed, absolute token position), never of the step
    counter or the slot index.  A resumed sequence therefore draws the
    IDENTICAL randomness the uninterrupted run would have at every
    future position.  ``domain`` sub-derives disjoint streams for the
    speculative roles (draft proposal / acceptance / correction) that
    all consume randomness at the same position."""
    import jax

    def one(sd, p):
        k = jax.random.fold_in(jax.random.PRNGKey(sd), p)
        return k if domain is None else jax.random.fold_in(k, domain)
    return jax.vmap(one)(seeds, positions)


def _sample_tokens(logits, seeds, positions, temps, topks):
    """Per-slot next-token choice inside the compiled program: greedy
    where ``temps == 0``, temperature softmax-sampling elsewhere, with
    an optional top-k cut (``topks > 0``).  Both arms always compute —
    that is what keeps a mixed greedy/sampling batch ONE executable —
    and row ``i`` draws from its position-keyed stream
    ``fold_in(PRNGKey(seeds[i]), positions[i])``."""
    import jax
    import jax.numpy as jnp

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    masked = _scaled_masked(logits, temps, topks)
    keys = _position_keys(seeds, positions)
    drawn = jax.vmap(jax.random.categorical)(keys, masked).astype(jnp.int32)
    return jnp.where(temps > 0.0, drawn, greedy)


# -------------------------------------------------------- program builders --
def _tp_pieces(config, mesh, axis):
    """Shared tensor-parallel plumbing of the program builders: shard
    count, local head count, the param/pool PartitionSpecs, and the
    ``shard_map`` wrapper (``parallel.mesh`` — call-time axis
    validation) curried with the mesh."""
    import functools

    from jax.sharding import PartitionSpec

    from ..gluon.model_zoo.causal_lm import tp_param_specs, tp_validate
    from ..parallel.mesh import shard_map

    shards = int(mesh.shape[axis])
    tp_validate(config, shards)
    pspecs = tp_param_specs(config, mesh, axis)
    pool_spec = PartitionSpec(None, None, None, axis, None)
    repl = PartitionSpec()
    wrap = functools.partial(shard_map, mesh=mesh, check_vma=False)
    return shards, config.n_heads // shards, pspecs, pool_spec, repl, wrap


def build_decode_step(config, page_size, attention_impl=None, mesh=None,
                      tp_axis="tp", tp_collectives="f32"):
    """The ONE decode executable: every in-flight mix of sequences runs
    this program over the fixed slot grid.

    Signature (all shapes configuration constants):
      ``(params, k_pool, v_pool, tokens[S], lengths[S], active[S],
      tables[S, P], cow_src[S], cow_dst[S], seeds[S], temps[S],
      topks[S])`` → ``(next_tokens[S], k_pool, v_pool)``.

    ``seeds[s]`` is slot ``s``'s per-sequence sampling seed; the next
    token (absolute position ``lengths[s] + 1`` of prompt+output) is
    drawn from ``fold_in(PRNGKey(seeds[s]), lengths[s] + 1)`` — the
    position-keyed schedule that makes resumed sequences token-exact
    (ISSUE 19).

    ``lengths[s]`` is the slot's cache occupancy BEFORE this step; the
    input token's K/V is written at position ``lengths[s]`` (page
    ``tables[s, lengths[s] // page_size]``), inactive slots sink to
    page 0, and attention covers ``lengths[s] + 1`` positions.  Pools
    are donated by the caller, so the update is in-place on device.

    ``cow_src``/``cow_dst`` are the copy-on-write fault lanes (ISSUE
    16): before anything else the program copies page ``cow_src[s]``
    onto page ``cow_dst[s]`` in both pools — the in-graph K/V page copy
    of a sequence diverging from a shared prefix, already remapped in
    ``tables`` by the host.  Slots without a fault pass ``(0, 0)``, a
    self-copy of the sink page — so the copy is ALWAYS part of the one
    pinned program and a CoW fault can never compile anything.

    With ``mesh`` (a ``tp_axis`` mesh) the SAME program lowers once
    over the mesh as one ``shard_map``: each device owns a head shard
    of the K/V pools (head-parallel paged attention — per-device pool
    HBM ∝ 1/shards), QKV/FFN-in are column-sharded and the output/
    FFN-out projections row-sharded (Megatron), and the two per-layer
    partial-product all-reduces run through
    ``parallel.quantize.all_reduce_activations`` in the
    ``tp_collectives`` wire format (``"f32"`` | ``"int8"`` — EQuARX:
    decode is latency-bound on collective bytes).  Slot state, tokens,
    and the sampled output stay replicated, so the serving loop drives
    both shapes identically."""
    import jax.numpy as jnp

    from ..gluon.model_zoo.causal_lm import decode_hidden, lm_logits
    from ..ops.paged_attention import paged_decode_attention
    from ..parallel.quantize import (ACTIVATION_REDUCE_MODES,
                                     all_reduce_activations)

    if tp_collectives not in ACTIVATION_REDUCE_MODES:
        raise ValueError(f"tp_collectives={tp_collectives!r} not in "
                         f"{ACTIVATION_REDUCE_MODES}")
    n_layers = config.n_layers
    heads, head_dim = config.n_heads, config.head_dim
    if mesh is None:
        shards, heads_l, reduce_fn = 1, heads, None
    else:
        shards, heads_l, pspecs, pool_spec, repl, wrap = _tp_pieces(
            config, mesh, tp_axis)

        def reduce_fn(x):
            return all_reduce_activations(x, tp_axis, shards,
                                          mode=tp_collectives)

    def decode_step(params, k_pool, v_pool, tokens, lengths, active,
                    tables, cow_src, cow_dst, seeds, temps, topks):
        slots = tokens.shape[0]
        # CoW fault lanes first: dst pages take on src pages' content
        # BEFORE this step's writes/reads (faultless slots self-copy
        # the page-0 sink).  The gather reads the pre-step pool, so a
        # lane whose src page was concurrently recycled still copies
        # the prefix content it diverged from.
        k_pool = k_pool.at[:, cow_dst].set(k_pool[:, cow_src])
        v_pool = v_pool.at[:, cow_dst].set(v_pool[:, cow_src])
        h = params["embed"][tokens]                     # [S, d]
        pos = lengths
        page = jnp.take_along_axis(tables, (pos // page_size)[:, None],
                                   axis=1)[:, 0]
        page = jnp.where(active, page, 0)               # sink inactive
        off = pos % page_size
        att_len = jnp.where(active, lengths + 1, 0)

        for layer in range(n_layers):
            def attend(q, k, v, _l=layer):
                nonlocal k_pool, v_pool
                k = k.reshape(slots, heads_l, head_dim)
                v = v.reshape(slots, heads_l, head_dim)
                q = q.reshape(slots, heads_l, head_dim)
                k_pool = k_pool.at[_l, page, off].set(k)
                v_pool = v_pool.at[_l, page, off].set(v)
                return paged_decode_attention(q, k_pool[_l], v_pool[_l],
                                              tables, att_len,
                                              impl=attention_impl)
            h = decode_hidden(params, layer, h, attend, reduce=reduce_fn)
        nxt = _sample_tokens(lm_logits(params, h), seeds, lengths + 1,
                             temps, topks)
        return nxt, k_pool, v_pool

    if mesh is None:
        return decode_step
    return wrap(decode_step,
                in_specs=(pspecs, pool_spec, pool_spec) + (repl,) * 9,
                out_specs=(repl, pool_spec, pool_spec))


def build_prefill_step(config, page_size, attention_impl=None, mesh=None,
                       tp_axis="tp"):
    """One prefill executable per ``(batch, length)`` bucket: the whole
    prompt forward (``causal_lm.prefill_forward``), K/V scattered into
    the paged pools by page table, and the FIRST new token sampled —
    so a prefilled sequence enters the decode grid already one token
    ahead.  Padded rows/positions sink their writes to page 0.

    With ``mesh`` the forward is Megatron-sharded like the decode step
    and each device scatters its OWN head shard of the prompt K/V into
    its pool shard.  Prefill collectives stay f32: the prompt forward
    is compute-bound, not latency-bound on collective bytes (the
    ``tp_collectives`` knob is a decode-path trade)."""
    import jax
    import jax.numpy as jnp

    from ..gluon.model_zoo.causal_lm import prefill_forward

    del attention_impl      # prefill is dense-causal (ops.multi_head_attention)

    if mesh is None:
        reduce_fn = None
    else:
        shards, _hl, pspecs, pool_spec, repl, wrap = _tp_pieces(
            config, mesh, tp_axis)

        def reduce_fn(x):
            return jax.lax.psum(x, tp_axis)

    def prefill_step(params, k_pool, v_pool, tokens, lengths, active,
                     tables, seeds, temps, topks):
        b, L = tokens.shape
        logits, k_all, v_all = prefill_forward(params, config, tokens,
                                               lengths, reduce=reduce_fn)
        pos = jnp.arange(L)
        valid = (pos[None, :] < lengths[:, None]) & active[:, None]
        page = jnp.where(valid, tables[:, pos // page_size], 0)  # [b, L]
        off = jnp.broadcast_to((pos % page_size)[None, :], (b, L))
        for layer in range(config.n_layers):
            k_pool = k_pool.at[layer, page, off].set(k_all[layer])
            v_pool = v_pool.at[layer, page, off].set(v_all[layer])
        # the first generated token sits at absolute position lengths[i]
        # (0-based) of prompt+output — same schedule the decode step
        # continues at lengths + 1
        first = _sample_tokens(logits, seeds, lengths, temps, topks)
        return first, k_pool, v_pool

    if mesh is None:
        return prefill_step
    return wrap(prefill_step,
                in_specs=(pspecs, pool_spec, pool_spec) + (repl,) * 7,
                out_specs=(repl, pool_spec, pool_spec))


def build_prefill_kv_step(config, attention_impl=None, mesh=None,
                          tp_axis="tp"):
    """The DISAGGREGATED prefill executable (one per ``(batch, length)``
    bucket): whole-prompt forward returning the first sampled token plus
    the prompt's K/V stacked ``[n_layers, b, L, heads, head_dim]`` —
    and NO pool arguments.  Because it neither reads nor donates the
    paged pools, it can run on a PREFILL-group worker concurrently with
    the decode group's pinned step: a 2048-token prompt no longer stalls
    every in-flight decode for its step, and a failed prefill can no
    longer consume the donated pools out from under the decode group's
    bystanders.  The output is the handoff payload ``build_handoff_step``
    scatters into the decode group's pool.

    With ``mesh`` the forward is Megatron-sharded (f32 collectives, see
    ``build_prefill_step``) and the payload comes back with its head
    axis sharded over ``tp_axis`` — the wire shape the sharded handoff
    scatter consumes."""
    import jax
    import jax.numpy as jnp

    from ..gluon.model_zoo.causal_lm import prefill_forward

    del attention_impl      # prefill is dense-causal (ops.multi_head_attention)

    if mesh is None:
        reduce_fn = None
    else:
        shards, _hl, pspecs, pool_spec, repl, wrap = _tp_pieces(
            config, mesh, tp_axis)

        def reduce_fn(x):
            return jax.lax.psum(x, tp_axis)

    def prefill_kv_step(params, tokens, lengths, seeds, temps, topks):
        logits, k_all, v_all = prefill_forward(params, config, tokens,
                                               lengths, reduce=reduce_fn)
        first = _sample_tokens(logits, seeds, lengths, temps, topks)
        # zero the padding positions so the handoff buffer stays inert
        # wherever lengths don't reach (the scatter sinks them to page 0
        # anyway — this just keeps the payload deterministic)
        L = tokens.shape[1]
        valid = (jnp.arange(L)[None, :]
                 < lengths[:, None])[None, :, :, None, None]
        return first, jnp.where(valid, k_all, 0.0), \
            jnp.where(valid, v_all, 0.0)

    if mesh is None:
        return prefill_kv_step
    return wrap(prefill_kv_step,
                in_specs=(pspecs,) + (repl,) * 5,
                out_specs=(repl, pool_spec, pool_spec))


def build_handoff_step(config, page_size, mesh=None, tp_axis="tp"):
    """The ONE handoff executable of a disaggregated server: scatter a
    batch of prefilled sequences' K/V (``[n_layers, B, L, H, D]``, a
    FIXED ``(B, L)`` staging shape — the model of the prefill→decode
    wire transfer) into the decode group's paged pools by page table.
    Inactive lanes and positions past ``lengths`` sink to page 0.
    Pools are donated; shapes are configuration constants, so however
    sequences are re-packed across handoffs this is always the same
    program — the census grows by exactly one.

    With ``mesh`` the payload AND the pools are head-sharded over
    ``tp_axis``: each device scatters its own head shard, no
    collectives at all (the scatter indices are head-independent)."""
    import jax.numpy as jnp

    if mesh is not None:
        _sh, _hl, _ps, pool_spec, repl, wrap = _tp_pieces(
            config, mesh, tp_axis)

    def handoff_step(k_pool, v_pool, k_all, v_all, lengths, active,
                     tables):
        B, L = k_all.shape[1], k_all.shape[2]
        pos = jnp.arange(L)
        valid = (pos[None, :] < lengths[:, None]) & active[:, None]
        page = jnp.where(valid, tables[:, pos // page_size], 0)   # [B, L]
        off = jnp.broadcast_to((pos % page_size)[None, :], (B, L))
        for layer in range(config.n_layers):
            k_pool = k_pool.at[layer, page, off].set(k_all[layer])
            v_pool = v_pool.at[layer, page, off].set(v_all[layer])
        return k_pool, v_pool

    if mesh is None:
        return handoff_step
    return wrap(handoff_step,
                in_specs=(pool_spec, pool_spec, pool_spec, pool_spec,
                          repl, repl, repl),
                out_specs=(pool_spec, pool_spec))


def build_dense_decode_step(config, max_ctx, attention_impl=None):
    """The dense max-length-cache decode variant: identical model and
    sampling, but every slot owns a ``[max_ctx, H, D]`` stripe of
    ``[n_layers, slots, max_ctx, H, D]`` caches — the per-sequence HBM
    reservation the paged pool replaces.  Exists for the parity tests
    and as the costguard ``llm_decode_step_dense`` golden the paged
    win is committed against; the serving loop never runs it."""
    import jax.numpy as jnp

    from ..gluon.model_zoo.causal_lm import decode_hidden, lm_logits
    from ..ops.paged_attention import dense_decode_attention

    del attention_impl
    n_layers = config.n_layers
    heads, head_dim = config.n_heads, config.head_dim

    def dense_step(params, k_cache, v_cache, tokens, lengths, active,
                   seeds, temps, topks):
        slots = tokens.shape[0]
        h = params["embed"][tokens]
        row = jnp.arange(slots)
        pos = jnp.clip(lengths, 0, max_ctx - 1)
        att_len = jnp.where(active, lengths + 1, 0)

        for layer in range(n_layers):
            def attend(q, k, v, _l=layer):
                nonlocal k_cache, v_cache
                k = k.reshape(slots, heads, head_dim)
                v = v.reshape(slots, heads, head_dim)
                q = q.reshape(slots, heads, head_dim)
                k_cache = k_cache.at[_l, row, pos].set(k)
                v_cache = v_cache.at[_l, row, pos].set(v)
                return dense_decode_attention(q, k_cache[_l], v_cache[_l],
                                              att_len)
            h = decode_hidden(params, layer, h, attend)
        nxt = _sample_tokens(lm_logits(params, h), seeds, lengths + 1,
                             temps, topks)
        return nxt, k_cache, v_cache

    return dense_step


def build_verify_step(config, draft_cfg, page_size, spec_k, window,
                      attention_impl=None, mesh=None, tp_axis="tp",
                      tp_collectives="f32"):
    """The ONE speculative-decoding executable: a small draft LM
    proposes ``spec_k`` tokens and the target model scores all
    ``spec_k + 1`` positions in the SAME compiled program — the census
    grows by exactly one whatever the traffic does.

    Signature (all shapes configuration constants):
      ``(params, draft_params, k_pool, v_pool, tokens[S],
      window[S, W], n_valid[S], lengths[S], active[S], tables[S, P],
      cow_src[S], cow_dst[S], seeds[S], temps[S], topks[S])`` →
      ``(emitted[S, spec_k + 1], n_accept[S], k_pool, v_pool)``.

    Randomness follows the same position-keyed schedule as the decode
    step (``seeds[s]`` + absolute token position), with a disjoint
    domain per speculative role at each position — draft proposal
    (domain 1), acceptance uniform (2), correction/bonus draw (3) — so
    a resumed sequence replays the identical accept/reject trajectory
    the uninterrupted run would have taken (ISSUE 19).

    Per slot the program (1) applies the CoW fault copy exactly like
    ``build_decode_step``, (2) runs the draft ``spec_k`` times over a
    right-aligned dense token window (``window``/``n_valid`` — the
    draft needs no pool), sampling proposal ``d_i`` from the SAME
    tempered/top-k distribution family as the target, (3) flattens the
    ``spec_k + 1`` candidate positions of all slots into ``S*(k+1)``
    lanes of the paged target forward — K/V for every lane written at
    ``lengths[s] + i``, attention masked to ``lengths[s] + i + 1``, so
    causality per lane is exact — and (4) accepts a leading run of
    proposals.  Greedy slots accept while ``d_i`` equals the target
    argmax (token-identical to plain decode by construction); sampling
    slots accept ``d_i`` with probability ``min(1, p_i(d_i)/q_i(d_i))``
    and on rejection draw from ``normalize(max(p_i - q_i, 0))``
    (all-accepted slots draw the bonus token from ``p_k``) — the
    Leviathan/Chen speculative-sampling identity, so the emitted
    process is distribution-EXACT whatever the draft proposes.

    ``emitted[s, :n_accept[s] + 1]`` are the step's real tokens (the
    ``+1`` is the correction/bonus, which becomes the next pending
    token); later entries are dead lanes.  K/V written past the
    accepted run is stale but masked — ``lengths`` advances only over
    accepted tokens, and the next step overwrites those positions.

    With ``mesh`` the target forward shards exactly like
    ``build_decode_step`` (head-parallel pools, Megatron weights,
    ``tp_collectives`` wire format); the draft params stay replicated —
    a draft small enough to speculate with is small enough to
    replicate."""
    import jax
    import jax.numpy as jnp

    from ..gluon.model_zoo.causal_lm import (init_causal_lm,
                                             verify_logits, window_logits)
    from ..ops.paged_attention import paged_decode_attention
    from ..parallel.quantize import (ACTIVATION_REDUCE_MODES,
                                     all_reduce_activations)

    if tp_collectives not in ACTIVATION_REDUCE_MODES:
        raise ValueError(f"tp_collectives={tp_collectives!r} not in "
                         f"{ACTIVATION_REDUCE_MODES}")
    if int(spec_k) < 1:
        raise ValueError(f"spec_k must be >= 1, got {spec_k}")
    if draft_cfg.vocab_size != config.vocab_size:
        raise ValueError(
            f"draft vocab {draft_cfg.vocab_size} != target vocab "
            f"{config.vocab_size} — speculative acceptance compares "
            f"distributions over the SAME token space")
    k = int(spec_k)
    K1 = k + 1
    n_layers = config.n_layers
    heads, head_dim = config.n_heads, config.head_dim
    if mesh is None:
        heads_l, reduce_fn = heads, None
    else:
        shards, heads_l, pspecs, pool_spec, repl, wrap = _tp_pieces(
            config, mesh, tp_axis)
        # the draft is replicated: every leaf gets the empty spec (its
        # key set comes from an eval_shape init — zero device work)
        draft_pspecs = {name: repl for name in jax.eval_shape(
            lambda: init_causal_lm(draft_cfg, 0))}

        def reduce_fn(x):
            return all_reduce_activations(x, tp_axis, shards,
                                          mode=tp_collectives)

    def verify_step(params, draft_params, k_pool, v_pool, tokens, window,
                    n_valid, lengths, active, tables, cow_src, cow_dst,
                    seeds, temps, topks):
        S = tokens.shape[0]
        W = window.shape[1]
        # (1) CoW fault lanes, exactly as in the decode step
        k_pool = k_pool.at[:, cow_dst].set(k_pool[:, cow_src])
        v_pool = v_pool.at[:, cow_dst].set(v_pool[:, cow_src])

        # (2) draft proposes k tokens from the dense right-aligned
        # window (pool-free; the draft runs replicated under tp).  q_i
        # is the proposal distribution the acceptance ratio divides by
        # — the SAME tempered/top-k transform the target uses.
        # Proposal i is a candidate for absolute position
        # lengths + 1 + i — keyed there (domain 1).
        drafts, qprobs = [], []
        win, nv = window, n_valid
        for i in range(k):
            lg = window_logits(draft_params, draft_cfg, win, nv)
            masked = _scaled_masked(lg, temps, topks)
            qprobs.append(jax.nn.softmax(masked, axis=-1))
            keys_i = _position_keys(seeds, lengths + 1 + i, domain=1)
            drawn = jax.vmap(jax.random.categorical)(
                keys_i, masked).astype(jnp.int32)
            d_i = jnp.where(temps > 0.0, drawn,
                            jnp.argmax(lg, axis=-1).astype(jnp.int32))
            drafts.append(d_i)
            win = jnp.concatenate([win[:, 1:], d_i[:, None]], axis=1)
            nv = jnp.minimum(nv + 1, W)

        # (3) ONE target forward over S*(k+1) flattened lanes: lane
        # (s, i) holds candidate token i of slot s at position
        # lengths[s] + i.  All lanes write K/V first, then attend with
        # att_len = pos + 1 — later lanes see earlier candidates,
        # earlier lanes mask later writes: per-lane causality is exact.
        T = jnp.stack([tokens] + drafts, axis=1)          # [S, K1]
        lanes = S * K1
        pos_l = (lengths[:, None]
                 + jnp.arange(K1)[None, :]).reshape(lanes)
        tables_l = jnp.repeat(tables, K1, axis=0)         # [lanes, P]
        active_l = jnp.repeat(active, K1)
        page_l = jnp.take_along_axis(
            tables_l, (pos_l // page_size)[:, None], axis=1)[:, 0]
        page_l = jnp.where(active_l, page_l, 0)           # sink inactive
        off_l = pos_l % page_size
        att_len = jnp.where(active_l, pos_l + 1, 0)

        def attend(_l, q, kk, vv):
            nonlocal k_pool, v_pool
            kk = kk.reshape(lanes, heads_l, head_dim)
            vv = vv.reshape(lanes, heads_l, head_dim)
            q = q.reshape(lanes, heads_l, head_dim)
            k_pool = k_pool.at[_l, page_l, off_l].set(kk)
            v_pool = v_pool.at[_l, page_l, off_l].set(vv)
            return paged_decode_attention(q, k_pool[_l], v_pool[_l],
                                          tables_l, att_len,
                                          impl=attention_impl)
        logits = verify_logits(params, config, T, attend,
                               reduce=reduce_fn)          # [S, K1, V]

        # (4) leading-run acceptance, both arms always computed
        vocab = logits.shape[-1]
        d_all = jnp.stack(drafts, axis=1)                 # [S, k]
        q_all = jnp.stack(qprobs, axis=1)                 # [S, k, V]
        masked_all = _scaled_masked(
            logits.reshape(S * K1, vocab),
            jnp.repeat(temps, K1), jnp.repeat(topks, K1)
        ).reshape(S, K1, vocab)
        p_all = jax.nn.softmax(masked_all, axis=-1)       # [S, K1, V]
        tgt_greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        a_greedy = jnp.cumprod(
            (d_all == tgt_greedy[:, :k]).astype(jnp.int32),
            axis=1).sum(axis=1)
        p_d = jnp.take_along_axis(p_all[:, :k], d_all[:, :, None],
                                  axis=2)[..., 0]
        q_d = jnp.take_along_axis(q_all, d_all[:, :, None],
                                  axis=2)[..., 0]
        # one scalar uniform per (slot, proposal), keyed at the
        # proposal's absolute position (domain 2)
        prop_pos = (lengths[:, None]
                    + 1 + jnp.arange(k)[None, :]).reshape(S * k)
        ukeys = _position_keys(jnp.repeat(seeds, k), prop_pos, domain=2)
        u = jax.vmap(lambda kk: jax.random.uniform(kk, ()))(
            ukeys).reshape(S, k)
        a_sample = jnp.cumprod(
            (u <= p_d / jnp.maximum(q_d, 1e-30)).astype(jnp.int32),
            axis=1).sum(axis=1)
        a = jnp.where(temps > 0.0, a_sample, a_greedy).astype(jnp.int32)

        # correction at the first rejection (residual p - q, renormed;
        # a zero residual means p == q there — fall back to p), bonus
        # from p_k when everything was accepted
        resid = jnp.maximum(p_all[:, :k] - q_all, 0.0)
        rsum = resid.sum(axis=-1, keepdims=True)
        resid = jnp.where(rsum > 0.0, resid / jnp.maximum(rsum, 1e-30),
                          p_all[:, :k])
        corr_dist = jnp.concatenate([resid, p_all[:, k:]], axis=1)
        # correction lane j replaces absolute position
        # lengths + 1 + j — keyed there (domain 3)
        corr_pos = (lengths[:, None]
                    + 1 + jnp.arange(K1)[None, :]).reshape(lanes)
        ckeys = _position_keys(jnp.repeat(seeds, K1), corr_pos, domain=3)
        corr_drawn = jax.vmap(jax.random.categorical)(
            ckeys, jnp.log(jnp.maximum(
                corr_dist.reshape(lanes, vocab), 1e-38))
        ).astype(jnp.int32).reshape(S, K1)
        corr = jnp.where(temps[:, None] > 0.0, corr_drawn, tgt_greedy)
        d_ext = jnp.concatenate(
            [d_all, jnp.zeros((S, 1), jnp.int32)], axis=1)
        j = jnp.arange(K1)[None, :]
        emitted = jnp.where(j < a[:, None], d_ext, corr)
        return emitted, a, k_pool, v_pool

    if mesh is None:
        return verify_step
    return wrap(verify_step,
                in_specs=(pspecs, draft_pspecs, pool_spec, pool_spec)
                + (repl,) * 11,
                out_specs=(repl, repl, pool_spec, pool_spec))


def prefix_admission_plan(n_pages, page_size, prompt_len, max_new,
                          shared_prefix_len):
    """Worst-case-fit admission math under prefix sharing — the pure
    arithmetic the scheduler's budgeting implements and the costguard
    ``llm_admission_*`` golden pair pins (docs/api.md "LLM serving").

    A sequence's worst case is ``pages_for(prompt_len + max_new)``
    pages.  With a resident shared prefix of ``shared_prefix_len``
    tokens, its leading FULL blocks map onto already-resident pages at
    zero cost, so admission charges only the ``charged_pages``
    remainder — the first holder of the prefix still pays in full.
    Returns the per-sequence page counts and the admissible concurrent
    sequences with and without sharing at this pool size."""
    ps = int(page_size)
    pool = int(n_pages) - 1                   # page 0 is the write sink
    total = -(-(int(prompt_len) + int(max_new)) // ps)
    shared = min(int(shared_prefix_len) // ps,
                 int(prompt_len) // ps)
    charged = total - shared
    unshared = pool // total if total else 0
    if pool < total:
        with_sharing = 0
    elif charged == 0:
        with_sharing = pool                   # every follower is free
    else:
        with_sharing = 1 + (pool - total) // charged
    return {"pages_per_seq": total, "shared_pages": shared,
            "charged_pages": charged, "admissible_unshared": unshared,
            "admissible_shared": with_sharing,
            "multiplier": with_sharing / max(unshared, 1)}


class SequenceSnapshot:
    """Resumable state of one in-flight generation (ISSUE 19) —
    capturable at any step boundary, portable across processes and
    replicas, JSON-serializable (the decode journal's record shape).

    Because sampling is position-keyed (``fold_in(PRNGKey(seed),
    position)``), this is ALL the state resume needs: re-prefilling
    ``prompt + out`` through the existing bucket grid reconstructs the
    KV cache, and every future draw coincides with the uninterrupted
    run's — greedy and seeded sampling alike.  ``deadline_wall`` is the
    absolute wall-clock expiry (``time.time()`` base — monotonic clocks
    don't survive a process), converted back to a remaining-seconds
    deadline at ``submit_resume``."""

    __slots__ = ("rid", "prompt", "out", "max_new", "temperature",
                 "top_k", "seed", "priority", "deadline_wall", "tenant",
                 "klass")

    def __init__(self, rid, prompt, out, max_new, temperature, top_k,
                 seed, priority=0, deadline_wall=None, tenant=None,
                 klass=None):
        self.rid = int(rid)
        self.prompt = [int(t) for t in prompt]
        self.out = [int(t) for t in out]
        self.max_new = int(max_new)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.seed = int(seed)
        self.priority = int(priority)
        self.deadline_wall = None if deadline_wall is None \
            else float(deadline_wall)
        self.tenant = tenant
        self.klass = klass

    def to_json(self):
        return {name: getattr(self, name) for name in self.__slots__}

    @classmethod
    def from_json(cls, d):
        return cls(**{name: d[name] for name in cls.__slots__
                      if name in d})

    def __repr__(self):
        return (f"SequenceSnapshot(rid={self.rid}, "
                f"prompt_len={len(self.prompt)}, "
                f"generated={len(self.out)}/{self.max_new}, "
                f"seed={self.seed})")


# ---------------------------------------------------------------- scheduler --
class _Seq:
    """Decode-loop-private state of one admitted sequence."""

    __slots__ = ("req", "prompt", "max_new", "temp", "top_k", "slot",
                 "pages", "cached", "out", "stamp", "ran", "priority",
                 "shared_n", "seed", "rid", "salvage", "replay")

    def __init__(self, req, prompt, max_new, temp, top_k, priority=0):
        self.req = req
        self.prompt = prompt
        self.max_new = max_new
        self.temp = temp
        self.top_k = top_k
        self.priority = priority  # QoS class priority — scheduling order
        self.slot = None
        self.pages = []
        self.cached = 0          # tokens whose K/V is in the pool
        self.out = []            # generated token ids (EOS excluded)
        self.stamp = 0.0         # admission order — eviction picks youngest
        self.ran = False         # ever prefilled (survives preemption)
        self.shared_n = 0        # leading pages mapped from the prefix index
        self.seed = 0            # per-sequence sampling seed (position-keyed)
        self.rid = -1            # admission ordinal — the journal's key
        self.salvage = 0         # failure-salvage retries consumed
        self.replay = []         # recorded tokens still to force post-resume


class GenerationServer:
    """Continuous-batching autoregressive generation server.

    Lifecycle mirrors ``InferenceServer``: construct → ``start()``
    (warmup-compiles the full prefill bucket grid AND the single decode
    executable before readiness flips) → ``submit()``/``__call__`` →
    ``drain()`` or ``serve_forever()``.  ``submit`` returns a
    ``Request`` future resolving to the generated token ids
    (``np.int32``, EOS excluded) or an explicit error.

    One decode loop thread owns all device state (pools, slot arrays,
    allocator traffic); client threads touch only the admission deque,
    the lock-guarded stats, and ``Request`` futures.

    **Disaggregated prefill/decode (ISSUE 12).**  With
    ``prefill_workers >= 1`` the server splits into two replica groups:
    prefill runs on a worker-thread group through POOL-FREE executables
    (``build_prefill_kv_step`` — in a multi-chip deployment these
    workers pin the prefill group's chips) while the decode loop — the
    decode group — keeps stepping its pinned executable undisturbed.  A
    finished prefill hands its KV payload + first token off through a
    staging buffer; the decode loop scatters it into the paged pool with
    the single fixed-shape ``build_handoff_step`` program and seats the
    sequence in a slot.  Consequences, both chaos-tested: a long prompt
    no longer stalls in-flight decodes for its step, and a prefill-side
    failure can no longer destroy the donated pools under the decode
    group's bystanders (the pool-free program never touches them).  The
    executable census becomes ``prefill grid + 2`` (handoff + decode).

    **Tensor-parallel sharded decode (ISSUE 14).**  ``tp_shards=N``
    lowers every program — the prefill grid, THE decode step, and (when
    disaggregated) the handoff scatter — once over an N-way ``tp`` mesh
    as ``shard_map`` programs: each device owns a head shard of the K/V
    page pools (per-device pool HBM ∝ 1/shards, so servable model size
    AND aggregate slot count multiply with the mesh), the causal LM's
    QKV/FFN weights are Megatron column/row-sharded, and the two
    per-layer partial-product all-reduces on the decode path run in the
    ``tp_collectives`` wire format (``"f32"`` or ``"int8"`` via
    ``parallel.quantize.all_reduce_activations`` — EQuARX's trade:
    decode is latency-bound on collective bytes).  Everything host-side
    is UNCHANGED: the ``PageAllocator`` stays layout-free (a page id
    addresses every device's shard of that page), slot arrays stay
    replicated, and the census contract survives — still prefill grid +
    decode (+ handoff), each lowered once over the mesh, so warmup,
    donation, preemption, QoS seating, and telemetry span trees are
    identical to the single-chip server.

    **Per-tenant QoS.**  ``qos=TenantQoS(...)`` adds priority classes
    and per-tenant token buckets at admission: the scheduler seats
    higher-priority classes first (FIFO within a class; eviction stays
    strictly seniority-ordered, so the livelock proof is untouched), an
    abusive tenant sheds alone with ``TenantThrottledError``, and
    ``healthz()["classes"]`` reports per-class deadline-miss and
    p50/p99 latency — the same keys ``InferenceServer`` serves, so
    fleet routers rank LLM and classifier replicas uniformly.

    Profiler series: ``<name>::tokens_out``, ``<name>::page_occupancy``
    (percent of allocatable pages held), ``<name>::preempted``,
    ``<name>::retired`` (sequences leaving a slot for any terminal
    reason: completed, failed, or expired).
    """

    _IDLE_TICK = 0.005

    def __init__(self, params, config, *, buckets=None, n_slots=8,
                 n_pages=64, page_size=16, max_context=None,
                 max_queue=128, rate=None, burst=None, breaker=None,
                 default_deadline=None, max_new_tokens=32, eos_id=None,
                 seed=0, attention_impl=None, prefill_workers=0,
                 qos=None, tp_shards=1, tp_collectives="f32",
                 draft=None, draft_config=None, spec_k=3,
                 spec_window=16, salvage_retries=2, journal=None,
                 journal_every=8, memory_report=None,
                 name="GenerationServer"):
        import jax
        import jax.numpy as jnp

        from ..parallel.quantize import ACTIVATION_REDUCE_MODES

        self.config = config
        # speculative decoding (ISSUE 16): a draft model switches the
        # scheduler's step from the decode program to the verify
        # program — spec_k proposals scored per step, output
        # distribution exact (greedy: token-identical)
        self._spec_k = int(spec_k)
        self._spec_window = int(spec_window)
        self._draft_cfg = draft_config
        if draft is not None:
            if draft_config is None:
                raise ValueError(f"{name}: draft= needs draft_config= "
                                 f"(the draft's CausalLMConfig)")
            if draft_config.vocab_size != config.vocab_size:
                raise ValueError(
                    f"{name}: draft vocab {draft_config.vocab_size} != "
                    f"target vocab {config.vocab_size}")
            if self._spec_k < 1:
                raise ValueError(f"{name}: spec_k must be >= 1")
            if self._spec_window < 1:
                raise ValueError(f"{name}: spec_window must be >= 1")
        self.tp_shards = int(tp_shards)
        if tp_collectives not in ACTIVATION_REDUCE_MODES:
            raise ValueError(
                f"{name}: tp_collectives={tp_collectives!r} not in "
                f"{ACTIVATION_REDUCE_MODES}")
        self.tp_collectives = tp_collectives
        if self.tp_shards > 1:
            from ..gluon.model_zoo.causal_lm import tp_validate
            from ..parallel.mesh import make_mesh

            tp_validate(config, self.tp_shards)
            devices = jax.devices()
            if self.tp_shards > len(devices):
                raise ValueError(
                    f"{name}: tp_shards={self.tp_shards} exceeds the "
                    f"{len(devices)} visible devices")
            self._mesh = make_mesh(tp=self.tp_shards,
                                   devices=devices[:self.tp_shards])
        else:
            self._mesh = None
        if buckets is None:
            buckets = BucketSpec(batch=(1, 2), length=(16, 32))
        # a bare batch tuple wraps like InferenceServer's — and then
        # fails the length-bucket requirement below LOUDLY, instead of
        # silently serving the default grid
        self.buckets = buckets if isinstance(buckets, BucketSpec) \
            else BucketSpec(buckets)
        if self.buckets.length is None:
            raise ValueError(f"{name}: buckets must define length "
                             f"buckets — prompts are sequences")
        self.n_slots = int(n_slots)
        self.alloc = PageAllocator(n_pages, page_size)
        # per-sequence page-table width: enough for the longest prompt
        # bucket plus the default generation budget (the table is a
        # configuration constant — it shapes the compiled programs);
        # speculative mode adds spec_k — the verify step writes k
        # lookahead positions past the pending token
        if max_context is None:
            max_context = max(self.buckets.length) + int(max_new_tokens) \
                + (self._spec_k if draft is not None else 0)
        if max_context < max(self.buckets.length) + 1:
            raise ValueError(
                f"{name}: max_context {max_context} cannot hold the "
                f"largest length bucket {max(self.buckets.length)} plus "
                f"one generated token")
        self.pages_per_seq = self.alloc.pages_for(max_context)
        self.max_context = self.pages_per_seq * self.alloc.page_size
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        self._limiter = None if rate is None else TokenBucket(rate, burst)
        self._qos = qos if qos is not None else TenantQoS()
        self._default_deadline = default_deadline
        self._max_new = int(max_new_tokens)
        self._eos = None if eos_id is None else int(eos_id)
        self._name = name
        self._max_queue = int(max_queue)

        if self._mesh is not None:
            from ..gluon.model_zoo.causal_lm import tp_shard_params

            # one-time host relayout + committed sharded placement: the
            # compiled programs never re-transfer weights per call
            self._params = tp_shard_params(params, config, self._mesh)
        else:
            self._params = jax.tree.map(jnp.asarray, params)
        self._decode = jax.jit(
            build_decode_step(config, self.alloc.page_size,
                              attention_impl, mesh=self._mesh,
                              tp_collectives=self.tp_collectives),
            donate_argnums=(1, 2))
        if draft is not None:
            if self._mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec

                # the draft replicates over the mesh (tiny by design)
                rep = NamedSharding(self._mesh, PartitionSpec())
                self._draft_params = {
                    kname: jax.device_put(jnp.asarray(v), rep)
                    for kname, v in draft.items()}
            else:
                self._draft_params = jax.tree.map(jnp.asarray, draft)
            self._verify = jax.jit(
                build_verify_step(config, draft_config,
                                  self.alloc.page_size, self._spec_k,
                                  self._spec_window, attention_impl,
                                  mesh=self._mesh,
                                  tp_collectives=self.tp_collectives),
                donate_argnums=(2, 3))
        else:
            self._draft_params = None
            self._verify = None
        self._n_prefill_workers = int(prefill_workers)
        if self._n_prefill_workers > 0:
            # disaggregated: pool-free prefill grid + ONE handoff scatter
            self._prefill = jax.jit(
                build_prefill_kv_step(config, attention_impl,
                                      mesh=self._mesh))
            self._handoff = jax.jit(
                build_handoff_step(config, self.alloc.page_size,
                                   mesh=self._mesh),
                donate_argnums=(0, 1))
        else:
            self._prefill = jax.jit(
                build_prefill_step(config, self.alloc.page_size,
                                   attention_impl, mesh=self._mesh),
                donate_argnums=(1, 2))
            self._handoff = None
        # per-position PRNG (ISSUE 19): the server seed only SALTS the
        # per-sequence seed derivation (admission ordinal → splitmix) —
        # no step counter exists anywhere, so randomness is a pure
        # function of (sequence seed, token position) and resume is
        # token-exact by construction
        self._seed_root = int(seed) & 0xFFFFFFFFFFFFFFFF
        self._admit_ord = 0                 # _admit_lock-guarded
        # failure salvage + decode journal (ISSUE 19)
        self._salvage_retries = max(0, int(salvage_retries))
        self._journal = None if journal is None \
            else _telemetry.JsonlSink(journal)
        self._journal_every = max(1, int(journal_every))
        self._jsteps = 0                    # decode-loop-private
        self._handoff_exit = threading.Event()
        self.exported = []                  # SequenceSnapshots from handoff

        # decode-loop-private device + slot state (created in start())
        self._k_pool = self._v_pool = None
        self._seqs = {}                                  # slot -> _Seq
        self._tokens = np.zeros((self.n_slots,), np.int32)
        self._lengths = np.zeros((self.n_slots,), np.int32)
        self._active = np.zeros((self.n_slots,), bool)
        self._tables = np.zeros((self.n_slots, self.pages_per_seq),
                                np.int32)
        self._temps = np.zeros((self.n_slots,), np.float32)
        self._topks = np.zeros((self.n_slots,), np.int32)
        self._seeds = np.zeros((self.n_slots,), np.uint32)
        # CoW fault lanes, reset each step; (0, 0) = inert sink self-copy
        self._cow_src = np.zeros((self.n_slots,), np.int32)
        self._cow_dst = np.zeros((self.n_slots,), np.int32)
        # speculative draft context: right-aligned token windows
        self._window = np.zeros((self.n_slots, self._spec_window),
                                np.int32)
        self._nvalid = np.ones((self.n_slots,), np.int32)
        # prefix index (decode-loop-private): parent page (0 = root) →
        # {full-block token tuple: resident page}, plus the reverse map
        # releases use.  A chain walk from the root maps a new prompt's
        # leading blocks onto resident pages (``_match_prefix``).
        self._children = {}
        self._indexed_by_page = {}

        self._pending = collections.deque()
        self._admit_lock = threading.Lock()
        self._lock = threading.Lock()
        self._stats = {"admitted": 0, "completed": 0, "failed": 0,
                       "expired": 0, "rejected": 0, "retired": 0,
                       "preempted": 0, "tokens_out": 0, "prefills": 0,
                       "handoffs": 0, "decode_steps": 0, "active_slots": 0,
                       "verify_steps": 0, "spec_proposed": 0,
                       "spec_accepted": 0, "cow_faults": 0,
                       "pages_charged": 0, "pages_shared_mapped": 0,
                       "tokens_salvaged": 0, "resumes": 0,
                       "salvage_retries": 0, "journal_restores": 0,
                       "journal_errors": 0, "resume_pages_remapped": 0,
                       "handoff_exports": 0}
        self._last_error = None
        self._ready = threading.Event()
        self._draining = threading.Event()
        self._stop = threading.Event()
        self._loop_exited = threading.Event()
        self._started = threading.Event()
        self._thread = threading.Thread(target=self._loop, name=name,
                                        daemon=True)
        # disaggregated-mode plumbing: the prefill group's work queue
        # (bounded — the decode loop is the only producer and checks
        # full() first, so put_nowait cannot race), the handoff queue
        # (prefill workers → decode loop), the flight registry (groups a
        # worker currently owns, swept on loop exit so a dying worker
        # can never strand its group), and the decode-loop-local seat
        # backlog of prefilled sequences waiting for slots/pages.
        self._prefill_q = queue.Queue(
            maxsize=max(2, 2 * self._n_prefill_workers))
        self._handoff_q = queue.Queue()
        self._prefill_flight = {}          # id(group) -> group, _lock-guarded
        self._handoff_backlog = []         # decode-loop-private
        self._prefill_threads = [
            threading.Thread(target=self._prefill_worker,
                             name=f"{name}-prefill-w{i}", daemon=True)
            for i in range(self._n_prefill_workers)]
        self._c_tokens = _profiler.Counter(None, f"{name}::tokens_out")
        self._c_pages = _profiler.Counter(None, f"{name}::page_occupancy")
        self._c_preempted = _profiler.Counter(None, f"{name}::preempted")
        self._c_retired = _profiler.Counter(None, f"{name}::retired")
        # live memory gauges (ISSUE 15): per-device argument/peak bytes
        # from an already-parsed costguard report, stamped at warmup
        self._mem_gauges = _telemetry.memory_gauges(memory_report)
        # per-slot page-occupancy histogram: observed at every
        # retirement, so the exposition shows how sequences actually
        # used the pool (not just the aggregate free count)
        self._h_slot_pages = _telemetry.registry().histogram(
            f"{name}::slot_pages",
            _telemetry.log_buckets(1.0, 4096.0, per_decade=4))
        # per-step draft acceptance rate (accepted / spec_k), observed
        # per slot each verify step — the speculative win's live gauge
        self._h_accept = _telemetry.registry().histogram(
            f"{name}::spec_accept_rate", [i / 8 for i in range(1, 9)])

    # ------------------------------------------------------------ lifecycle --
    def start(self, warmup=True):
        """Allocate the pools and (by default) compile the WHOLE
        executable space — every prefill bucket signature plus the one
        decode program — with inert all-inactive arguments (writes sink
        to page 0, the allocator is untouched) before readiness flips.
        After warmup the jit caches hold exactly ``census()`` entries
        and live traffic can never add one."""
        if self._draining.is_set():
            raise ServerClosedError(f"{self._name}: already drained")
        # the decode thread owns the pools once it starts (two lines
        # down); the lock here is for the thread-contract checker —
        # nothing races a thread that does not exist yet.  The pool
        # device_put (sharded placement under tp) runs BEFORE taking it:
        # only the attribute assignment needs the lock.
        pools = self._new_pools()
        with self._admit_lock:
            self._k_pool, self._v_pool = pools
        if warmup:
            for b in self.buckets.batch:
                for L in self.buckets.length:
                    if self._n_prefill_workers > 0:
                        self._run_prefill_kv(
                            np.zeros((b, L), np.int32),
                            np.zeros((b,), np.int32),
                            np.zeros((b,), np.uint32),
                            np.zeros((b,), np.float32),
                            np.zeros((b,), np.int32))
                    else:
                        self._run_prefill(
                            np.zeros((b, L), np.int32),
                            np.zeros((b,), np.int32),
                            np.zeros((b,), bool),
                            np.zeros((b, self.pages_per_seq), np.int32),
                            np.zeros((b,), np.uint32),
                            np.zeros((b,), np.float32),
                            np.zeros((b,), np.int32))
            if self._n_prefill_workers > 0:
                self._run_handoff(*self._staging(), np.zeros(
                    (self.buckets.max_batch,), np.int32),
                    np.zeros((self.buckets.max_batch,), bool),
                    np.zeros((self.buckets.max_batch, self.pages_per_seq),
                             np.int32))
            self._run_decode()
            if self._verify is not None:
                # the verify program joins the pinned set: inert
                # all-inactive arguments, writes sink to page 0
                self._run_verify()
            # the whole executable space exists now (census() programs):
            # any later compile at this site is an UNEXPECTED recompile —
            # the counter chaos_check --mode obs asserts stays zero.  A
            # warmup=False server compiles lazily by choice, so nothing
            # is pinned and its compiles stay ordinary events.
            if _telemetry.ACTIVE:
                _telemetry.pin_compile_census(self._name)
        self._started.set()
        self._thread.start()
        for t in self._prefill_threads:
            t.start()
        self._ready.set()
        return self

    def __enter__(self):
        if not self._started.is_set():
            self.start()
        return self

    def __exit__(self, *exc):
        self.drain()
        return False

    def census(self):
        """The static executable count: one prefill program per (batch,
        length) bucket plus THE decode program — plus THE handoff
        program when disaggregated (``prefill_workers >= 1``), plus THE
        verify program when speculative (``draft=`` — census grows by
        exactly one).  ``jit_cache_count()`` must equal this after
        warmup, forever."""
        grid = len(self.buckets.batch) * len(self.buckets.length)
        return grid + 1 + (1 if self._n_prefill_workers > 0 else 0) \
            + (1 if self._verify is not None else 0)

    def jit_cache_count(self):
        """Runtime executables actually compiled (every jit cache)."""
        n = self._prefill._cache_size() + self._decode._cache_size()
        if self._handoff is not None:
            n += self._handoff._cache_size()
        if self._verify is not None:
            n += self._verify._cache_size()
        return n

    # ------------------------------------------------------------ admission --
    def submit(self, tokens, *, max_new_tokens=None, temperature=0.0,
               top_k=0, deadline=None, tenant=None, klass=None,
               seed=None, trace_parent=None):
        """Admit one prompt; returns a ``Request`` future resolving to
        the generated ``np.int32`` token ids (EOS excluded).

        ``tenant``/``klass`` are the QoS labels (``TenantQoS``): the
        class supplies the default deadline, its priority orders the
        scheduler's seating, and the resolution lands in the class's
        ``healthz()["classes"]`` stats.

        ``seed`` pins this sequence's sampling seed explicitly (any
        uint32); by default it derives from the server seed and the
        admission ordinal.  Two servers given the same seed and prompt
        produce the same sampled stream — the oracle lever of the
        resume-exactness tests (ISSUE 19).

        Refusals are immediate and explicit (PR 4 contract):
        ``ServerClosedError`` draining, ``CircuitOpenError`` fast-fail,
        ``RejectedError`` for rate limit / full queue / a prompt no
        length bucket holds / a worst case that could never fit the
        page pool, ``TenantThrottledError`` for an over-rate tenant.
        None of them touched the device."""
        t0_us = _telemetry.now_us() if _telemetry.ACTIVE else None
        if self._draining.is_set():
            self._bump("rejected")
            raise ServerClosedError(f"{self._name}: draining — "
                                    f"not admitting")
        if not self._ready.is_set():
            self._bump("rejected")
            raise RejectedError(f"{self._name}: not started")
        if not self._thread.is_alive():
            self._bump("rejected")
            raise ServerClosedError(f"{self._name}: decode loop is not "
                                    f"running — not admitting")
        if self.breaker.engaged():
            self._bump("rejected")
            raise CircuitOpenError(
                f"{self._name}: circuit open after repeated step failures "
                f"— fast-failing until a probe succeeds")
        raw = np.asarray(tokens)
        if not np.issubdtype(raw.dtype, np.integer):
            raise ValueError(
                f"{self._name}: prompt dtype {raw.dtype} is not an "
                f"integer token array — casting would silently "
                f"truncate; tokenize first")
        prompt = raw.astype(np.int32)
        if prompt.ndim != 1 or prompt.shape[0] < 1:
            raise ValueError(f"{self._name}: prompt must be a 1-D, "
                             f"non-empty int sequence")
        max_new = self._max_new if max_new_tokens is None \
            else int(max_new_tokens)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if float(temperature) < 0.0 or int(top_k) < 0:
            raise ValueError("temperature must be >= 0 and top_k >= 0")
        n = prompt.shape[0]
        try:
            if n > max(self.buckets.length):
                raise RejectedError(
                    f"prompt length {n} exceeds the largest length bucket "
                    f"{max(self.buckets.length)} — no prefill executable "
                    f"exists for this shape")
            # speculative mode verifies spec_k lookahead positions past
            # the pending token — the worst case must hold them too
            spare = self._spec_k if self._verify is not None else 0
            if n + max_new + spare > self.max_context:
                raise RejectedError(
                    f"prompt {n} + max_new_tokens {max_new}"
                    + (f" + spec_k {spare}" if spare else "")
                    + f" exceeds the page capacity {self.max_context} "
                    f"per sequence")
            if self.alloc.pages_for(n + max_new + spare) \
                    > self.alloc.allocatable:
                raise RejectedError(
                    f"worst case needs "
                    f"{self.alloc.pages_for(n + max_new + spare)} "
                    f"pages, pool holds {self.alloc.allocatable} — this "
                    f"request could never be served")
        except RejectedError:
            self._bump("rejected")
            raise
        # QoS verdict AFTER structural checks (an unservable prompt must
        # not burn a tenant token), BEFORE the global limiter
        try:
            qc = self._qos.classify(tenant=tenant, klass=klass)
        except RejectedError:
            self._bump("rejected")
            raise
        if deadline is None:
            deadline = qc.deadline if qc.deadline is not None \
                else self._default_deadline
        if self._limiter is not None and not self._limiter.try_acquire():
            self._qos.refund(tenant, qc)
            self._bump("rejected")
            raise RejectedError(f"{self._name}: rate limit exceeded — "
                                f"shedding")
        req = Request((prompt,), deadline=deadline, tenant=tenant,
                      klass=qc.name)
        seq = _Seq(req, prompt, max_new, float(temperature), int(top_k),
                   priority=qc.priority)
        seq.stamp = time.monotonic()
        # a class's admit_frac is a threshold on TOTAL queue depth:
        # low-priority work sheds once the whole backlog reaches its
        # fraction, keeping the rest of the queue exclusively for the
        # classes above it (the queue-depth twin of the fleet's
        # in-flight threshold)
        queue_cap = self._max_queue if qc.admit_frac >= 1.0 \
            else int(qc.admit_frac * self._max_queue)
        # trace BEFORE joining the queue — the decode loop may pop the
        # sequence immediately and needs the queue span already open.  A
        # refusal below never resolves the request, so the trace is
        # never exported.
        if trace_parent is not None or t0_us is not None:
            _telemetry.begin_request(req, self._name, t0_us=t0_us,
                                     parent=trace_parent)
        with self._admit_lock:
            admitted = not self._stop.is_set() \
                and len(self._pending) < queue_cap
            if admitted:
                seq.rid = self._admit_ord
                self._admit_ord += 1
                seq.seed = self._derive_seed(seq.rid) if seed is None \
                    else int(seed) & 0xFFFFFFFF
                self._pending.append(seq)
            else:
                stopped = self._stop.is_set()
        if not admitted:
            if self._limiter is not None:
                self._limiter.refund()
            self._qos.refund(tenant, qc)
            self._bump("rejected")
            _telemetry.abort_request(req)
            if stopped:
                raise ServerClosedError(f"{self._name}: draining — "
                                        f"not admitting")
            raise RejectedError(
                f"{self._name}: request queue at class "
                f"{qc.name!r}'s cap ({queue_cap} of "
                f"{self._max_queue}) — shedding")
        self._qos.track(qc, req)
        self._bump("admitted")
        self._journal_admit(seq)
        return req

    def submit_resume(self, snapshot, *, deadline=None):
        """Admit a ``SequenceSnapshot`` — the resume half of ISSUE 19:
        the sequence re-enters the queue WITH its generated-so-far
        tokens and its ORIGINAL sampling seed, re-prefills prompt +
        generated through the existing bucket grid, and completes
        token-exact with what the uninterrupted run would have
        produced.  Fleet failover and journal restore both land here.

        ``deadline`` (seconds from now) overrides the snapshot's
        wall-clock expiry; with neither, the sequence has no deadline.
        QoS classification is NOT re-applied (the request paid at its
        original admission); the snapshot's priority orders seating.
        Refusals match ``submit``: ``ServerClosedError`` draining,
        ``CircuitOpenError`` fast-fail, ``RejectedError`` full queue /
        structurally unservable."""
        t0_us = _telemetry.now_us() if _telemetry.ACTIVE else None
        if isinstance(snapshot, dict):
            snapshot = SequenceSnapshot.from_json(snapshot)
        if self._draining.is_set():
            self._bump("rejected")
            raise ServerClosedError(f"{self._name}: draining — "
                                    f"not admitting")
        if not self._ready.is_set():
            self._bump("rejected")
            raise RejectedError(f"{self._name}: not started")
        if not self._thread.is_alive():
            self._bump("rejected")
            raise ServerClosedError(f"{self._name}: decode loop is not "
                                    f"running — not admitting")
        if self.breaker.engaged():
            self._bump("rejected")
            raise CircuitOpenError(
                f"{self._name}: circuit open after repeated step failures "
                f"— fast-failing until a probe succeeds")
        prompt = np.asarray(snapshot.prompt, np.int32)
        n = prompt.shape[0]
        max_new = int(snapshot.max_new)
        spare = self._spec_k if self._verify is not None else 0
        try:
            if n < 1:
                raise RejectedError("snapshot prompt is empty")
            if n > max(self.buckets.length):
                raise RejectedError(
                    f"snapshot prompt length {n} exceeds the largest "
                    f"length bucket {max(self.buckets.length)} on this "
                    f"server — no prefill executable exists")
            if n + max_new + spare > self.max_context \
                    or self.alloc.pages_for(n + max_new + spare) \
                    > self.alloc.allocatable:
                raise RejectedError(
                    f"snapshot worst case ({n} + {max_new} new) does not "
                    f"fit this server's page capacity")
        except RejectedError:
            self._bump("rejected")
            raise
        if deadline is None and snapshot.deadline_wall is not None:
            deadline = snapshot.deadline_wall - time.time()
        req = Request((prompt,), deadline=deadline,
                      tenant=snapshot.tenant, klass=snapshot.klass)
        seq = _Seq(req, prompt, max_new, float(snapshot.temperature),
                   int(snapshot.top_k), priority=snapshot.priority)
        seq.out = [int(t) for t in snapshot.out]
        seq.stamp = time.monotonic()
        if len(seq.out) >= max_new:
            # complete already (the journal caught it between its last
            # token and its retirement record) — resolve without work
            self._bump("admitted")
            req.set_result(np.asarray(seq.out[:max_new], np.int32))
            self._bump("completed")
            self._bump("retired")
            return req
        if t0_us is not None:
            _telemetry.begin_request(req, self._name, t0_us=t0_us)
        with self._admit_lock:
            admitted = not self._stop.is_set() \
                and len(self._pending) < self._max_queue
            if admitted:
                seq.rid = self._admit_ord
                self._admit_ord += 1
                seq.seed = int(snapshot.seed) & 0xFFFFFFFF
                self._pending.append(seq)
            else:
                stopped = self._stop.is_set()
        if not admitted:
            self._bump("rejected")
            _telemetry.abort_request(req)
            if stopped:
                raise ServerClosedError(f"{self._name}: draining — "
                                        f"not admitting")
            raise RejectedError(f"{self._name}: request queue full "
                                f"({self._max_queue}) — shedding")
        self._bump("admitted")
        self._journal_admit(seq)
        return req

    def restore_journal(self, path):
        """Import a crashed sibling's decode journal (ISSUE 19): replay
        ``gen_admit``/``gen_snapshot``/``gen_handoff``/``gen_retire``
        records in order, reconstruct every sequence that was admitted
        but never retired, and ``submit_resume`` each — the restored
        server completes them token-exact (position-keyed sampling +
        the journaled seed).  Stale in-flight snapshots are harmless:
        the missing tail regenerates identically.

        Reads the rotated ``<path>.1`` first, then ``path``; a torn
        tail line (kill -9 mid-write) is skipped.  Returns ``{rid:
        Request}`` for the resumed sequences (rids from the DEAD
        server's journal).  Sequences this server must refuse
        structurally raise through; call on a started, healthy server
        before opening it to traffic."""
        import json
        import os

        live = {}
        for p in (str(path) + ".1", str(path)):
            if not os.path.exists(p):
                continue
            with open(p, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue               # torn tail — kill -9
                    if rec.get("kind") != "generate":
                        continue
                    nm, rid = rec.get("name"), rec.get("rid")
                    if rid is None:
                        continue
                    if nm in ("gen_admit", "gen_handoff"):
                        live[rid] = dict(rec)
                    elif nm == "gen_snapshot" and rid in live:
                        live[rid]["out"] = list(rec.get("out", []))
                    elif nm == "gen_retire":
                        live.pop(rid, None)
        restored = {}
        for rid, rec in live.items():
            snap = SequenceSnapshot.from_json(rec)
            restored[rid] = self.submit_resume(snap)
            self._bump("journal_restores")
        return restored

    def __call__(self, tokens, timeout=None, **kw):
        """Blocking convenience: submit + ``result()``."""
        return self.submit(tokens, **kw).result(timeout)

    def _bump(self, key, n=1):
        with self._lock:
            self._stats[key] += n

    def _note_step_failure(self, exc):
        with self._lock:
            self._last_error = (type(exc).__name__, time.monotonic())

    # ---------------------------------------------------- snapshots + journal --
    def _snapshot_of(self, seq):
        """Capture one sequence's resumable state (step boundary —
        decode-loop thread, or admission state not yet seated)."""
        dw = None
        if seq.req.deadline is not None:
            dw = time.time() + (seq.req.deadline - time.monotonic())
        return SequenceSnapshot(
            rid=seq.rid, prompt=seq.prompt, out=seq.out,
            max_new=seq.max_new, temperature=seq.temp, top_k=seq.top_k,
            seed=seq.seed, priority=seq.priority, deadline_wall=dw,
            tenant=seq.req.tenant, klass=seq.req.klass)

    def _journal_event(self, name, **fields):
        """Append one record to the decode journal.  Write failures are
        swallowed into the ``journal_errors`` counter — the journal is
        a durability aid, never a serving liability (``generate.journal``
        is the fault point that proves it)."""
        if self._journal is None:
            return
        try:
            _fault.fire("generate.journal")
            self._journal.write("generate", name=name, **fields)
        except Exception:   # noqa: BLE001 — journaling must not fail serving
            self._bump("journal_errors")

    def _journal_admit(self, seq):
        """One ``gen_admit`` record per accepted sequence — the full
        snapshot (out included: a resumed admission re-journals its
        salvaged tokens, so restore needs no cross-file history)."""
        if self._journal is not None:
            self._journal_event("gen_admit", **self._snapshot_of(seq)
                                .to_json())

    def _journal_tick(self):
        """Periodic in-flight snapshots (every ``journal_every``
        successful steps): bounds how many trailing tokens a kill -9
        can force the restored server to regenerate — regeneration is
        token-exact either way, this only trades journal bytes against
        recompute."""
        if self._journal is None:
            return
        self._jsteps += 1
        if self._jsteps % self._journal_every:
            return
        for seq in self._seqs.values():
            self._journal_event("gen_snapshot", rid=seq.rid,
                                out=list(seq.out))

    # ----------------------------------------------------------- decode loop --
    def _derive_seed(self, ordinal):
        """The per-sequence sampling seed: a splitmix64-style mix of the
        server seed and the admission ordinal.  Stable across processes
        (pure arithmetic — no RNG object, no clock), so a journal
        restore or a fleet redispatch carries the ORIGINAL seed and the
        resumed sequence samples the original stream.  ``submit(seed=)``
        overrides it per request."""
        x = (self._seed_root
             + (int(ordinal) + 1) * 0x9E3779B97F4A7C15) \
            & 0xFFFFFFFFFFFFFFFF
        x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
        x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
        return (x ^ (x >> 31)) & 0xFFFFFFFF

    def _run_prefill(self, tokens, lengths, active, tables, seeds,
                     temps, topks):
        """One prefill program invocation (pools donated/reassigned)."""
        with _telemetry.compile_guard(
                self._name, self._prefill,
                key=f"prefill/b{tokens.shape[0]}_l{tokens.shape[1]}"):
            first, self._k_pool, self._v_pool = self._prefill(
                self._params, self._k_pool, self._v_pool, tokens, lengths,
                active, tables, seeds, temps, topks)
        return np.asarray(first)

    def _run_prefill_kv(self, tokens, lengths, seeds, temps, topks):
        """One POOL-FREE prefill invocation (disaggregated mode; any
        prefill-group worker thread).  Host-realizes the outputs so the
        device wait lands on the worker, never the decode loop."""
        with _telemetry.compile_guard(
                self._name, self._prefill,
                key=f"prefill/b{tokens.shape[0]}_l{tokens.shape[1]}"):
            first, k_all, v_all = self._prefill(
                self._params, tokens, lengths, seeds, temps, topks)
        return np.asarray(first), np.asarray(k_all), np.asarray(v_all)

    def _staging(self):
        """Fresh zeroed host staging buffers for one handoff batch —
        the fixed ``(B, L)`` shape that keeps the scatter ONE program."""
        c = self.config
        B, L = self.buckets.max_batch, max(self.buckets.length)
        shape = (c.n_layers, B, L, c.n_heads, c.head_dim)
        return np.zeros(shape, np.float32), np.zeros(shape, np.float32)

    def _run_handoff(self, k_all, v_all, lengths, active, tables):
        """One handoff-scatter invocation (pools donated/reassigned)."""
        with _telemetry.compile_guard(self._name, self._handoff, key="handoff"):
            self._k_pool, self._v_pool = self._handoff(
                self._k_pool, self._v_pool, k_all, v_all, lengths, active,
                tables)

    def _new_pools(self):
        """Fresh zeroed K/V pools — head axis sharded over the tp mesh
        when one exists (each device hosts ``n_heads / tp_shards`` of
        every page: per-device pool HBM ∝ 1/shards), plain single-device
        arrays otherwise."""
        import jax
        import jax.numpy as jnp

        c, npg, psz = self.config, self.alloc.n_pages, self.alloc.page_size
        shape = (c.n_layers, npg, psz, c.n_heads, c.head_dim)
        if self._mesh is None:
            return jnp.zeros(shape, jnp.float32), \
                jnp.zeros(shape, jnp.float32)
        from jax.sharding import NamedSharding, PartitionSpec
        # NB trailing-None-free spec: jax normalizes the sharding it
        # stamps on jit OUTPUTS to PartitionSpec(None, None, None,
        # "tp"), and the lowering cache keys on spec equality — a
        # 5-entry spec here would make the warmup entry (fresh pools)
        # and the live entries (pools round-tripped through the donated
        # programs) TWO executables, breaking census == jit-cache
        sh = NamedSharding(self._mesh,
                           PartitionSpec(None, None, None, "tp"))
        return (jax.device_put(jnp.zeros(shape, jnp.float32), sh),
                jax.device_put(jnp.zeros(shape, jnp.float32), sh))

    def _recover_pools(self):
        """A device call that failed MID-EXECUTION already consumed the
        donated pools — every in-flight sequence's cache is gone with
        them.  Re-zero the pools and fail the sequences explicitly (the
        error path that got here resolves its own group; this sweeps the
        bystanders whose state was collateral).  A host-side failure
        (e.g. an armed fault point) never reaches this: the pools are
        intact and bystanders keep decoding.  Under tensor parallelism
        this is also the mid-decode SHARD-LOSS path: a device falling
        out of the gang fails the collective, the step raises, and the
        re-zeroed pools come back sharded over the same mesh — the
        breaker keeps the server fast-failing until the gang answers
        again (docs/api.md failure matrix).  Bystanders whose cache was
        collateral are SALVAGED (ISSUE 19): their tokens requeue for a
        token-exact resume against the fresh pools, unbudgeted — the
        failing step was not theirs."""
        if self._k_pool is not None and not self._k_pool.is_deleted() \
                and not self._v_pool.is_deleted():
            return
        self._k_pool, self._v_pool = self._new_pools()
        self._salvage_seated(ServerClosedError(
            "KV pool lost to a failed device step"), budgeted=False)

    def _run_decode(self):
        """One decode program invocation over the full slot grid."""
        with _telemetry.compile_guard(self._name, self._decode, key="decode"):
            nxt, self._k_pool, self._v_pool = self._decode(
                self._params, self._k_pool, self._v_pool, self._tokens,
                self._lengths, self._active, self._tables,
                self._cow_src, self._cow_dst, self._seeds,
                self._temps, self._topks)
        return np.asarray(nxt)

    def _run_verify(self):
        """One verify program invocation over the full slot grid
        (speculative mode's decode step; pools donated/reassigned)."""
        with _telemetry.compile_guard(self._name, self._verify, key="verify"):
            emitted, n_acc, self._k_pool, self._v_pool = self._verify(
                self._params, self._draft_params, self._k_pool,
                self._v_pool, self._tokens, self._window, self._nvalid,
                self._lengths, self._active, self._tables,
                self._cow_src, self._cow_dst, self._seeds,
                self._temps, self._topks)
        return np.asarray(emitted), np.asarray(n_acc)

    def _pipeline_idle(self):
        """True when the disaggregated prefill pipeline holds no work
        (trivially true in fused mode).  Order matters: a group stays in
        ``_prefill_flight`` until AFTER its handoff payloads are
        enqueued, so flight must be checked FIRST — checking the queues
        first races a worker finishing between the two checks, and the
        stale verdict would let drain strand a prefilled sequence."""
        if self._n_prefill_workers == 0:
            return True
        with self._lock:
            if self._prefill_flight:
                return False
        return not self._handoff_backlog and self._handoff_q.empty() \
            and self._prefill_q.empty()

    def _loop(self):
        try:
            while True:
                if self._stop.is_set() and self._handoff_exit.is_set():
                    # handoff drain: export unfinished work for a
                    # successor instead of generating to completion
                    self._export_all()
                    return
                if self._stop.is_set() and not self._seqs \
                        and not self._pending and self._pipeline_idle():
                    return
                worked = self._retire_expired()
                if self._draining.is_set() and self.breaker.engaged():
                    # drain must terminate: an open breaker during drain
                    # cannot half-open through traffic it refuses, so
                    # everything still accepted resolves explicitly now
                    # (handoff mode exports instead — same termination
                    # guarantee, no work destroyed)
                    if self._handoff_exit.is_set():
                        self._export_all()
                        return
                    self._fail_everything(CircuitOpenError(
                        f"{self._name}: circuit open during drain — "
                        f"fast-failing accepted work"))
                    return
                if self._n_prefill_workers > 0:
                    worked = self._drain_handoffs() or worked
                    worked = self._dispatch_prefill() or worked
                else:
                    worked = self._admit() or worked
                if self._seqs:
                    if self._verify is not None:
                        self._verify_once()
                    else:
                        self._decode_once()
                    worked = True
                if not worked and not self._seqs:
                    time.sleep(self._IDLE_TICK)
        finally:
            with self._admit_lock:
                self._stop.set()
            # only NOW may the prefill group stand down: drain() sets
            # _stop while the loop is still feeding queued work through
            # the workers — a worker that exits on _stop alone deadlocks
            # the drain (groups pile up in a queue nobody serves and
            # _pipeline_idle never goes true).  Workers key off THIS
            # event instead, set strictly after the loop stopped
            # producing.  Then stop them BEFORE the residue sweep: a
            # worker mid-prefill could otherwise stage its payload after
            # the sweep and strand the client forever.  Sentinels are a
            # fast-path; the timeout-get + _loop_exited check is the
            # guarantee.
            self._loop_exited.set()
            for _ in self._prefill_threads:
                try:
                    self._prefill_q.put_nowait(None)
                except queue.Full:
                    break
            for t in self._prefill_threads:
                t.join(timeout=30)
            self._fail_residue()

    # ---- prefix sharing ----
    def _release(self, pages):
        """Drop one hold on ``pages`` and withdraw the prefix-index
        entries of every page that actually left residency — the ONLY
        way scheduler code returns pages (a raw ``alloc.free`` would
        leave the index advertising free-listed pages).  Decode-loop
        thread only, like every index touch."""
        released = self.alloc.free(pages)
        for p in released:
            ent = self._indexed_by_page.pop(p, None)
            if ent is not None:
                parent, toks = ent
                kids = self._children.get(parent)
                if kids is not None:
                    kids.pop(toks, None)
                    if not kids:
                        self._children.pop(parent, None)
        for p in released:
            # a released parent takes its child table with it (its
            # children were released in the same call — nothing live
            # can outlive the prefix it chains from)
            self._children.pop(p, None)
        return released

    def _deindex(self, page):
        """Withdraw one page's prefix-index entry (about to be written
        by its sole holder — the advertised block content would lie)."""
        ent = self._indexed_by_page.pop(int(page), None)
        if ent is not None:
            parent, toks = ent
            kids = self._children.get(parent)
            if kids is not None:
                kids.pop(toks, None)
                if not kids:
                    self._children.pop(parent, None)

    def _match_prefix(self, prompt):
        """Resident pages a new prompt's leading blocks can map onto:
        walk the index chain from the root matching FULL token blocks
        exactly; the final PARTIAL block may additionally map onto a
        resident full block whose leading tokens match (a superset —
        the extra tokens are masked by ``lengths``, and the sequence's
        first write into that page takes the CoW fault).  Returns the
        (possibly empty) list of resident page ids, prefix order."""
        ps = self.alloc.page_size
        n = int(prompt.shape[0])
        shared, parent = [], 0
        for b in range(-(-n // ps)):
            kids = self._children.get(parent)
            if not kids:
                break
            chunk = prompt[b * ps:(b + 1) * ps]
            if chunk.shape[0] == ps:
                page = kids.get(tuple(int(t) for t in chunk))
                if page is None:
                    break
                shared.append(page)
                parent = page
            else:
                r = chunk.shape[0]
                part = tuple(int(t) for t in chunk)
                for toks, page in kids.items():
                    if toks[:r] == part:
                        shared.append(page)     # superset: CoW on write
                        break
                break
        return shared

    def _index_prompt(self, seq):
        """Publish a seated sequence's FULL prompt blocks to the prefix
        index (first writer wins — a block already resident elsewhere
        keeps its canonical page).  Only full blocks are indexable:
        their content is complete and, because decode writes always
        land past the prompt, immutable while resident."""
        ps = self.alloc.page_size
        parent = 0
        for b in range(int(seq.prompt.shape[0]) // ps):
            toks = tuple(int(t) for t in seq.prompt[b * ps:(b + 1) * ps])
            page = seq.pages[b]
            kids = self._children.get(parent)
            cur = None if kids is None else kids.get(toks)
            if cur is None:
                if kids is None:
                    kids = self._children.setdefault(parent, {})
                kids[toks] = page
                self._indexed_by_page[page] = (parent, toks)
                cur = page
            if cur != page:
                # the canonical chain diverged from our residency (a
                # twin indexed first) — stop; the canonical pages
                # already serve future matches
                break
            parent = page

    def _map_pages(self, seq):
        """Hand one admitted sequence its prompt pages: leading blocks
        resident in the prefix index are SHARED (a refcount bump, zero
        pool cost); only the remainder is allocated — all-or-nothing,
        so ``PoolExhaustedError`` leaves nothing taken."""
        ptoks = self._prefill_tokens(seq)
        n = int(ptoks.shape[0])
        shared = self._match_prefix(ptoks)
        own = self.alloc.alloc(self.alloc.pages_for(n) - len(shared))
        self.alloc.share(shared)
        seq.pages = shared + own
        seq.shared_n = len(shared)
        self._bump("pages_charged", len(own))
        if shared:
            self._bump("pages_shared_mapped", len(shared))
            if seq.out:
                # resume re-maps onto still-resident pages — the
                # prefix-index dividend that makes preemption cheap
                self._bump("resume_pages_remapped", len(shared))
        # index NOW, not at seat time: the same program call that maps
        # these pages fills them (prefill scatter / handoff), so a
        # LATER sequence in the same batch can already share them — a
        # fleet of identical system prompts shares from request two
        # onward.  A failed prefill releases the pages, which withdraws
        # the entries again.
        self._index_prompt(seq)

    def _scatter_table_row(self, seq):
        """The page-table row a PREFILL/HANDOFF scatter may write
        through: shared blocks are zeroed so their writes sink to page
        0 — resident shared pages must never be rewritten (a superset-
        shared page holds MORE tokens than this prompt claims, and the
        program zero-pads past ``lengths``).  The DECODE table keeps
        the real ids: attention reads the resident prefix."""
        row = np.zeros((self.pages_per_seq,), np.int32)
        row[:len(seq.pages)] = seq.pages
        row[:seq.shared_n] = 0
        return row

    # ---- retirement ----
    def _vacate(self, seq):
        """Release a sequence's slot + pages (no request resolution)."""
        if seq.req.trace is not None:
            _telemetry.end_span(seq.req, "decode", tokens=len(seq.out))
        if seq.slot is not None:
            s = seq.slot
            self._bump("active_slots", -1)
            self._active[s] = False
            self._lengths[s] = 0
            self._tokens[s] = 0
            self._tables[s, :] = 0
            self._temps[s] = 0.0
            self._topks[s] = 0
            self._cow_src[s] = 0
            self._cow_dst[s] = 0
            self._window[s, :] = 0
            self._nvalid[s] = 1
            self._seqs.pop(s, None)
            seq.slot = None
        if seq.pages:
            self._release(seq.pages)
            seq.pages = []
        seq.shared_n = 0
        self._note_occupancy()

    def _note_occupancy(self):
        total = self.alloc.allocatable
        held = total - self.alloc.free_count()
        self._c_pages.set_value(int(100 * held / total))

    def _retire(self, seq, error=None, stat="completed"):
        """Terminal retirement: vacate, resolve the future, account.
        Journaled (retirement granularity) — EXCEPT in handoff-drain
        mode, where exported sequences must stay importable: a retire
        record would erase the handoff record the next server reads."""
        if seq.pages:
            self._h_slot_pages.observe(len(seq.pages))
        self._vacate(seq)
        if error is None:
            seq.req.set_result(np.asarray(seq.out, np.int32))
        else:
            seq.req.set_error(error)
        if not self._handoff_exit.is_set():
            self._journal_event("gen_retire", rid=seq.rid, status=stat)
        self._bump(stat)
        self._bump("retired")
        self._c_retired.increment()

    def _retire_expired(self):
        """Deadline sweep: queued sequences expire without device work,
        in-flight ones mid-generation (pages freed either way; the
        error carries the partial tokens — progress is visible, ISSUE
        19, not discarded silently)."""
        worked = False
        now = time.monotonic()
        for seq in [s for s in self._seqs.values()
                    if s.req.expired(now)]:
            self._retire(seq, DeadlineExceededError(
                f"deadline exceeded mid-generation after "
                f"{len(seq.out)} of {seq.max_new} tokens — pages freed, "
                f"partial output on the error",
                tokens_generated=len(seq.out),
                partial_tokens=[int(t) for t in seq.out]),
                stat="expired")
            worked = True
        with self._admit_lock:
            queued = [s for s in self._pending if s.req.expired(now)]
            for s in queued:
                self._pending.remove(s)
        for seq in queued:
            self._retire(seq, DeadlineExceededError(
                "deadline exceeded in queue after preemption — partial "
                "tokens on the error" if seq.ran else
                "deadline exceeded in queue — the request never touched "
                "the device",
                tokens_generated=len(seq.out),
                partial_tokens=[int(t) for t in seq.out]),
                stat="expired")
            worked = True
        return worked

    # ---- admission into slots ----
    def _free_slots(self):
        return [s for s in range(self.n_slots) if s not in self._seqs]

    def _bucket_len(self, n):
        return next(L for L in self.buckets.length if L >= n)

    def _prefill_len(self, seq):
        """Tokens a (re-)prefill of this sequence runs through the
        bucket grid.  Fresh sequence: the prompt.  Resume (``seq.out``
        non-empty): prompt + generated-so-far minus the pending token —
        the exact step-boundary cache occupancy — capped at the largest
        length bucket.  The overflow tail becomes ``seq.replay``,
        forced one token per step through the pinned decode/verify
        program (a chunked prefill through the grid is impossible: the
        bucket programs recompute the whole context, so a chunk's
        forward would need K/V the grid cannot be given).  Either way
        resume reuses ONLY existing executables — the census contract
        is untouched."""
        n = int(seq.prompt.shape[0])
        if not seq.out:
            return n
        return min(n + len(seq.out) - 1, max(self.buckets.length))

    def _prefill_tokens(self, seq):
        """The token array a (re-)prefill feeds the bucket grid."""
        if not seq.out:
            return seq.prompt
        full = np.concatenate([seq.prompt,
                               np.asarray(seq.out, np.int32)])
        return full[:self._prefill_len(seq)]

    def _take_prefill_group(self, need_resources=True):
        """Pop one same-length-bucket group of queued sequences, highest
        QoS priority first (FIFO by admission stamp within a class —
        the per-class p99 ordering the SLO chaos mode asserts).  With
        ``need_resources`` (the fused path) the group is also capped by
        free slots and budgeted against free pages; the disaggregated
        path prefills ahead of seat availability — flow control is the
        bounded prefill queue.  Returns [] when nothing can start."""
        if need_resources:
            limit = min(len(self._free_slots()), self.buckets.max_batch)
        else:
            limit = self.buckets.max_batch
        if limit == 0:
            return []
        with self._admit_lock:
            if not self._pending:
                return []
            ordered = sorted(self._pending,
                             key=lambda s: (-s.priority, s.stamp))
            bucket = self._bucket_len(self._prefill_len(ordered[0]))
            group, budget = [], self.alloc.free_count()
            for seq in ordered:
                if len(group) >= limit:
                    break
                if self._bucket_len(self._prefill_len(seq)) != bucket:
                    continue
                if need_resources:
                    # charge only NON-shared pages: blocks resident in
                    # the prefix index cost nothing — the concurrency
                    # multiplier of prefix sharing lands here
                    need = self.alloc.pages_for(self._prefill_len(seq)) \
                        - len(self._match_prefix(
                            self._prefill_tokens(seq)))
                    if need > budget:
                        break   # keep order: don't starve the big one
                    budget -= need
                group.append(seq)
            for seq in group:
                self._pending.remove(seq)
        return group

    def _admit(self):
        """Admit queued sequences into free decode slots (prefill).
        While the breaker fast-fails nothing is admitted; once its probe
        timer expires a SINGLE group goes through as the trial — its
        verdict closes or re-opens the circuit (the
        ``InferenceServer`` admission stance, at group granularity)."""
        if self.breaker.engaged():
            return False
        cautious = self.breaker.state_code() != 0
        worked = False
        while True:
            group = self._take_prefill_group()
            if not group:
                return worked
            worked = True
            self._prefill_group(group)
            if cautious:
                return worked

    # ---- disaggregated prefill group ----
    def _dispatch_prefill(self):
        """Feed queued sequences to the prefill worker group (bounded
        queue = flow control; only the decode loop produces, so
        ``full()`` then ``put_nowait`` cannot race).  Mirrors
        ``_admit``'s breaker stance: nothing while engaged, a single
        trial group while cautious."""
        if self.breaker.engaged():
            return False
        cautious = self.breaker.state_code() != 0
        worked = False
        while not self._prefill_q.full() \
                and len(self._handoff_backlog) <= self.n_slots:
            group = self._take_prefill_group(need_resources=False)
            if not group:
                return worked
            for seq in group:          # queue ends at dispatch; prefill
                if seq.req.trace is not None:   # covers the worker leg
                    _telemetry.end_span(seq.req, "queue")
                    _telemetry.open_span(seq.req, "prefill")
            with self._lock:
                self._prefill_flight[id(group)] = group
            self._prefill_q.put_nowait(group)
            worked = True
            if cautious:
                return worked
        return worked

    def _prefill_worker(self):
        """One prefill-group worker: pull a group, run the pool-free
        prefill, stage the KV payload onto the handoff queue.  Never
        touches the pools, the allocator, or the slot arrays — the
        decode group's state is not this thread's to break."""
        while True:
            try:
                group = self._prefill_q.get(timeout=self._IDLE_TICK * 4)
            except queue.Empty:
                # NOT self._stop: drain() sets that while the decode loop
                # is still dispatching queued work through this group —
                # exiting then strands every group it would have served.
                # The loop signals _loop_exited once it truly stops.
                if self._loop_exited.is_set():
                    return
                continue
            if group is None:              # drain sentinel, one per worker
                return
            try:
                self._do_prefill_kv(group)
            finally:
                with self._lock:
                    self._prefill_flight.pop(id(group), None)

    def _do_prefill_kv(self, group):
        """Run one group through the pool-free prefill and hand off the
        per-sequence payloads.  Resumed members run prompt + generated
        through the same bucket executables.  A failure resolves the
        whole group explicitly (breaker sees it; resumed members are
        salvaged against their retry budget); the pools are untouched
        either way — prefill-side faults cannot hurt seated
        sequences."""
        k = len(group)
        bucket = self._bucket_len(max(self._prefill_len(s)
                                      for s in group))
        b = self.buckets.batch_bucket(k)
        tokens = np.zeros((b, bucket), np.int32)
        lengths = np.zeros((b,), np.int32)
        seeds = np.zeros((b,), np.uint32)
        temps = np.zeros((b,), np.float32)
        topks = np.zeros((b,), np.int32)
        pspans = None
        worker = threading.current_thread().name
        for i, seq in enumerate(group):
            ptoks = self._prefill_tokens(seq)
            n = ptoks.shape[0]
            tokens[i, :n] = ptoks
            lengths[i] = n
            seeds[i] = seq.seed
            temps[i] = seq.temp
            topks[i] = seq.top_k
            if seq.req.trace is not None:
                sp = _telemetry.get_span(seq.req, "prefill")
                if sp is not None:
                    sp.attrs["worker"] = worker     # who ran the prefill
                    if pspans is None:
                        pspans = []
                    pspans.append(sp)
        if pspans is not None:
            _telemetry.push_current(pspans)
        try:
            _fault.fire("generate.prefill")
            if any(s.out for s in group):
                _fault.fire("generate.resume")
            with _profiler.scope(f"{self._name}.prefill", cat="serving"):
                first, k_all, v_all = self._run_prefill_kv(
                    tokens, lengths, seeds, temps, topks)
        except Exception as exc:    # noqa: BLE001 — resolved per sequence
            self.breaker.record_failure()
            self._note_step_failure(exc)
            err = _fault.with_context(exc, f"{self._name} prefill of {k}")
            for seq in group:
                if seq.out:
                    self._requeue_salvaged(seq, err)
                else:
                    self._retire(seq, err, stat="failed")
            return
        finally:
            if pspans is not None:
                _telemetry.pop_current()
        self.breaker.record_success()
        self._bump("prefills")
        for i, seq in enumerate(group):
            n = self._prefill_len(seq)
            if seq.req.trace is not None:   # handoff wait + scatter next
                _telemetry.end_span(seq.req, "prefill")
                _telemetry.open_span(seq.req, "handoff")
            # per-sequence payload: the decode loop re-packs any mix of
            # these into the fixed-shape handoff batch.  Copied — a view
            # parked in the handoff backlog would pin the whole
            # [n_layers, b, L, H, D] batch output, not just its own rows
            self._handoff_q.put((seq, int(first[i]),
                                 k_all[:, i, :n].copy(),
                                 v_all[:, i, :n].copy()))

    def _drain_handoffs(self):
        """Seat prefilled sequences: pack every seatable payload (free
        slot + pages, deadline not passed) into ONE fixed-shape handoff
        batch, scatter it into the pools, seat the sequences.  Payloads
        that cannot seat yet stay in the backlog for the next tick —
        slots free every step as sequences retire."""
        backlog = self._handoff_backlog
        self._handoff_backlog = []
        while True:
            try:
                backlog.append(self._handoff_q.get_nowait())
            except queue.Empty:
                break
        if not backlog:
            return False
        worked = False
        batch, still = [], []
        now = time.monotonic()
        free_slots = self._free_slots()
        budget = self.alloc.free_count()
        for entry in backlog:
            seq, first_tok, k_seq, v_seq = entry
            if seq.req.expired(now):
                self._retire(seq, DeadlineExceededError(
                    "deadline exceeded before the prefilled sequence "
                    "reached a decode slot — pages never held",
                    tokens_generated=len(seq.out),
                    partial_tokens=[int(t) for t in seq.out]),
                    stat="expired")
                worked = True
                continue
            need = self.alloc.pages_for(self._prefill_len(seq)) \
                - len(self._match_prefix(self._prefill_tokens(seq)))
            if len(batch) >= min(len(free_slots), self.buckets.max_batch) \
                    or need > budget:
                still.append(entry)
                continue
            budget -= need
            batch.append(entry)
        self._handoff_backlog = still
        if not batch:
            return worked
        B = self.buckets.max_batch
        kbuf, vbuf = self._staging()
        lengths = np.zeros((B,), np.int32)
        active = np.zeros((B,), bool)
        tables = np.zeros((B, self.pages_per_seq), np.int32)
        seated = []
        hspans = None
        for seq, _t, _k, _v in batch:
            if seq.req.trace is not None:
                sp = _telemetry.get_span(seq.req, "handoff")
                if sp is not None:
                    if hspans is None:
                        hspans = []
                    hspans.append(sp)
        if hspans is not None:
            _telemetry.push_current(hspans)
        try:
            _fault.fire("fleet.handoff")
            for j, (seq, first_tok, k_seq, v_seq) in enumerate(batch):
                n = k_seq.shape[1]
                self._map_pages(seq)
                kbuf[:, j, :n] = k_seq
                vbuf[:, j, :n] = v_seq
                lengths[j] = n
                active[j] = True
                tables[j] = self._scatter_table_row(seq)
                seated.append(seq)
            with _profiler.scope(f"{self._name}.handoff", cat="serving"):
                self._run_handoff(kbuf, vbuf, lengths, active, tables)
        except Exception as exc:    # noqa: BLE001 — resolved per sequence
            self.breaker.record_failure()
            self._note_step_failure(exc)
            err = _fault.with_context(
                exc, f"{self._name} handoff of {len(batch)}")
            for seq, _t, _k, _v in batch:
                if seq.out:
                    self._requeue_salvaged(seq, err)
                else:
                    self._retire(seq, err, stat="failed")
            self._recover_pools()
            return True
        finally:
            if hspans is not None:
                _telemetry.pop_current()
        self._bump("handoffs")
        slots = self._free_slots()
        for j, (seq, first_tok, _k, _v) in enumerate(batch):
            self._seat(seq, slots[j], first_tok)
        self._note_occupancy()
        return True

    def _prefill_group(self, group):
        """Prefill one bucket-aligned group and seat it in decode slots.
        Resumed members (``seq.out`` non-empty) run prompt + generated
        through the SAME bucket executables — their sampled first token
        is overridden at seat time by the recorded one."""
        k = len(group)
        bucket = self._bucket_len(max(self._prefill_len(s)
                                      for s in group))
        b = self.buckets.batch_bucket(k)
        slots = self._free_slots()[:k]
        pspans = None
        worker = threading.current_thread().name
        for seq in group:              # queue ended at the pop; prefill
            if seq.req.trace is not None:   # covers alloc + the program
                _telemetry.end_span(seq.req, "queue")
                sp = _telemetry.open_span(seq.req, "prefill",
                                          worker=worker)
                if sp is not None:
                    if pspans is None:
                        pspans = []
                    pspans.append(sp)
        try:
            for seq in group:
                self._map_pages(seq)
        except PoolExhaustedError:
            # _take_prefill_group budgeted against the free count, so
            # only a racing... nothing else allocates; defensive re-queue
            for seq in group:
                self._vacate(seq)
                if seq.req.trace is not None:
                    _telemetry.end_span(seq.req, "prefill")
                    _telemetry.open_span(seq.req, "queue", requeued=True)
            with self._admit_lock:
                self._pending.extendleft(reversed(group))
            return
        tokens = np.zeros((b, bucket), np.int32)
        lengths = np.zeros((b,), np.int32)
        active = np.zeros((b,), bool)
        tables = np.zeros((b, self.pages_per_seq), np.int32)
        seeds = np.zeros((b,), np.uint32)
        temps = np.zeros((b,), np.float32)
        topks = np.zeros((b,), np.int32)
        for i, seq in enumerate(group):
            ptoks = self._prefill_tokens(seq)
            n = ptoks.shape[0]
            tokens[i, :n] = ptoks
            lengths[i] = n
            active[i] = True
            tables[i] = self._scatter_table_row(seq)
            seeds[i] = seq.seed
            temps[i] = seq.temp
            topks[i] = seq.top_k
        if pspans is not None:
            _telemetry.push_current(pspans)
        try:
            _fault.fire("generate.prefill")
            if any(s.out for s in group):
                _fault.fire("generate.resume")
            with _profiler.scope(f"{self._name}.prefill", cat="serving"):
                first = self._run_prefill(tokens, lengths, active, tables,
                                          seeds, temps, topks)
        except Exception as exc:    # noqa: BLE001 — resolved per sequence
            self.breaker.record_failure()
            self._note_step_failure(exc)
            err = _fault.with_context(exc, f"{self._name} prefill of {k}")
            for seq in group:
                if seq.out:
                    # a resumed member's tokens survive the failed
                    # re-prefill — salvage against its retry budget
                    self._requeue_salvaged(seq, err)
                else:
                    self._retire(seq, err, stat="failed")
            self._recover_pools()
            return
        finally:
            if pspans is not None:
                _telemetry.pop_current()
        self.breaker.record_success()
        self._bump("prefills")
        for i, seq in enumerate(group):
            if seq.req.trace is not None:
                _telemetry.end_span(seq.req, "prefill")
            self._seat(seq, slots[i], int(first[i]))
        self._note_occupancy()

    def _seat(self, seq, slot, tok):
        """Seat one prefilled sequence in a decode slot: slot init is
        seat-time only — the per-token path advances ``_tokens`` /
        ``_lengths``; ``_ensure_capacity`` appends table entries.

        A RESUMED sequence (``seq.out`` non-empty) re-enters here after
        its re-prefill covered ``full[:H]`` (``full`` = prompt ++
        generated, ``H = _prefill_len``): the pending token is forced to
        the recorded ``full[H]`` (the prefill's sampled first token is
        identical under position-keyed sampling, but the record is
        authoritative), recorded tokens past ``H`` replay one per step
        through the pinned decode path, and only then does live sampling
        continue — token-exact, zero new executables."""
        if seq.req.trace is not None:
            _telemetry.end_span(seq.req, "handoff")   # no-op when fused
            _telemetry.open_span(seq.req, "decode", slot=slot)
        seq.cached = seq.prompt.shape[0]
        seq.ran = True
        s = seq.slot = slot
        self._seqs[s] = seq
        self._bump("active_slots")
        self._tables[s, :] = 0
        # the REAL table: shared pages included — decode attention
        # reads the resident prefix (the scatter row already sank its
        # writes to page 0)
        self._tables[s, :len(seq.pages)] = seq.pages
        self._temps[s] = seq.temp
        self._topks[s] = seq.top_k
        self._seeds[s] = seq.seed
        self._active[s] = True
        self._cow_src[s] = 0
        self._cow_dst[s] = 0
        if seq.out:
            full = np.concatenate(
                [seq.prompt, np.asarray(seq.out, np.int32)])
            H = self._prefill_len(seq)
            seq.cached = H
            seq.replay = [int(t) for t in full[H + 1:]]
            self._tokens[s] = int(full[H])
            self._lengths[s] = H
            self._bump("resumes")
            if seq.req.trace is not None:
                _telemetry.span_event(seq.req, "resume",
                                      tokens=len(seq.out),
                                      replay=len(seq.replay))
            if self._verify is not None:
                self._refresh_window(seq)
            return
        if not self._finish_token(seq, tok) and self._verify is not None:
            self._refresh_window(seq)

    def _finish_token(self, seq, tok):
        """Account one newly generated token; True if the sequence
        retired (EOS or max-tokens).  A continuing sequence's per-token
        slot state advances so the next decode step consumes ``tok``
        (the page-table row is owned by seat-time init +
        ``_ensure_capacity`` — never rewritten here)."""
        if self._eos is not None and tok == self._eos:
            self._retire(seq)
            return True
        seq.out.append(tok)
        self._bump("tokens_out")
        self._c_tokens.increment()
        if len(seq.out) >= seq.max_new:
            self._retire(seq)
            return True
        s = seq.slot
        self._tokens[s] = tok
        self._lengths[s] = seq.cached
        return False

    # ---- decode ----
    def _ensure_capacity(self, seq, lookahead=0):
        """Guarantee pages exist for this step's write positions (the
        pending token plus ``lookahead`` speculative candidates), then
        arm the slot's CoW fault if the write block is shared.  When
        the pool is dry, eviction is strictly seniority-ordered: a
        sequence may only preempt YOUNGER neighbours (later admission
        stamp — preserved across preemptions, so a restarted sequence
        keeps its place in line); with no younger neighbour it yields
        ITSELF back to the queue.  The oldest in-flight sequence is
        therefore never evicted — combined with admission's
        worst-case-fit check (its full need fits the pool alone) that
        is the global progress guarantee: symmetric mutual eviction, the
        livelock where two sequences endlessly restart each other, is
        impossible by construction.  Returns False when ``seq`` yielded
        (the caller must skip it this step)."""
        while True:
            try:
                while self.alloc.pages_for(seq.cached + 1 + lookahead) \
                        > len(seq.pages):
                    seq.pages.extend(self.alloc.alloc(1))
                    self._tables[seq.slot, len(seq.pages) - 1] = \
                        seq.pages[-1]
                self._cow_guard(seq)
                return True
            except PoolExhaustedError:
                victims = [s for s in self._seqs.values()
                           if s is not seq and s.stamp > seq.stamp]
                if victims:
                    self._preempt(max(victims, key=lambda s: s.stamp))
                elif len(self._seqs) > 1:
                    self._preempt(seq)     # we are the youngest: yield
                    return False
                else:
                    raise     # alone and dry: admission math was violated

    def _cow_guard(self, seq):
        """Copy-on-write fault check for this step's write block.  Only
        the block holding position ``seq.cached`` can be shared (all
        shared blocks are prompt blocks and writes land at or past the
        prompt's tail; later lookahead positions are in freshly
        allocated pages), so ONE check per slot per step suffices.  On
        a fault: allocate a fresh page (``PoolExhaustedError``
        propagates to the caller's preemption loop), drop our hold on
        the shared page, remap table + page list, and arm the in-graph
        page copy lanes.  A sole-holder write into a still-indexed page
        instead withdraws the index entry — the block's advertised
        content is about to change."""
        s = seq.slot
        blk = seq.cached // self.alloc.page_size
        page = seq.pages[blk]
        if self.alloc.refcount(page) > 1:
            fresh = self.alloc.alloc(1)[0]
            self._release([page])          # others still hold it
            seq.pages[blk] = fresh
            seq.shared_n = min(seq.shared_n, blk)
            self._tables[s, blk] = fresh
            self._cow_src[s] = page
            self._cow_dst[s] = fresh
            self._bump("cow_faults")
        elif page in self._indexed_by_page:
            self._deindex(page)

    def _refresh_window(self, seq):
        """Right-align the draft's token context: the last
        ``spec_window`` tokens through the PENDING token (the draft
        proposes its successors).  At steady state that is all of
        prompt + generated; during resume replay the pending token sits
        at position ``seq.cached`` and later recorded tokens must stay
        out of the draft's view."""
        s = seq.slot
        W = self._spec_window
        toks = np.concatenate(
            [seq.prompt,
             np.asarray(seq.out, np.int32)])[:seq.cached + 1][-W:]
        self._window[s, :] = 0
        self._window[s, W - len(toks):] = toks
        self._nvalid[s] = len(toks)

    def _preempt(self, victim):
        """Evict a sequence: free its pages and requeue it at the FRONT
        WITH its generated-so-far tokens (ISSUE 19) — re-admission
        re-prefills prompt + generated through the existing bucket grid
        and the position-keyed sampler continues the identical stream,
        so preemption costs latency, never work.  The request future is
        untouched: preemption is invisible to the client beyond that
        latency.  Preemption is scheduling, not failure — it does NOT
        consume the salvage-retry budget."""
        _fault.fire("generate.evict")
        self._vacate(victim)
        victim.cached = 0
        victim.replay = []
        if victim.out:
            self._bump("tokens_salvaged", len(victim.out))
        self._bump("preempted")
        self._c_preempted.increment()
        self._journal_event("gen_snapshot", rid=victim.rid,
                            out=list(victim.out))
        if victim.req.trace is not None:
            # preemption is a span event on the tree, and the requeue
            # wait is a fresh queue span — the restarted life (queue →
            # prefill → decode again) stays attributed
            _telemetry.span_event(victim.req, "preempt",
                                  tokens_salvaged=len(victim.out))
            _telemetry.open_span(victim.req, "queue", requeued=True)
        with self._admit_lock:
            self._pending.appendleft(victim)

    def _requeue_salvaged(self, seq, err, budgeted=True):
        """Salvage one accepted sequence off a failure domain (ISSUE
        19): keep its generated tokens, requeue it for a token-exact
        resume.  ``budgeted`` failures (the sequence sat in the failing
        step) consume the per-sequence ``salvage_retries`` budget —
        exhausted, the sequence retires with a terminal error carrying
        ``tokens_generated`` / ``partial_tokens`` / ``snapshot``, which
        is what fleet failover redispatches to the next replica.
        Unbudgeted salvage (breaker fast-fail, collateral pool loss)
        preserves work without charging the sequence for a failure
        that was not its own.  Returns True when the sequence was
        requeued, False when it retired terminally."""
        if budgeted:
            seq.salvage += 1
            if seq.salvage > self._salvage_retries:
                terminal = _fault.with_context(
                    err, f"{self._name}: salvage budget "
                    f"({self._salvage_retries}) exhausted after "
                    f"{len(seq.out)} of {seq.max_new} tokens — partial "
                    f"output and a resume snapshot ride the error")
                terminal.tokens_generated = len(seq.out)
                terminal.partial_tokens = [int(t) for t in seq.out]
                terminal.snapshot = self._snapshot_of(seq)
                self._retire(seq, terminal, stat="failed")
                return False
            self._bump("salvage_retries")
        try:
            _fault.fire("generate.salvage")
        except Exception as sexc:   # noqa: BLE001 — salvage path faulted
            terminal = _fault.with_context(
                sexc, f"{self._name}: salvage of sequence {seq.rid} "
                f"failed — resolving with partial output")
            terminal.tokens_generated = len(seq.out)
            terminal.partial_tokens = [int(t) for t in seq.out]
            terminal.snapshot = self._snapshot_of(seq)
            self._retire(seq, terminal, stat="failed")
            return False
        self._vacate(seq)
        seq.cached = 0
        seq.replay = []
        self._bump("tokens_salvaged", len(seq.out))
        self._journal_event("gen_snapshot", rid=seq.rid,
                            out=list(seq.out))
        if seq.req.trace is not None:
            _telemetry.end_span(seq.req, "prefill")
            _telemetry.end_span(seq.req, "handoff")
            _telemetry.span_event(seq.req, "salvage",
                                  tokens_salvaged=len(seq.out),
                                  retry=seq.salvage)
            _telemetry.open_span(seq.req, "queue", requeued=True)
        with self._admit_lock:
            self._pending.appendleft(seq)
        return True

    def _salvage_seated(self, err, budgeted=True):
        """Requeue every seated sequence with its tokens intact — the
        ISSUE 19 replacement for failing everything on a device step
        failure or a breaker fast-fail."""
        for seq in list(self._seqs.values()):
            self._requeue_salvaged(seq, err, budgeted=budgeted)

    def _decode_once(self):
        """One token for every in-flight sequence: capacity, the pinned
        decode executable, then per-slot retirement/advance."""
        self._cow_src[:] = 0        # fault lanes re-arm per step
        self._cow_dst[:] = 0
        try:
            # oldest first: seniors claim pages (evicting juniors if the
            # pool is dry) before juniors decide whether to yield
            for seq in sorted(self._seqs.values(), key=lambda s: s.stamp):
                if seq.slot is None:
                    continue     # preempted by an earlier neighbour
                self._ensure_capacity(seq)
        except PoolExhaustedError as exc:
            # unreachable via admission's worst-case check; resolve
            # rather than wedge if it ever happens
            self._fail_everything(_fault.with_context(
                exc, f"{self._name} page pool wedged"))
            return
        if not self._seqs:
            return
        if not self.breaker.allow():
            # breaker fast-fail: salvage, don't destroy — seated work
            # goes back to the queue with tokens intact and re-seats
            # when the probe succeeds.  Unbudgeted: the breaker being
            # open is not this sequence's failure.
            self._salvage_seated(CircuitOpenError(
                f"{self._name}: circuit open — fast-failing in-flight "
                f"generation"), budgeted=False)
            return
        dspans = None
        for seq in self._seqs.values():    # fault firings → span events
            if seq.req.trace is not None:
                sp = _telemetry.get_span(seq.req, "decode")
                if sp is not None:
                    if dspans is None:
                        dspans = []
                    dspans.append(sp)
        if dspans is not None:
            _telemetry.push_current(dspans)
        try:
            _fault.fire("generate.decode")
            with _profiler.scope(f"{self._name}.decode", cat="serving"):
                nxt = self._run_decode()
        except Exception as exc:    # noqa: BLE001 — resolved per sequence
            self.breaker.record_failure()
            self._note_step_failure(exc)
            err = _fault.with_context(
                exc, f"{self._name} decode step over "
                f"{len(self._seqs)} sequences")
            self._salvage_seated(err)
            self._recover_pools()
            return
        finally:
            if dspans is not None:
                _telemetry.pop_current()
        self.breaker.record_success()
        self._bump("decode_steps")
        for seq in list(self._seqs.values()):
            seq.cached += 1          # this step wrote the input token
            if seq.replay:
                # resume replay: the step re-derived this recorded
                # token (position-keyed sampling); advance the slot
                # from the record — never re-append to seq.out
                tok = seq.replay.pop(0)
                self._tokens[seq.slot] = tok
                self._lengths[seq.slot] = seq.cached
                if self._verify is not None:
                    self._refresh_window(seq)
                continue
            self._finish_token(seq, int(nxt[seq.slot]))
        self._journal_tick()

    def _verify_once(self):
        """One SPECULATIVE step for every in-flight sequence: capacity
        with ``spec_k`` lookahead, the pinned verify executable, then
        1..k+1 accepted tokens per slot.  Mirrors ``_decode_once``'s
        failure/breaker/span semantics exactly — same fault point, so
        chaos drives both paths with one name."""
        self._cow_src[:] = 0
        self._cow_dst[:] = 0
        try:
            for seq in sorted(self._seqs.values(), key=lambda s: s.stamp):
                if seq.slot is None:
                    continue     # preempted by an earlier neighbour
                self._ensure_capacity(seq, lookahead=self._spec_k)
        except PoolExhaustedError as exc:
            self._fail_everything(_fault.with_context(
                exc, f"{self._name} page pool wedged"))
            return
        if not self._seqs:
            return
        if not self.breaker.allow():
            self._salvage_seated(CircuitOpenError(
                f"{self._name}: circuit open — fast-failing in-flight "
                f"generation"), budgeted=False)
            return
        dspans = None
        for seq in self._seqs.values():
            if seq.req.trace is not None:
                sp = _telemetry.get_span(seq.req, "decode")
                if sp is not None:
                    if dspans is None:
                        dspans = []
                    dspans.append(sp)
        if dspans is not None:
            _telemetry.push_current(dspans)
        try:
            _fault.fire("generate.decode")
            with _profiler.scope(f"{self._name}.verify", cat="serving"):
                emitted, n_acc = self._run_verify()
        except Exception as exc:    # noqa: BLE001 — resolved per sequence
            self.breaker.record_failure()
            self._note_step_failure(exc)
            err = _fault.with_context(
                exc, f"{self._name} verify step over "
                f"{len(self._seqs)} sequences")
            self._salvage_seated(err)
            self._recover_pools()
            return
        finally:
            if dspans is not None:
                _telemetry.pop_current()
        self.breaker.record_success()
        self._bump("decode_steps")
        self._bump("verify_steps")
        k = self._spec_k
        for seq in list(self._seqs.values()):
            s = seq.slot
            if seq.replay:
                # resume replay: force ONE recorded token per step and
                # skip speculative accounting — the draft window is
                # truncated at the pending position, so acceptance
                # stats over replayed steps would be meaningless
                seq.cached += 1
                tok = seq.replay.pop(0)
                self._tokens[s] = tok
                self._lengths[s] = seq.cached
                self._refresh_window(seq)
                continue
            a = int(n_acc[s])
            self._bump("spec_proposed", k)
            self._bump("spec_accepted", a)
            self._h_accept.observe(a / k)
            # positions 0..a hold real K/V (pending + accepted drafts);
            # emitted[a] is the correction/bonus — the next pending
            # token, K/V not yet written
            for j in range(a + 1):
                seq.cached += 1
                if self._finish_token(seq, int(emitted[s, j])):
                    break
            else:
                self._refresh_window(seq)
        self._journal_tick()

    def _export_error(self, seq):
        """Resolve one exported sequence's request (handoff drain): the
        snapshot — and the partial tokens — ride a ``ServerClosedError``
        so the caller (typically a fleet router) can redispatch it
        token-exact, and the journal gains a ``gen_handoff`` record a
        successor's ``restore_journal`` re-admits."""
        snap = self._snapshot_of(seq)
        self.exported.append(snap)
        self._journal_event("gen_handoff", **snap.to_json())
        self._bump("handoff_exports")
        err = ServerClosedError(
            f"{self._name}: drained with handoff after {len(seq.out)} "
            f"of {seq.max_new} tokens — resume snapshot exported")
        err.tokens_generated = len(seq.out)
        err.partial_tokens = [int(t) for t in seq.out]
        err.snapshot = snap
        return err

    def _export_all(self):
        """Handoff-drain sweep: every accepted sequence still alive —
        seated or queued — exports instead of finishing.  Disaggregated
        pipeline residue is swept by ``_fail_residue``, which routes
        through the same exporter in handoff mode."""
        for seq in list(self._seqs.values()):
            self._retire(seq, self._export_error(seq), stat="failed")
        with self._admit_lock:
            residue = list(self._pending)
            self._pending.clear()
        for seq in residue:
            self._retire(seq, self._export_error(seq), stat="failed")

    def _fail_everything(self, err, queued=True):
        """Explicitly resolve every in-flight (and optionally queued)
        sequence — the terminal sweep for breaker-open-during-drain and
        never-happens pool wedges.  Nothing is silently dropped."""
        for seq in list(self._seqs.values()):
            self._retire(seq, err, stat="failed")
        if not queued:
            return
        with self._admit_lock:
            residue = list(self._pending)
            self._pending.clear()
        for seq in residue:
            self._retire(seq, err, stat="failed")

    def _fail_residue(self):
        """Loop-exit sweep (a clean drain leaves nothing; a crashed loop
        may): every accepted-but-unresolved sequence gets an explicit
        terminal error — wherever it was parked, including the
        disaggregated prefill/handoff pipeline (workers are already
        joined by the caller, so these containers have no producers)."""
        residue = list(self._seqs.values())
        self._seqs = {}
        with self._admit_lock:
            residue += list(self._pending)
            self._pending.clear()
        while True:
            try:
                item = self._prefill_q.get_nowait()
            except queue.Empty:
                break
            if item is not None:
                residue += list(item)
        while True:
            try:
                residue.append(self._handoff_q.get_nowait()[0])
            except queue.Empty:
                break
        residue += [entry[0] for entry in self._handoff_backlog]
        self._handoff_backlog = []
        with self._lock:
            flight = list(self._prefill_flight.values())
            self._prefill_flight = {}
        for group in flight:
            residue += list(group)
        for seq in residue:
            if seq.slot is not None:
                seq.slot = None
                self._bump("active_slots", -1)
            if seq.req.done():
                continue
            if seq.pages:
                self._release(seq.pages)
                seq.pages = []
                seq.shared_n = 0
            if self._handoff_exit.is_set():
                seq.req.set_error(self._export_error(seq))
            else:
                seq.req.set_error(ServerClosedError(
                    "server stopped before this sequence finished"))
            self._bump("failed")
            self._bump("retired")

    # ---------------------------------------------------------------- health --
    def alive(self):
        return self._thread.is_alive()

    def ready(self):
        return (self._ready.is_set() and self.alive()
                and not self._draining.is_set()
                and not self.breaker.engaged())

    def healthz(self):
        """Router-rankable snapshot: the same keys as
        ``InferenceServer.healthz`` — ``breaker_state`` / ``in_flight`` /
        ``queue_depth`` / ``classes`` (per-class deadline-miss + p50/p99
        from ``TenantQoS.snapshot``) / ``last_error`` — so a
        ``ServingFleet`` ranks LLM and classifier replicas uniformly,
        plus the paging/disaggregation gauges.  Non-blocking: host
        counters and primitives only."""
        with self._admit_lock:
            depth = len(self._pending)
        with self._lock:
            s = self._stats
            in_flight = (s["admitted"] - s["completed"] - s["failed"]
                         - s["expired"])
            active = s["active_slots"]
            last = self._last_error
            prefill_flight = len(self._prefill_flight)
        return {"alive": self.alive(), "ready": self.ready(),
                "draining": self._draining.is_set(),
                "breaker": self.breaker.state,
                "breaker_state": self.breaker.state_code(),
                "queue_depth": depth,
                "in_flight": max(0, in_flight),
                "active_slots": active,
                "free_pages": self.alloc.free_count(),
                "total_pages": self.alloc.allocatable,
                "pages_shared": self.alloc.shared_pages(),
                "speculative": int(self._verify is not None),
                "prefill_workers": self._n_prefill_workers,
                "prefill_inflight": prefill_flight,
                "tp_shards": self.tp_shards,
                "tp_collectives": self.tp_collectives,
                "classes": self._qos.snapshot(),
                "last_error": None if last is None else
                {"type": last[0], "age": time.monotonic() - last[1]}}

    def _page_bytes(self):
        """HBM bytes one page id addresses across BOTH pools (f32
        K + V, every layer, all heads — the whole stripe a shared
        page avoids duplicating)."""
        c = self.config
        return (2 * c.n_layers * self.alloc.page_size * c.n_heads
                * c.head_dim * 4)

    @property
    def stats(self):
        with self._lock:
            out = dict(self._stats)
        out["free_pages"] = self.alloc.free_count()
        out["pages_shared"] = self.alloc.shared_pages()
        out["breaker"] = self.breaker.state
        return out

    def stamp_memory_report(self, report):
        """Stamp a costguard-style memory report (``argument_bytes`` /
        ``peak_bytes`` / ``per_device``) onto this server's ``mem_*``
        exposition gauges — the bytes are a property of the compiled
        program set, so one stamp at warmup is live until the census
        changes (see ``InferenceServer.stamp_memory_report``)."""
        self._mem_gauges = _telemetry.memory_gauges(report)
        return self._mem_gauges

    def telemetry(self, fmt="json"):
        """The unified metrics exposition (ISSUE 13): lifecycle counters,
        paging/disaggregation gauges, per-phase latency histograms
        (``queue``/``prefill``/``handoff``/``decode`` span durations,
        ms), and the per-class SLO rows — the SAME
        ``telemetry.exposition`` key schema every runtime serves.
        ``fmt="prom"`` renders Prometheus-style text."""
        h = self.healthz()
        with self._lock:
            counters = dict(self._stats)
        counters.pop("active_slots", None)     # a gauge, reported below
        gauges = {"queue_depth": h["queue_depth"],
                  "in_flight": h["in_flight"],
                  "breaker_state": h["breaker_state"],
                  "active_slots": h["active_slots"],
                  "free_pages": h["free_pages"],
                  "used_pages": h["total_pages"] - h["free_pages"],
                  "total_pages": h["total_pages"],
                  # prefix-sharing gauges (ISSUE 16): resident pages
                  # with >1 holder, CoW faults taken, and the pool
                  # bytes sharing is currently standing in for
                  "pages_shared": h["pages_shared"],
                  "pages_cow_faults": counters.get("cow_faults", 0),
                  "bytes_saved_by_sharing":
                      self.alloc.extra_refs() * self._page_bytes(),
                  "spec_k": self._spec_k if self._verify is not None
                      else 0,
                  # resume economics (ISSUE 19): pages a resumed
                  # sequence re-mapped from the prefix index instead of
                  # re-allocating — the preemption-is-cheap dividend
                  "resume_prefill_pages_remapped":
                      counters.get("resume_pages_remapped", 0),
                  "prefill_workers": h["prefill_workers"],
                  "prefill_inflight": h["prefill_inflight"],
                  "tp_shards": h["tp_shards"],
                  "ready": int(h["ready"]), "alive": int(h["alive"]),
                  "draining": int(h["draining"])}
        # the runtime-introspection families (ISSUE 15): jit-cache
        # behavior + stamped memory bytes, same keys on every runtime
        gauges.update(_telemetry.compile_gauges(self._name))
        gauges.update(self._mem_gauges)
        gauges.update(_telemetry.ckpt_gauges())
        snap = _telemetry.registry().snapshot(prefix=f"{self._name}::")
        # the registry gauges under this server's prefix ride along too
        # (page_occupancy/tokens_out/preempted/retired were previously
        # invisible to the exposition — the ISSUE 15 satellite fix);
        # healthz-derived values win on key collision
        for k, v in snap["gauges"].items():
            gauges.setdefault(k, v)
        hist = snap["histograms"]
        for cname, csnap in self._qos.latency_snapshots().items():
            hist[f"class_{cname}_latency_s"] = csnap
        payload = _telemetry.exposition("generation_server", self._name,
                                        counters, gauges, hist,
                                        h["classes"])
        return _telemetry.render(payload, fmt)

    # ----------------------------------------------------------------- drain --
    def drain(self, timeout=None, handoff=False):
        """Graceful shutdown: stop admitting (submits raise
        ``ServerClosedError``), finish EVERY accepted sequence — queued
        ones included; generation is bounded by per-request max-tokens —
        then stop the loop.  After ``drain()`` every ``Request`` ever
        returned is ``done()``.  True when the loop exited in time.

        ``handoff=True`` (ISSUE 19, rolling updates): instead of
        finishing long generations, EXPORT every unfinished sequence as
        a ``SequenceSnapshot`` — collected in ``self.exported`` and
        written to the journal as ``gen_handoff`` records — and resolve
        its request with a ``ServerClosedError`` carrying the snapshot
        and partial tokens.  A successor server completes them
        token-exact via ``submit_resume`` / ``restore_journal``."""
        if handoff:
            self._handoff_exit.set()
        self._draining.set()
        self._ready.clear()
        with self._admit_lock:
            self._stop.set()
        if self._started.is_set():
            self._thread.join(timeout)
        if not self._thread.is_alive():
            self._fail_residue()
        return not self._thread.is_alive()

    close = drain

    def serve_forever(self, poll=0.05, handoff=False):
        """Block until SIGTERM/SIGINT (``fault.GracefulExit``), then
        drain — accepted sequences resolve, mid-decode work finishes
        (``handoff=True``: they export for a successor instead)."""
        with _fault.GracefulExit() as g:
            while not g.requested and self.alive():
                time.sleep(poll)
        return self.drain(handoff=handoff)
