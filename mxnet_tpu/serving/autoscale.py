"""Supervised fleet autoscaling: a control loop over dynamic membership.

The PR 9 elastic supervisor keeps a TRAINING gang alive; this module is
its serving-side sibling (ISSUE 12): a ``FleetAutoscaler`` watches one
``ServingFleet`` replica group's load signals — queue depth, occupancy
of the live in-flight capacity, per-class deadline-miss rate — and
drives ``ServingFleet.add_replica`` / ``retire_replica`` to track them,
reusing the supervisor idioms wholesale:

- **hysteresis, not twitching** — ``ScalingPolicy`` demands
  ``up_ticks``/``down_ticks`` CONSECUTIVE over/under-threshold
  observations before a verdict, plus a post-action cooldown; a single
  traffic spike never churns membership.
- **watchdog** — scale actions run on a helper thread the control loop
  join-polls; an action that wedges past ``watchdog_secs`` (a warmup
  compile stall, a drain that never finishes) is declared hung, logged,
  and backed off — the control loop itself never blocks.
- **backoff** — failed or hung actions back off on the
  ``fault.backoff_delay`` schedule (the one exponential policy in the
  stack), resetting on the next success.
- **JSONL event log** — every verdict/action/failure lands in an
  ``elastic.EventLog`` stream (``scale-up`` / ``scale-down`` /
  ``scale-failed`` / ``scale-wedged`` / ``stop``), machine-parseable by
  the same tooling that reads the training supervisor's history.

The fleet methods themselves carry the safety contract (warmup
census-complete before a scale-up serves, quarantine→drain→remove with
zero dropped accepted requests on retire — see ``serving.fleet``); the
autoscaler only decides WHEN.  Both fault points (``fleet.scale_up``,
``fleet.retire``) fire inside the fleet methods, so chaos tests drive
the autoscaler and manual scaling through the same failure surface.
"""
from __future__ import annotations

import threading
import time

from .. import fault as _fault
from .. import telemetry as _telemetry
from ..elastic import EventLog

__all__ = ["ScalingPolicy", "FleetAutoscaler"]


class ScalingPolicy:
    """Threshold + hysteresis verdicts over ``ServingFleet``
    ``scaling_signals`` snapshots.

    Scale **up** when occupancy >= ``up_occupancy`` OR queue depth >=
    ``up_queue_depth`` OR deadline misses accrued since the last tick
    exceed ``miss_budget`` — sustained for ``up_ticks`` consecutive
    ticks, membership below ``max_replicas``.  Scale **down** when
    occupancy <= ``down_occupancy`` AND the queue is empty AND no new
    misses — sustained for ``down_ticks``.  The down bound is on READY
    replicas (``min_replicas`` must stay serving after the retire), or
    on membership when the group carries dead/quarantined members — the
    autoscaler retires those first, which never reduces live capacity.
    ``cooldown`` seconds follow every action (scale effects need a beat
    to show up in the signals; acting on stale pressure
    double-scales)."""

    def __init__(self, min_replicas=1, max_replicas=8, up_occupancy=0.75,
                 down_occupancy=0.2, up_queue_depth=8, miss_budget=0,
                 up_ticks=2, down_ticks=5, cooldown=0.5):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError(
                f"ScalingPolicy: need 1 <= min_replicas <= max_replicas, "
                f"got {min_replicas}..{max_replicas}")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.up_occupancy = float(up_occupancy)
        self.down_occupancy = float(down_occupancy)
        self.up_queue_depth = None if up_queue_depth is None \
            else int(up_queue_depth)
        self.miss_budget = int(miss_budget)
        self.up_ticks = int(up_ticks)
        self.down_ticks = int(down_ticks)
        self.cooldown = float(cooldown)
        self._over = 0            # consecutive over-pressure ticks
        self._under = 0           # consecutive under-pressure ticks
        self._last_miss = None    # cumulative miss count at last tick
        self._cool_until = 0.0

    def record_action(self):
        """An action just ran (by this policy or anyone else): reset the
        streaks and start the cooldown window."""
        self._over = self._under = 0
        self._cool_until = time.monotonic() + self.cooldown

    def verdict(self, signals):
        """``"up"`` / ``"down"`` / ``None`` for one signals snapshot."""
        misses = signals.get("deadline_miss", 0)
        new_misses = 0 if self._last_miss is None \
            else max(0, misses - self._last_miss)
        self._last_miss = misses
        pressure = signals["occupancy"] >= self.up_occupancy
        if self.up_queue_depth is not None:
            pressure = pressure or \
                signals["queue_depth"] >= self.up_queue_depth
        pressure = pressure or new_misses > self.miss_budget
        calm = (signals["occupancy"] <= self.down_occupancy
                and signals["queue_depth"] == 0 and new_misses == 0)
        self._over = self._over + 1 if pressure else 0
        self._under = self._under + 1 if calm else 0
        if time.monotonic() < self._cool_until:
            return None
        if pressure and self._over >= self.up_ticks \
                and signals["replicas"] < self.max_replicas:
            return "up"
        if calm and self._under >= self.down_ticks:
            # dead/quarantined members are free to retire (they serve
            # nothing); a LIVE retire must leave min_replicas serving
            deadwood = signals["replicas"] - signals["ready"]
            if deadwood > 0 and signals["replicas"] > self.min_replicas:
                return "down"
            if signals["ready"] > self.min_replicas:
                return "down"
        return None


class FleetAutoscaler:
    """The control loop: poll ``fleet.scaling_signals(group)`` every
    ``tick`` seconds, act on the policy's verdict through
    ``fleet.add_replica`` / ``fleet.retire_replica``.

    ``make_apply`` (optional) builds the apply fn for each scale-up;
    without it the fleet clones the group's ``HotSwapApply`` template
    (shared jitted fn + current params — the zero-recompile path).
    ``event_log`` is a path or an ``elastic.EventLog``.

    Thread contract: the control loop is the only thread that launches
    actions; each action runs on its own helper thread so a wedged
    warmup/drain can be WATCHED instead of suffered (``watchdog_secs``).
    Counters and the action cell are ``self._lock``-guarded; ``stats``
    is the public, non-blocking snapshot.
    """

    def __init__(self, fleet, policy=None, group="default", *,
                 make_apply=None, tick=0.05, watchdog_secs=60.0,
                 retire_timeout=30.0, backoff_base=0.2, backoff_max=5.0,
                 event_log=None, name=None):
        self.fleet = fleet
        self.policy = policy if policy is not None else ScalingPolicy()
        self.group = str(group)
        if self.group not in fleet.groups:
            raise ValueError(f"FleetAutoscaler: fleet has no group "
                             f"{self.group!r} ({sorted(fleet.groups)})")
        self._make_apply = make_apply
        self._tick = float(tick)
        self._watchdog = float(watchdog_secs)
        self._retire_timeout = float(retire_timeout)
        self._backoff_base = float(backoff_base)
        self._backoff_max = float(backoff_max)
        self._name = name if name is not None \
            else f"{fleet._name}-autoscaler"
        self.log = event_log if isinstance(event_log, EventLog) \
            else EventLog(event_log)
        self._lock = threading.Lock()
        self._stats = {"scale_ups": 0, "scale_downs": 0, "failures": 0,
                       "wedged": 0}
        self._stop = threading.Event()
        self._thread = None
        # the one in-flight action: (thread, direction, deadline, result
        # cell) — control-loop-owned, lock-guarded for stats readers
        self._action = None
        self._attempts = 0        # consecutive failures → backoff
        self._resume_at = 0.0

    # ------------------------------------------------------------ lifecycle --
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name=self._name, daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout=None):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
        self.log.emit("stop", name=self._name)
        return self._thread is None or not self._thread.is_alive()

    @property
    def stats(self):
        with self._lock:
            out = dict(self._stats)
            out["action_in_flight"] = self._action is not None
        return out

    def telemetry(self, fmt="json"):
        """The unified metrics exposition (ISSUE 13): the control loop's
        action counters plus liveness gauges under the SAME
        ``telemetry.exposition`` key schema every runtime serves — one
        scraper reads fleet, replicas, generation servers, supervisor,
        and this autoscaler uniformly.  ``fmt="prom"`` renders the
        Prometheus-style text form."""
        with self._lock:
            counters = dict(self._stats)
            in_flight = self._action is not None
            attempts = self._attempts
        gauges = {"action_in_flight": int(in_flight),
                  "consecutive_failures": attempts,
                  "alive": int(self._thread is not None
                               and self._thread.is_alive()),
                  "tick_secs": self._tick,
                  "min_replicas": self.policy.min_replicas,
                  "max_replicas": self.policy.max_replicas,
                  "events": len(self.log.records)}
        # the uniform ISSUE 15 gauge families (the control loop compiles
        # nothing and stamps no report — zeros, but scrapers never branch)
        gauges.update(_telemetry.compile_gauges(self._name))
        gauges.update(_telemetry.memory_gauges(None))
        gauges.update(_telemetry.ckpt_gauges())
        payload = _telemetry.exposition("fleet_autoscaler", self._name,
                                        counters, gauges)
        return _telemetry.render(payload, fmt)

    # ------------------------------------------------------------- the loop --
    def _loop(self):
        wedge_logged = False
        while not self._stop.wait(self._tick):
            with self._lock:
                action = self._action
            if action is not None:
                thread, direction, deadline, cell = action
                if thread.is_alive():
                    if time.monotonic() >= deadline and not wedge_logged:
                        # hung scale action: log once, count it as a
                        # failure for the backoff schedule, and keep
                        # watching — the loop itself must never block
                        wedge_logged = True
                        with self._lock:
                            self._stats["wedged"] += 1
                        self.log.emit("scale-wedged", direction=direction,
                                      group=self.group,
                                      watchdog_secs=self._watchdog)
                        self._note_failure()
                    continue
                # harvest the finished action
                with self._lock:
                    self._action = None
                wedge_logged = False
                err = cell.get("error")
                if err is not None:
                    with self._lock:
                        self._stats["failures"] += 1
                    self.log.emit("scale-failed", direction=direction,
                                  group=self.group, error=repr(err))
                    self._note_failure()
                else:
                    key = "scale_ups" if direction == "up" \
                        else "scale_downs"
                    with self._lock:
                        self._stats[key] += 1
                        self._attempts = 0
                    self.log.emit(f"scale-{direction}", group=self.group,
                                  replica=cell.get("replica"),
                                  signals=cell.get("signals"))
                self.policy.record_action()
                continue
            if time.monotonic() < self._resume_at:
                continue
            if self.fleet._draining.is_set():
                return
            signals = self.fleet.scaling_signals(self.group)
            direction = self.policy.verdict(signals)
            if direction is None:
                continue
            cell = {"signals": signals}
            thread = threading.Thread(
                target=self._run_action, args=(direction, cell),
                name=f"{self._name}-{direction}", daemon=True)
            with self._lock:
                self._action = (thread, direction,
                                time.monotonic() + self._watchdog, cell)
            thread.start()

    def _note_failure(self):
        self._attempts += 1
        self._resume_at = time.monotonic() + _fault.backoff_delay(
            self._attempts, self._backoff_base, self._backoff_max)

    def _run_action(self, direction, cell):
        """One scale action (helper thread — the loop watches it)."""
        try:
            if direction == "up":
                apply_fn = None if self._make_apply is None \
                    else self._make_apply()
                rep = self.fleet.add_replica(apply_fn=apply_fn,
                                             group=self.group)
                cell["replica"] = rep.index
            else:
                rep = self._retire_candidate()
                self.fleet.retire_replica(rep,
                                          timeout=self._retire_timeout)
                cell["replica"] = rep.index
        except Exception as exc:    # noqa: BLE001 — harvested by the loop
            cell["error"] = exc

    def _retire_candidate(self):
        """Dead or quarantined members first (retiring them costs zero
        live capacity — it is the cleanup a killed replica needs), then
        the least-loaded live member."""
        with self.fleet._lock:
            view = [(rep.quarantined, rep.in_flight, rep.index, rep)
                    for rep in self.fleet.groups[self.group].replicas]
        deadwood = [rep for q, _n, _i, rep in view
                    if q or not rep.server.alive()]
        if deadwood:
            return deadwood[0]
        live = sorted(((n, i, rep) for q, n, i, rep in view
                       if not q and rep.server.alive()),
                      key=lambda t: t[:2])
        if not live:
            raise RuntimeError(f"{self._name}: no retirable replica in "
                               f"group {self.group!r}")
        return live[0][2]
