"""Admission-control primitives: errors, rate limiting, request futures,
and per-tenant QoS (priority classes + tenant token buckets).

ref: the reference stack has no serving layer at all (Module.predict is a
bare loop); the design here follows the TPU-serving literature's stance
(PAPERS.md — Ragged Paged Attention, the Gemma-on-TPU serving comparison)
that overload is a *normal* lifecycle event: a server that cannot keep up
must say so immediately (bounded queue, explicit ``RejectedError``) rather
than buffer without bound and melt every request into a timeout.

The QoS layer (ISSUE 12) extends the same stance to mixed-tenant traffic:
a single shared ``TokenBucket`` lets one noisy tenant starve everyone, so
``TenantQoS`` gives every tenant its OWN bucket (the abusive tenant sheds
with ``TenantThrottledError``; its neighbours never notice) and sorts
requests into **priority classes** (``QoSClass``) that carry a default
deadline, a routing-group pin, and an admission headroom fraction.  Each
class tracks deadline misses and a sliding-window latency distribution
(``ClassStats`` — p50/p99) that servers surface through ``healthz()`` so
routers and operators see SLO state per class.

Everything here is stdlib-only; the device-facing pieces live in
``serving.server``.
"""
from __future__ import annotations

import collections
import threading
import time

from .. import fault as _fault
from .. import telemetry as _telemetry

__all__ = ["RejectedError", "CircuitOpenError", "ServerClosedError",
           "DeadlineExceededError", "NonFiniteOutputError",
           "TenantThrottledError", "TokenBucket", "Request", "QoSClass",
           "ClassStats", "TenantQoS"]


class RejectedError(RuntimeError):
    """The server refused the request at admission (queue full, rate
    limit, oversize shape).  Shedding is an explicit, immediate verdict
    the client can retry against another replica — never an unbounded
    queue.  The request did NOT touch the device."""


class CircuitOpenError(RejectedError):
    """Fast-fail: the circuit breaker is open after consecutive step
    failures; new work is refused until a half-open probe succeeds."""


class ServerClosedError(RejectedError):
    """The server is draining or has shut down — not admitting."""


class DeadlineExceededError(RuntimeError):
    """The request's deadline passed — in queue (expired without
    touching the device) or mid-generation.  For generation requests
    the error carries the salvageable progress (ISSUE 19):
    ``tokens_generated`` and ``partial_tokens`` expose what was
    produced before expiry instead of silently discarding it."""

    def __init__(self, *args, tokens_generated=0, partial_tokens=None):
        super().__init__(*args)
        self.tokens_generated = int(tokens_generated)
        self.partial_tokens = [] if partial_tokens is None \
            else list(partial_tokens)


class NonFiniteOutputError(RuntimeError):
    """This request's rows of the batched output contained NaN/Inf — the
    request fails alone; batch neighbours and the server are unaffected
    (the serving counterpart of ``TrainStep(skip_nonfinite=True)``)."""


class TenantThrottledError(RejectedError):
    """THIS tenant's token bucket is empty — the request is shed for the
    tenant alone.  Other tenants' admission is untouched (per-tenant
    buckets are the isolation boundary; a shared limiter would let one
    abusive client starve everyone at zero served throughput)."""


class TokenBucket:
    """Token-bucket rate limiter: ``rate`` tokens/second refill up to a
    ``burst`` capacity; ``try_acquire`` never blocks (admission control
    sheds, it does not queue the client thread)."""

    def __init__(self, rate, burst=None):
        if rate <= 0:
            raise ValueError("TokenBucket: rate must be > 0")
        self._rate = float(rate)
        self._capacity = float(burst) if burst is not None \
            else max(1.0, self._rate)
        if self._capacity < 1.0:
            raise ValueError("TokenBucket: burst must allow >= 1 token")
        self._tokens = self._capacity
        self._stamp = time.monotonic()
        self._lock = threading.Lock()

    def try_acquire(self, n=1.0):
        """Take ``n`` tokens if available; False (no side effect) if not."""
        with self._lock:
            now = time.monotonic()
            self._tokens = min(self._capacity,
                               self._tokens + (now - self._stamp) * self._rate)
            self._stamp = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def refund(self, n=1.0):
        """Return tokens a request charged but never used (it was shed
        downstream of the limiter) — otherwise refused work burns the
        budget valid clients needed.  Capped at capacity."""
        with self._lock:
            self._tokens = min(self._capacity, self._tokens + n)


# --------------------------------------------------------------------- QoS --
class QoSClass:
    """One priority class of a ``TenantQoS`` policy.

    ``priority`` orders classes (higher = more important — schedulers
    serve it first, eviction spares it longest).  ``deadline`` is the
    class's default request deadline AND its SLO latency target: a
    request of this class that resolves later than ``deadline`` seconds
    after submission counts as a deadline miss even when it succeeded.
    ``admit_frac`` is an admission threshold on the server's TOTAL
    load: requests of this class are admitted only while overall
    utilization (all classes combined) is below the fraction, so the
    top ``1 - admit_frac`` of capacity is reserved exclusively for
    higher classes (a class with ``admit_frac=0.5`` sheds whenever the
    server is more than half full — under a sustained high-priority
    storm that saturates the threshold, the class yields entirely;
    this is strict priority admission, not a per-class occupancy
    quota).  ``group`` optionally pins the class to a named
    ``ServingFleet`` replica group.
    """

    __slots__ = ("name", "priority", "deadline", "admit_frac", "group")

    def __init__(self, name, priority=0, deadline=None, admit_frac=1.0,
                 group=None):
        self.name = str(name)
        self.priority = int(priority)
        self.deadline = None if deadline is None else float(deadline)
        self.admit_frac = float(admit_frac)
        if not 0.0 < self.admit_frac <= 1.0:
            raise ValueError(f"QoSClass {name!r}: admit_frac must be in "
                             f"(0, 1], got {admit_frac}")
        self.group = None if group is None else str(group)


class ClassStats:
    """SLO accounting for one priority class.

    Counters (monotonic): ``admitted`` / ``throttled`` / ``shed`` /
    ``completed`` / ``failed`` / ``expired`` / ``deadline_miss``.
    Latencies land in BOTH a sliding window of the last ``window``
    resolutions — which feeds the p50/p99 the ``snapshot()`` reports,
    so a router ranking replicas on healthz sees CURRENT behaviour (a
    recovered replica's p99 decays; a degraded one's isn't diluted by
    hours of healthy history) — and a cumulative ``telemetry.Histogram``
    (ISSUE 13: fixed log-spaced buckets,
    ``telemetry.LATENCY_BUCKETS_S``), the mergeable series the unified
    ``telemetry()`` expositions serve (scrapers window it themselves by
    differencing scrapes, Prometheus-style).  ``snapshot()`` is
    non-blocking in the healthz sense: one short lock over host
    counters and a bounded sort — no device work, no queue waits."""

    def __init__(self, window=256):
        self._lock = threading.Lock()
        self._window = collections.deque(maxlen=int(window))
        self._lat = _telemetry.Histogram("latency_s",
                                         _telemetry.LATENCY_BUCKETS_S)
        self._counts = {"admitted": 0, "throttled": 0, "shed": 0,
                        "completed": 0, "failed": 0, "expired": 0,
                        "deadline_miss": 0}

    def count(self, key, n=1):
        with self._lock:
            self._counts[key] += n

    def observe(self, latency, outcome, missed):
        """One resolved request: ``latency`` seconds, ``outcome`` in
        ``completed``/``failed``/``expired``, ``missed`` = SLO verdict."""
        latency = float(latency)
        with self._lock:
            self._counts[outcome] += 1
            if missed:
                self._counts["deadline_miss"] += 1
            self._window.append(latency)
        self._lat.observe(latency)

    def latency_snapshot(self):
        """The mergeable (cumulative) histogram snapshot (seconds) —
        served by the runtimes' ``telemetry()`` expositions as the
        ``class_<name>_latency_s`` histogram series."""
        return self._lat.snapshot()

    def snapshot(self):
        with self._lock:
            out = dict(self._counts)
            lat = sorted(self._window)
        n = len(lat)
        out["p50_ms"] = round(lat[n // 2] * 1e3, 3) if n else None
        out["p99_ms"] = round(lat[min(n - 1, (99 * n) // 100)] * 1e3,
                              3) if n else None
        return out


class TenantQoS:
    """Per-tenant token buckets + priority classes at admission.

    ``classes`` is an iterable of ``QoSClass`` (default: one class named
    ``"default"``).  ``tenant_rate``/``tenant_burst`` configure the
    per-tenant ``TokenBucket`` (``None`` rate = no tenant limiting);
    buckets are created lazily per tenant id and capped at
    ``max_tenants`` live buckets, evicting the least-recently-seen — a
    tenant-id cardinality attack must not grow host memory without
    bound.  ``classify()`` is the admission verdict (it fires the
    ``admission.classify`` fault point); ``track()`` arms SLO
    accounting on an accepted request; ``snapshot()`` is the per-class
    healthz payload.

    Thread contract: ``classify`` runs on client threads; the policy
    lock guards only the bucket/LRU dict — ``TokenBucket`` calls happen
    OUTSIDE it (the bucket has its own lock), and ``ClassStats`` guards
    itself.
    """

    def __init__(self, classes=None, default_class=None, tenant_rate=None,
                 tenant_burst=None, max_tenants=1024, window=256):
        if classes is None:
            classes = (QoSClass("default"),)
        self.classes = {}
        for qc in classes:
            if qc.name in self.classes:
                raise ValueError(f"TenantQoS: duplicate class {qc.name!r}")
            self.classes[qc.name] = qc
        if default_class is None:
            default_class = next(iter(self.classes))
        if default_class not in self.classes:
            raise ValueError(f"TenantQoS: default_class {default_class!r} "
                             f"is not one of {sorted(self.classes)}")
        self.default_class = default_class
        self._rate = None if tenant_rate is None else float(tenant_rate)
        self._burst = tenant_burst
        self._max_tenants = int(max_tenants)
        self._lock = threading.Lock()
        self._buckets = collections.OrderedDict()    # tenant -> TokenBucket
        self._stats = {name: ClassStats(window=window)
                       for name in self.classes}

    def klass(self, name=None):
        """Resolve a class name (``None`` = the default class); raises
        ``RejectedError`` for an unknown name — an unconfigured class is
        a client bug, not a new SLO tier."""
        if name is None:
            name = self.default_class
        qc = self.classes.get(name)
        if qc is None:
            raise RejectedError(
                f"unknown priority class {name!r} — configured classes: "
                f"{sorted(self.classes)}")
        return qc

    def _bucket(self, tenant):
        """This tenant's bucket (created on first sight, LRU-capped)."""
        with self._lock:
            b = self._buckets.get(tenant)
            if b is not None:
                self._buckets.move_to_end(tenant)
                return b
            b = TokenBucket(self._rate, self._burst)
            self._buckets[tenant] = b
            while len(self._buckets) > self._max_tenants:
                self._buckets.popitem(last=False)
            return b

    def classify(self, tenant=None, klass=None):
        """The admission verdict for one request: resolve its class,
        charge the tenant's bucket.  Returns the ``QoSClass``; raises
        ``RejectedError`` (unknown class) or ``TenantThrottledError``
        (this tenant is out of tokens — nobody else is affected)."""
        _fault.fire("admission.classify")
        qc = self.klass(klass)
        stats = self._stats[qc.name]
        if tenant is not None and self._rate is not None:
            bucket = self._bucket(tenant)
            if not bucket.try_acquire():
                stats.count("throttled")
                raise TenantThrottledError(
                    f"tenant {tenant!r} exceeded its rate — shedding this "
                    f"tenant only")
        stats.count("admitted")
        return qc

    def refund(self, tenant, qc):
        """A classified request was refused downstream (queue full,
        headroom): give the tenant its token back and move the admission
        to the ``shed`` column so the books stay honest."""
        if tenant is not None and self._rate is not None:
            self._bucket(tenant).refund()
        stats = self._stats[qc.name]
        stats.count("admitted", -1)
        stats.count("shed")

    def track(self, qc, req):
        """Arm SLO accounting on an accepted request: when it resolves,
        its latency/outcome/deadline-miss land in the class stats (from
        the resolving thread's done callback — no watcher thread)."""
        req.add_done_callback(lambda r: self._observe(qc, r))
        return req

    def _observe(self, qc, req):
        err = req.exception(timeout=0)            # resolved by now
        latency = time.monotonic() - req.submitted_at
        if err is None:
            outcome = "completed"
        elif isinstance(err, DeadlineExceededError):
            outcome = "expired"
        else:
            outcome = "failed"
        missed = outcome == "expired" \
            or (qc.deadline is not None and latency > qc.deadline)
        self._stats[qc.name].observe(latency, outcome, missed)

    def snapshot(self):
        """``{class: ClassStats.snapshot()}`` plus the class's static
        config — the ``healthz()["classes"]`` payload."""
        out = {}
        for name, qc in self.classes.items():
            s = self._stats[name].snapshot()
            s["priority"] = qc.priority
            s["deadline"] = qc.deadline
            out[name] = s
        return out

    def latency_snapshots(self):
        """``{class: ClassStats.latency_snapshot()}`` — the cumulative,
        mergeable per-class latency histograms the ``telemetry()``
        expositions serve (as ``class_<name>_latency_s`` series)."""
        return {name: st.latency_snapshot()
                for name, st in self._stats.items()}


class Request:
    """One accepted inference request: payload + deadline + a future.

    The client thread blocks in ``result()``; the batch thread resolves
    it with ``set_result``/``set_error``.  The handoff is the
    ``threading.Event`` — by the time ``wait()`` returns, the write is
    visible.  ``deadline`` is seconds from submission; an expired request
    is failed with ``DeadlineExceededError`` *in queue*, without touching
    the device.

    ``add_done_callback`` is the non-blocking observation channel a
    router needs: the fleet layer re-dispatches failed-over requests from
    the resolving thread's callback instead of parking a watcher thread
    per request in ``result()``.

    ``tenant``/``klass`` are the QoS labels admission stamped on the
    request (``None`` when the server runs without tenant attribution) —
    carried here so schedulers can order work and SLO accounting can
    attribute the resolution without a side table.

    ``trace``/``tspans`` are the request-tracing channel (ISSUE 13):
    ``telemetry.begin_request`` stamps an accepted request with its
    ``telemetry.Trace`` and the open phase spans; every downstream
    instrumentation site guards on the single ``trace is not None``
    attribute check, so an untraced request allocates nothing and pays
    one attribute read per site.
    """

    __slots__ = ("data", "submitted_at", "deadline", "tenant", "klass",
                 "trace", "tspans",
                 "_event", "_result", "_error", "_callbacks", "_cb_lock")

    def __init__(self, data, deadline=None, tenant=None, klass=None):
        self.data = data
        self.tenant = tenant
        self.klass = klass
        self.trace = None              # telemetry.Trace once begun
        self.tspans = None             # {phase: Span}, traced only
        self.submitted_at = time.monotonic()
        self.deadline = None if deadline is None \
            else self.submitted_at + float(deadline)
        self._event = threading.Event()
        self._result = None
        self._error = None
        self._callbacks = []
        self._cb_lock = threading.Lock()

    def expired(self, now=None):
        return self.deadline is not None and \
            (time.monotonic() if now is None else now) >= self.deadline

    # ---- resolution (batch-thread side) ----
    def set_result(self, value):
        self._result = value
        self._finish()

    def set_error(self, exc):
        self._error = exc
        self._finish()

    def _finish(self):
        # the lock closes the add-after-resolve race: a callback is
        # either in the list this drain snapshots, or added after the
        # event is visibly set (and invoked by the adder) — exactly once
        # either way.  Callbacks run OUTSIDE the lock (they are arbitrary
        # router code).
        with self._cb_lock:
            self._event.set()
            cbs, self._callbacks = self._callbacks, []
        for cb in cbs:
            try:
                cb(self)
            except Exception:    # noqa: BLE001 — a raising callback must
                pass             # not strand the REST of a resolving batch

    def add_done_callback(self, fn):
        """Call ``fn(request)`` once the request is resolved — on the
        resolving thread, or immediately on this one when it already is.
        Callbacks must not block (the batch thread is the caller);
        exceptions they raise are swallowed — resolution must never fail
        halfway through a batch."""
        with self._cb_lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    # ---- future protocol (client side) ----
    def done(self):
        return self._event.is_set()

    def exception(self, timeout=None):
        """The error this request resolved with (None on success); raises
        builtin ``TimeoutError`` if unresolved within ``timeout``."""
        if not self._event.wait(timeout):
            raise TimeoutError("Request: not resolved within "
                               f"{timeout}s")
        return self._error

    def result(self, timeout=None):
        err = self.exception(timeout)
        if err is not None:
            raise err
        return self._result
