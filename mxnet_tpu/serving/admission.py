"""Admission-control primitives: errors, rate limiting, request futures.

ref: the reference stack has no serving layer at all (Module.predict is a
bare loop); the design here follows the TPU-serving literature's stance
(PAPERS.md — Ragged Paged Attention, the Gemma-on-TPU serving comparison)
that overload is a *normal* lifecycle event: a server that cannot keep up
must say so immediately (bounded queue, explicit ``RejectedError``) rather
than buffer without bound and melt every request into a timeout.

Everything here is stdlib-only; the device-facing pieces live in
``serving.server``.
"""
from __future__ import annotations

import threading
import time

__all__ = ["RejectedError", "CircuitOpenError", "ServerClosedError",
           "DeadlineExceededError", "NonFiniteOutputError", "TokenBucket",
           "Request"]


class RejectedError(RuntimeError):
    """The server refused the request at admission (queue full, rate
    limit, oversize shape).  Shedding is an explicit, immediate verdict
    the client can retry against another replica — never an unbounded
    queue.  The request did NOT touch the device."""


class CircuitOpenError(RejectedError):
    """Fast-fail: the circuit breaker is open after consecutive step
    failures; new work is refused until a half-open probe succeeds."""


class ServerClosedError(RejectedError):
    """The server is draining or has shut down — not admitting."""


class DeadlineExceededError(RuntimeError):
    """The request's deadline passed while it waited in queue; it was
    expired without touching the device."""


class NonFiniteOutputError(RuntimeError):
    """This request's rows of the batched output contained NaN/Inf — the
    request fails alone; batch neighbours and the server are unaffected
    (the serving counterpart of ``TrainStep(skip_nonfinite=True)``)."""


class TokenBucket:
    """Token-bucket rate limiter: ``rate`` tokens/second refill up to a
    ``burst`` capacity; ``try_acquire`` never blocks (admission control
    sheds, it does not queue the client thread)."""

    def __init__(self, rate, burst=None):
        if rate <= 0:
            raise ValueError("TokenBucket: rate must be > 0")
        self._rate = float(rate)
        self._capacity = float(burst) if burst is not None \
            else max(1.0, self._rate)
        if self._capacity < 1.0:
            raise ValueError("TokenBucket: burst must allow >= 1 token")
        self._tokens = self._capacity
        self._stamp = time.monotonic()
        self._lock = threading.Lock()

    def try_acquire(self, n=1.0):
        """Take ``n`` tokens if available; False (no side effect) if not."""
        with self._lock:
            now = time.monotonic()
            self._tokens = min(self._capacity,
                               self._tokens + (now - self._stamp) * self._rate)
            self._stamp = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def refund(self, n=1.0):
        """Return tokens a request charged but never used (it was shed
        downstream of the limiter) — otherwise refused work burns the
        budget valid clients needed.  Capped at capacity."""
        with self._lock:
            self._tokens = min(self._capacity, self._tokens + n)


class Request:
    """One accepted inference request: payload + deadline + a future.

    The client thread blocks in ``result()``; the batch thread resolves
    it with ``set_result``/``set_error``.  The handoff is the
    ``threading.Event`` — by the time ``wait()`` returns, the write is
    visible.  ``deadline`` is seconds from submission; an expired request
    is failed with ``DeadlineExceededError`` *in queue*, without touching
    the device.

    ``add_done_callback`` is the non-blocking observation channel a
    router needs: the fleet layer re-dispatches failed-over requests from
    the resolving thread's callback instead of parking a watcher thread
    per request in ``result()``.
    """

    __slots__ = ("data", "submitted_at", "deadline", "_event", "_result",
                 "_error", "_callbacks", "_cb_lock")

    def __init__(self, data, deadline=None):
        self.data = data
        self.submitted_at = time.monotonic()
        self.deadline = None if deadline is None \
            else self.submitted_at + float(deadline)
        self._event = threading.Event()
        self._result = None
        self._error = None
        self._callbacks = []
        self._cb_lock = threading.Lock()

    def expired(self, now=None):
        return self.deadline is not None and \
            (time.monotonic() if now is None else now) >= self.deadline

    # ---- resolution (batch-thread side) ----
    def set_result(self, value):
        self._result = value
        self._finish()

    def set_error(self, exc):
        self._error = exc
        self._finish()

    def _finish(self):
        # the lock closes the add-after-resolve race: a callback is
        # either in the list this drain snapshots, or added after the
        # event is visibly set (and invoked by the adder) — exactly once
        # either way.  Callbacks run OUTSIDE the lock (they are arbitrary
        # router code).
        with self._cb_lock:
            self._event.set()
            cbs, self._callbacks = self._callbacks, []
        for cb in cbs:
            try:
                cb(self)
            except Exception:    # noqa: BLE001 — a raising callback must
                pass             # not strand the REST of a resolving batch

    def add_done_callback(self, fn):
        """Call ``fn(request)`` once the request is resolved — on the
        resolving thread, or immediately on this one when it already is.
        Callbacks must not block (the batch thread is the caller);
        exceptions they raise are swallowed — resolution must never fail
        halfway through a batch."""
        with self._cb_lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    # ---- future protocol (client side) ----
    def done(self):
        return self._event.is_set()

    def exception(self, timeout=None):
        """The error this request resolved with (None on success); raises
        builtin ``TimeoutError`` if unresolved within ``timeout``."""
        if not self._event.wait(timeout):
            raise TimeoutError("Request: not resolved within "
                               f"{timeout}s")
        return self._error

    def result(self, timeout=None):
        err = self.exception(timeout)
        if err is not None:
            raise err
        return self._result
