"""Circuit breaker: fast-fail degraded mode with exponential half-open
probing.

A TPU serving replica whose step function is failing (driver wedge,
preempted donor core, poisoned executable cache) must stop queueing work
against a dead device: after ``threshold`` CONSECUTIVE step failures the
breaker opens, every dispatch (and new admission) fast-fails with
``CircuitOpenError``, and recovery is probed — one trial batch at a time,
on a schedule given by ``fault.backoff_delay``, the same
exponential+jitter policy ``fault.retry_call`` sleeps through, recast as
a state machine so the serving thread never blocks on a backoff.

States: CLOSED → (threshold consecutive failures) → OPEN → (probe timer
expires; next ``allow()`` caller is the trial) → HALF_OPEN → CLOSED on
success, back to OPEN with a doubled delay on failure.
"""
from __future__ import annotations

import threading
import time

from .. import fault as _fault
from .. import telemetry as _telemetry

__all__ = ["CircuitBreaker", "CLOSED", "OPEN", "HALF_OPEN"]

CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
_STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    """Thread-safe; shared between client threads (``engaged`` at
    admission) and the batch thread (``allow``/``record_*`` at dispatch).
    ``threshold=0`` disables the breaker entirely (always CLOSED)."""

    def __init__(self, threshold=3, base_delay=0.05, max_delay=2.0,
                 jitter=0.5):
        self.threshold = int(threshold)
        self._base = float(base_delay)
        self._max = float(max_delay)
        self._jitter = float(jitter)
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0       # consecutive
        self._opens = 0          # consecutive OPEN episodes → backoff attempt
        self._retry_at = 0.0
        self.trips = 0           # lifetime count of CLOSED/HALF_OPEN → OPEN

    @property
    def state(self):
        with self._lock:
            return self._state

    def state_code(self):
        """0 closed / 1 half-open / 2 open — the numeric form the
        ``::breaker_state`` profiler counter carries."""
        return _STATE_CODE[self.state]

    def engaged(self):
        """True while NEW work should fast-fail at admission: the breaker
        is open and the probe timer has not expired yet.  Once it has,
        admission lets traffic through so there is something to probe
        with."""
        with self._lock:
            return self._state == OPEN and time.monotonic() < self._retry_at

    def allow(self):
        """Dispatch-side gate.  CLOSED → go.  OPEN with the probe timer
        expired → this caller IS the half-open trial.  Otherwise
        fast-fail without touching the device."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN and time.monotonic() >= self._retry_at:
                self._state = HALF_OPEN
                return True
            return False

    def record_success(self):
        with self._lock:
            self._state = CLOSED
            self._failures = 0
            self._opens = 0

    def record_failure(self):
        """One step failure.  Trips on the ``threshold``-th consecutive
        failure, or instantly from HALF_OPEN (the probe failed); each
        re-open doubles the next probe delay via ``fault.backoff_delay``.
        A fresh trip into OPEN — from CLOSED, the start of a dark
        episode — fires the flight-recorder dump (ISSUE 15): the
        seconds of spans/faults/compiles that preceded the replica
        going dark are exactly what the post-mortem needs.  Re-trips
        (failed half-open probes of a still-dark replica) do NOT dump
        again: a sustained outage probes every few seconds for hours,
        and one bundle per episode is the record, not one per probe."""
        dump = False
        with self._lock:
            self._failures += 1
            if self.threshold <= 0:
                return
            if self._state == HALF_OPEN or self._failures >= self.threshold:
                dump = self._state == CLOSED
                self._opens += 1
                self.trips += 1
                self._state = OPEN
                self._retry_at = time.monotonic() + _fault.backoff_delay(
                    self._opens, self._base, self._max, self._jitter)
        if dump:         # outside the lock: dump() does file I/O
            _telemetry.flight_trip("breaker-open", trips=self.trips)
