"""mx.serving — fault-hardened inference serving runtime (ISSUE 4 + 7).

The inference-side sibling of ``mx.fault``'s training runtime: admission
control with load shedding, deadline-aware shape-bucketed dynamic
batching (bounded jit cache — recompiles are the TPU availability
killer), a circuit breaker with exponential half-open probing, health
predicates, and SIGTERM graceful drain.  One tier up,
``serving.ServingFleet`` replicates the server N ways behind a
health-aware router with replica failover and zero-downtime rolling
weight updates (``serving.WeightUpdater``).  See ``docs/api.md``
"Serving" and "Serving fleet".

    from mxnet_tpu import serving

    srv = serving.InferenceServer(apply_fn, buckets=(1, 4, 8),
                                  sample=example).start()
    out = srv(example, deadline=0.1)          # submit + blocking result
    srv.drain()                               # or serve_forever() + SIGTERM

    fleet = serving.ServingFleet.replicated(fn, params, 3,
                                            sample=example).start()
    serving.WeightUpdater(fleet, ckpt_manager).start()   # live weights
    fleet.serve_forever()
"""
from .admission import (RejectedError, CircuitOpenError, ServerClosedError,
                        DeadlineExceededError, NonFiniteOutputError,
                        TenantThrottledError, TokenBucket, Request,
                        QoSClass, ClassStats, TenantQoS)
from .batcher import BucketSpec, DynamicBatcher
from .breaker import CircuitBreaker
from .server import InferenceServer, module_apply
from .fleet import (ServingFleet, ReplicaGroup, HotSwapApply,
                    WeightUpdater, SnapshotRejectedError,
                    SnapshotPrunedError, UpdateRolledBackError,
                    validate_params)
from .generate import (GenerationServer, PageAllocator,
                       PoolExhaustedError, SequenceSnapshot,
                       prefix_admission_plan)
from .autoscale import FleetAutoscaler, ScalingPolicy

__all__ = ["InferenceServer", "module_apply", "BucketSpec",
           "DynamicBatcher", "CircuitBreaker", "TokenBucket", "Request",
           "RejectedError", "CircuitOpenError", "ServerClosedError",
           "DeadlineExceededError", "NonFiniteOutputError",
           "TenantThrottledError", "QoSClass", "ClassStats", "TenantQoS",
           "ServingFleet", "ReplicaGroup", "HotSwapApply", "WeightUpdater",
           "SnapshotRejectedError", "SnapshotPrunedError",
           "UpdateRolledBackError",
           "validate_params", "GenerationServer", "PageAllocator",
           "PoolExhaustedError", "SequenceSnapshot",
           "prefix_admission_plan",
           "FleetAutoscaler", "ScalingPolicy"]
