"""Dynamic batcher: shape-bucketed request coalescing with a latency cap.

Why buckets: on TPU every unseen input signature costs a full XLA
recompile — tens of seconds of availability loss on a big model, the
single worst serving failure mode (PAPERS.md: the Ragged-Paged-Attention
kernel exists precisely to stop per-shape recompiles).  So the batcher
never dispatches a raw shape: every group of requests is padded onto a
fixed grid of (batch, length) buckets, making the jit cache's size a
*configuration constant* — at most ``len(batch) * len(length)``
executables, all compilable up front during warmup.

The flush policy is the classic dynamic-batching tradeoff: a batch goes
to the device when it fills the largest bucket OR when the oldest queued
request has waited ``max_delay`` — occupancy when loaded, latency when
idle.
"""
from __future__ import annotations

import queue
import threading
import time

import numpy as np

from .. import fault as _fault
from .. import telemetry as _telemetry
from .admission import (DeadlineExceededError, RejectedError,
                        ServerClosedError)

__all__ = ["BucketSpec", "DynamicBatcher"]


def _as_leaves(data):
    """Normalize a request payload to a tuple of per-example arrays."""
    if isinstance(data, (tuple, list)):
        return tuple(np.asarray(d) for d in data)
    return (np.asarray(data),)


class BucketSpec:
    """The fixed shape grid requests are padded onto.

    ``batch``: allowed batch sizes; a group of k requests pads up to the
    smallest bucket >= k (the batcher never gathers past the largest).
    ``length``: optional sequence-length buckets applied to axis 0 of the
    FIRST payload leaf (the token axis of a language-model request);
    shorter examples pad with ``pad_value``, an example longer than the
    largest bucket is rejected at admission — it could never be served
    without an unbounded-signature recompile.  Padded batch ROWS replicate
    the last real example, so apply fns that normalise over the batch
    still see finite values.
    """

    def __init__(self, batch=(1, 2, 4, 8), length=None, pad_value=0.0):
        batch = sorted({int(b) for b in batch})
        if not batch or batch[0] < 1:
            raise ValueError(f"BucketSpec: batch buckets must be >= 1 "
                             f"integers, got {batch}")
        self.batch = tuple(batch)
        self.length = None if length is None \
            else tuple(sorted({int(l) for l in length}))
        if self.length is not None and (not self.length
                                        or self.length[0] < 1):
            raise ValueError(f"BucketSpec: length buckets must be >= 1 "
                             f"integers, got {self.length}")
        self.pad_value = pad_value

    @property
    def max_batch(self):
        return self.batch[-1]

    def batch_bucket(self, k):
        """Smallest batch bucket that fits ``k`` examples."""
        for b in self.batch:
            if b >= k:
                return b
        return self.max_batch

    def pad_example(self, data):
        """Length-pad one request payload onto the grid; returns a tuple
        of np leaves.  Raises ``RejectedError`` for a payload no bucket
        can hold — admission-time, so an unservable request is refused
        before it occupies queue space."""
        leaves = _as_leaves(data)
        if self.length is None:
            return leaves
        head = leaves[0]
        if head.ndim < 1:
            raise RejectedError(
                "BucketSpec: length bucketing needs a >=1-D first leaf, "
                f"got a scalar")
        n = head.shape[0]
        for L in self.length:
            if L >= n:
                if L > n:
                    pad = np.full((L - n,) + head.shape[1:], self.pad_value,
                                  dtype=head.dtype)
                    head = np.concatenate([head, pad], axis=0)
                return (head,) + leaves[1:]
        raise RejectedError(
            f"request length {n} exceeds the largest length bucket "
            f"{self.length[-1]} — no executable exists for this shape")

    @staticmethod
    def signature(leaves):
        """Grouping key: padded per-example (shape, dtype) per leaf."""
        return tuple((l.shape, str(l.dtype)) for l in leaves)

    def pad_group(self, group, target):
        """Stack the group's (pre-length-padded) examples into batch
        leaves of size ``target``, replicating the last example into the
        padding rows."""
        out = []
        for i in range(len(group[0].data)):
            rows = [r.data[i] for r in group]
            while len(rows) < target:
                rows.append(rows[-1])
            out.append(np.stack(rows, axis=0))
        return tuple(out)


class DynamicBatcher:
    """Producer/consumer coalescer: a bounded request queue drained by one
    batch thread that groups same-signature requests, pads them onto the
    ``BucketSpec`` grid, and hands them to ``runner(group, padded)``.

    Admission is the producer side: ``offer`` is non-blocking and raises
    ``RejectedError`` when the queue is full (load shedding — depth is
    the declared bound, never growth).  Expired requests are resolved via
    ``on_expire`` at dequeue, without touching the device.  ``idle`` (if
    given) runs on the batch thread whenever the queue goes quiet — the
    server hooks breaker probes there.

    Thread contract (mxlint ``thread-unlocked-attr`` gated): everything
    shared between ``offer``/public readers and the batch thread travels
    through the bounded ``Queue`` and ``Event``s; ``_holdover`` (the
    one-deep foreign-signature stash) is touched by the batch thread
    only.
    """

    _IDLE_TICK = 0.05      # max latency for noticing stop / running idle

    def __init__(self, runner, buckets, max_delay=0.005, capacity=64,
                 on_expire=None, on_fail=None, idle=None,
                 name="DynamicBatcher"):
        self.buckets = buckets if isinstance(buckets, BucketSpec) \
            else BucketSpec(buckets)
        self._runner = runner
        self._on_fail = on_fail    # observes requests THIS layer errors
        self._max_delay = float(max_delay)
        if capacity < 1:
            raise ValueError("DynamicBatcher: capacity must be >= 1")
        self._q = queue.Queue(maxsize=int(capacity))
        self._on_expire = on_expire
        self._idle = idle
        # makes offer's stop-check + put ATOMIC against drain's stop-set:
        # a request is either refused, or enqueued strictly before _stop is
        # observable — and the loop only exits on (stopped AND empty), so
        # every enqueued request is flushed.  Without this, a put racing
        # drain could land after the final residue sweep and hang its
        # client forever (the one way to drop an accepted request).
        self._admit_lock = threading.Lock()
        self._stop = threading.Event()
        self._started = threading.Event()
        self._holdover = []        # batch-thread-local only
        self._thread = threading.Thread(target=self._loop, name=name,
                                        daemon=True)

    # ------------------------------------------------------ producer side --
    def start(self):
        if not self._started.is_set():
            self._started.set()
            self._thread.start()

    def offer(self, req):
        """Admit one request.  Non-blocking: a full queue sheds with
        ``RejectedError`` (the caller's cue to retry elsewhere), a
        stopped batcher refuses with ``ServerClosedError``."""
        with self._admit_lock:
            if self._stop.is_set():
                raise ServerClosedError("batcher is draining — not "
                                        "admitting")
            try:
                self._q.put_nowait(req)
            except queue.Full:
                raise RejectedError(
                    f"request queue full ({self._q.maxsize}) — shedding") \
                    from None
        return req

    def depth(self):
        return self._q.qsize()

    def alive(self):
        return self._thread.is_alive()

    def drain(self, timeout=None):
        """Stop admitting, let the batch thread flush every queued
        request to a terminal state, and join it.  True when the thread
        exited within ``timeout``."""
        with self._admit_lock:       # serialize with in-flight offer()s
            self._stop.set()
        if self._started.is_set():
            self._thread.join(timeout)
        if not self._thread.is_alive():
            # never started, or already dead: there is no loop left to
            # flush the queue — resolve any stragglers right here
            # (idempotent; safe single-threaded since the loop is gone)
            self._fail_residue()
        return not self._thread.is_alive()

    # ------------------------------------------------------ consumer side --
    def _loop(self):
        try:
            while True:
                group = self._gather()
                if group is None:
                    if self._stop.is_set() and self._q.empty() \
                            and not self._holdover:
                        return
                    if self._idle is not None and not self._stop.is_set():
                        try:
                            self._idle()
                        except Exception:
                            pass     # a probe failure is breaker state,
                            #          never a dead serving loop
                    continue
                self._dispatch(group)
        finally:
            # a crashed loop must close admission BEFORE sweeping, under
            # the same lock offer() holds — otherwise a put can land just
            # after the sweep and hang its client (same race drain()
            # closes, on the crash path)
            with self._admit_lock:
                self._stop.set()
            self._fail_residue()

    def _take(self, timeout):
        """One live request from the holdover or the queue; None on
        timeout.  Expired requests resolve via ``on_expire`` here —
        in-queue, before any padding or device work."""
        while True:
            if self._holdover:
                req = self._holdover.pop(0)
            else:
                try:
                    req = self._q.get(timeout=timeout)
                except queue.Empty:
                    return None
            if req.expired():
                if self._on_expire is not None:
                    self._on_expire(req)
                elif not req.done():
                    # no server hook: resolve here — the request left the
                    # queue, so nothing downstream would ever see it again
                    req.set_error(DeadlineExceededError(
                        "deadline exceeded in queue — the request never "
                        "touched the device"))
                continue
            if req.trace is not None:       # queue wait ends at the pop
                _telemetry.end_span(req, "queue")
                _telemetry.open_span(req, "coalesce")
            return req

    def _gather(self):
        """Collect one same-signature group: up to the largest batch
        bucket, or whatever arrived within ``max_delay`` of the first
        request.  A foreign-signature arrival is stashed (one deep) and
        flushes the current group."""
        spec = self.buckets
        first = self._take(self._IDLE_TICK)
        if first is None:
            return None
        group, sig = [first], spec.signature(first.data)
        t0 = time.monotonic()
        while len(group) < spec.max_batch:
            rem = self._max_delay - (time.monotonic() - t0)
            if rem <= 0:
                break
            if self._stop.is_set() and self._q.empty() \
                    and not self._holdover:
                break            # draining: flush now, don't wait the timer
            req = self._take(min(rem, self._IDLE_TICK))
            if req is None:
                continue
            if spec.signature(req.data) != sig:
                self._holdover.append(req)
                break
            group.append(req)
        return group

    def _dispatch(self, group):
        """Pad + run one group.  Any batching-layer failure (including an
        armed ``serving.batch`` fault) resolves every request explicitly —
        an accepted request is never left hanging."""
        tspans = None
        for r in group:                   # close the coalesce window —
            if r.trace is not None:       # padding + device work follow
                _telemetry.end_span(r, "coalesce")
                if tspans is None:
                    tspans = []
                tspans.append(r.tspans["_c"])
        if tspans is not None:            # fault firings → span events
            _telemetry.push_current(tspans)
        try:
            _fault.fire("serving.batch")
            padded = self.buckets.pad_group(
                group, self.buckets.batch_bucket(len(group)))
            self._runner(group, padded)
        except Exception as exc:      # noqa: BLE001 — resolves, then state
            for r in group:
                self._resolve_error(r, exc)
        except BaseException:
            # the batch THREAD is dying (SystemExit & co. — a killed
            # replica).  The loop's finally sweeps the queue, but this
            # group already left it: resolve it here or its clients hang
            # forever — the one way to drop an accepted request.  A
            # ServerClosedError is retry-safe, which is exactly right:
            # the batch never completed, so a fleet router may re-dispatch
            # it to a live replica.
            err = ServerClosedError(
                "batch thread died mid-batch — this request was not "
                "served")
            for r in group:
                self._resolve_error(r, err)
            raise
        finally:
            if tspans is not None:
                _telemetry.pop_current()
        for r in group:
            # a runner that forgot a request is a bug, but the client
            # must still get an answer — and an honest one: the batch DID
            # run, so this must not be a RejectedError subclass (whose
            # contract is "never touched the device, retry elsewhere")
            self._resolve_error(r, RuntimeError(
                "batch completed without resolving this request — the "
                "runner dropped it (server bug); the batch did execute"))

    def _resolve_error(self, req, exc):
        """Error-resolve a request at the batching layer, keeping the
        owner's accounting honest via ``on_fail`` (without it, requests
        this layer resolves would vanish from the server's
        completed+failed+expired totals)."""
        if req.done():
            return
        req.set_error(exc)
        if self._on_fail is not None:
            self._on_fail(req, exc)

    def _fail_residue(self):
        """On loop exit (normal drain leaves nothing; a crashed loop may):
        every still-queued request gets an explicit terminal error."""
        residue = list(self._holdover)
        self._holdover = []
        while True:
            try:
                residue.append(self._q.get_nowait())
            except queue.Empty:
                break
        for req in residue:
            self._resolve_error(req, ServerClosedError(
                "server stopped before this request was served"))
