"""InferenceServer: the fault-hardened serving front of the stack.

Turns a compiled apply fn (a jitted function, an ``EvalStep``, a Gluon
net, or a bound ``Module`` via ``module_apply``) into a request server
with the full robustness lifecycle (ISSUE 4):

- **admission control** — bounded queue + optional token-bucket rate
  limit; overload sheds with ``RejectedError`` instead of growing a
  queue.
- **dynamic batching** — requests coalesce into fixed shape buckets
  (``serving.BucketSpec``) so the jit cache stays a configuration
  constant; recompiles, the TPU availability killer, cannot be triggered
  by traffic.
- **deadlines + circuit breaker** — queued requests expire without
  touching the device; consecutive step failures trip into fast-fail with
  exponential half-open probing (``serving.CircuitBreaker``).
- **health + drain** — ``alive()``/``ready()``/``healthz()`` predicates
  (readiness flips only after warmup compiles), profiler counters, and a
  SIGTERM drain (``serve_forever`` on ``fault.GracefulExit``): stop
  admitting, flush every accepted request to a terminal state, exit.  An
  accepted request is NEVER silently dropped.

Every failure path is deterministically testable through the
``serving.admit`` / ``serving.batch`` / ``serving.step`` /
``serving.drain`` fault points (``fault.inject``).
"""
from __future__ import annotations

import threading
import time

import numpy as np

from .. import fault as _fault
from .. import profiler as _profiler
from .. import telemetry as _telemetry
from .admission import (CircuitOpenError, DeadlineExceededError,
                        NonFiniteOutputError, RejectedError, Request,
                        ServerClosedError, TenantQoS, TokenBucket)
from .batcher import BucketSpec, DynamicBatcher
from .breaker import OPEN, CircuitBreaker

__all__ = ["InferenceServer", "module_apply"]


def _to_np(out):
    """Normalize one apply-fn output to a numpy batch array."""
    if hasattr(out, "asnumpy"):         # NDArray
        return out.asnumpy()
    return np.asarray(out)


class InferenceServer:
    """Robust request server over a batched apply fn.

    ``apply_fn(*batch_leaves) -> batch_out | tuple`` runs on the batch
    thread only, always on shapes from the bucket grid.  Per-request
    payloads are single examples (one row of the batch; tuples for
    multi-input models).

    Lifecycle: construct → ``start()`` (warmup-compiles every batch
    bucket when ``sample`` is given, THEN flips readiness — a recompile
    stall never lands on a live request) → ``submit()``/``__call__`` →
    ``drain()`` (or ``serve_forever()`` + SIGTERM).

    Thread contract (mxlint-gated): client threads and the batch thread
    share state only through the batcher's bounded queue, ``Event``s,
    profiler ``Counter``s, the breaker's own lock, and the
    ``self._lock``-guarded stats dict.

    Profiler series (readable with the profiler off via
    ``profiler.counter_value`` / ``profiler.counters``):
    ``<name>::queue_depth``, ``<name>::shed``, ``<name>::expired``,
    ``<name>::batch_occupancy`` (percent, last dispatched batch),
    ``<name>::breaker_state`` (0 closed / 1 half-open / 2 open).
    """

    def __init__(self, apply_fn, buckets=(1, 2, 4, 8), *, max_queue=128,
                 max_delay=0.005, rate=None, burst=None, breaker=None,
                 sample=None, default_deadline=None, guard_nonfinite=True,
                 pin_signature=True, qos=None, memory_report=None,
                 name="InferenceServer"):
        self._apply = apply_fn
        # compile-event stream (ISSUE 15): when the apply fn exposes its
        # jit cache (a raw jax.jit, fleet.HotSwapApply, or the int8
        # module_apply closure), compile events come from REAL cache
        # growth — a fleet replica warming against a shared jit fn
        # records hits, not phantom compiles.  Otherwise the dispatched-
        # signature set stands in (one executable per padded signature
        # is the module_apply/executor contract).
        probe = getattr(apply_fn, "jit_cache_size", None)
        if probe is None:
            probe = getattr(apply_fn, "_cache_size", None)
        self._cache_probe = probe if callable(probe) else None
        # the object whose jit cache the probe reads (the SHARED fn for
        # HotSwapApply wrappers) — the dedupe key for concurrent growth
        self._cache_owner = getattr(apply_fn, "jit_cache_owner",
                                    apply_fn)
        # live memory gauges (ISSUE 15): per-device argument/peak bytes
        # from an already-parsed costguard report, stamped at warmup
        self._mem_gauges = _telemetry.memory_gauges(memory_report)
        # per-tenant/per-class QoS (ISSUE 12).  Always present: without an
        # explicit policy every request lands in one "default" class with
        # no tenant limiting, so healthz()["classes"] carries the SLO
        # snapshot for ANY server — the uniform key fleet routers rank on.
        self._qos = qos if qos is not None else TenantQoS()
        self.buckets = buckets if isinstance(buckets, BucketSpec) \
            else BucketSpec(buckets)
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        self._limiter = None if rate is None else TokenBucket(rate, burst)
        self._default_deadline = default_deadline
        self._guard = bool(guard_nonfinite)
        # pin_signature (default on): the served example signature is
        # fixed — by ``sample``, else by the first accepted request — and
        # any later payload with a different leaf count/dtype/shape
        # (beyond the length grid) is REJECTED at admission.  Without
        # this, one stray float64 list or transposed array from a client
        # is a fresh XLA compile stalling the device under live
        # deadlines — the exact failure the bucket grid exists to kill.
        self._pin = bool(pin_signature)
        self._name = name
        self._sample = None if sample is None \
            else self.buckets.pad_example(sample)
        # only (shape, dtype) per leaf is ever compared — storing the
        # actual first-request arrays would pin them (and alias the
        # client's buffers) for the server's lifetime
        self._template = None if self._sample is None \
            else self._sig_of(self._sample)
        self._lock = threading.Lock()
        self._stats = {"admitted": 0, "completed": 0, "failed": 0,
                       "shed": 0, "expired": 0, "rejected": 0,
                       "batches": 0, "probes": 0}
        self._last_error = None       # (type name, monotonic stamp)
        self._shapes = set()          # distinct dispatched signatures
        self._ready = threading.Event()
        self._draining = threading.Event()
        self._c_depth = _profiler.Counter(None, f"{name}::queue_depth")
        self._c_shed = _profiler.Counter(None, f"{name}::shed")
        self._c_expired = _profiler.Counter(None, f"{name}::expired")
        self._c_occupancy = _profiler.Counter(None,
                                              f"{name}::batch_occupancy")
        self._c_breaker = _profiler.Counter(None, f"{name}::breaker_state")
        self._batcher = DynamicBatcher(
            self._run_batch, self.buckets, max_delay=max_delay,
            capacity=max_queue, on_expire=self._expire,
            on_fail=lambda req, exc: self._bump("failed"),
            idle=self._idle_probe, name=f"{name}-batcher")

    # ------------------------------------------------------------ lifecycle --
    def start(self, warmup=None):
        """Start the batch thread.  ``warmup`` (default: on when a
        ``sample`` payload was given) first pushes the sample through
        every batch bucket so EVERY executable the grid allows exists
        before readiness flips — compiles happen here, not under a live
        deadline."""
        if self._draining.is_set():
            raise ServerClosedError(f"{self._name}: already drained")
        if warmup is None:
            warmup = self._sample is not None
        if warmup:
            if self._sample is None:
                raise ValueError("start(warmup=True) needs sample= at "
                                 "construction")
            for leaves in self._sample_grid():
                for b in self.buckets.batch:
                    sig = (b,) + BucketSpec.signature(leaves)
                    self._tracked_apply(self._padded(leaves, b), sig)
                    with self._lock:
                        self._shapes.add(sig)
            # every executable the grid allows now exists: later misses
            # are UNEXPECTED recompiles (the chaos-asserted counter)
            if _telemetry.ACTIVE:
                _telemetry.pin_compile_census(self._name)
        self._batcher.start()
        self._ready.set()
        return self

    def _tracked_apply(self, padded, sig):
        """Run the apply fn through the compile-event chokepoint
        (ISSUE 15).  With a jit-cache probe the verdict is real cache
        growth; without one, a signature this server never dispatched
        counts as the compile it implies."""
        if not _telemetry.ACTIVE:
            return self._apply(*padded)
        key = f"b{sig[0]}"
        if self._cache_probe is not None:
            with _telemetry.track_compile(self._name,
                                          probe=self._cache_probe,
                                          key=key,
                                          hw_key=self._cache_owner):
                return self._apply(*padded)
        with self._lock:
            new = sig not in self._shapes
        with _telemetry.track_compile(self._name, key=key,
                                      assume_miss=new):
            return self._apply(*padded)

    def stamp_memory_report(self, report):
        """Stamp a costguard-style memory report (``argument_bytes`` /
        ``peak_bytes`` / ``per_device``) onto this server's ``mem_*``
        exposition gauges — what warmup tooling calls after compiling
        the grid (the bytes are a property of the executables, so one
        stamp is live until the program set changes)."""
        self._mem_gauges = _telemetry.memory_gauges(report)
        return self._mem_gauges

    def __enter__(self):
        if not self._batcher.alive():
            self.start()
        return self

    def __exit__(self, *exc):
        self.drain()
        return False

    def _sample_grid(self):
        """The sample resized onto every length bucket (the whole grid a
        request could dispatch as — warmup must compile all of it, not
        just the sample's own bucket)."""
        if self.buckets.length is None:
            return [self._sample]
        head, rest = self._sample[0], self._sample[1:]
        out = []
        for L in self.buckets.length:
            h = head[:L]
            if h.shape[0] < L:
                h = np.concatenate(
                    [h, np.full((L - h.shape[0],) + h.shape[1:],
                                self.buckets.pad_value, h.dtype)], axis=0)
            out.append((h,) + rest)
        return out

    @staticmethod
    def _padded(leaves, b):
        return tuple(np.stack([leaf] * b, axis=0) for leaf in leaves)

    @staticmethod
    def _sig_of(leaves):
        return tuple((tuple(l.shape), l.dtype) for l in leaves)

    def _check_signature(self, payload):
        """Admission-time signature pinning (see ``pin_signature``)."""
        if not self._pin:
            return
        sig = self._sig_of(payload)
        with self._lock:
            tpl = self._template
            if tpl is None:
                self._template = sig       # first request defines the API
                return
        if len(sig) != len(tpl):
            raise RejectedError(
                f"payload has {len(sig)} leaves, this server serves "
                f"{len(tpl)} — a new signature would recompile")
        for i, ((p_shape, p_dt), (t_shape, t_dt)) in enumerate(zip(sig,
                                                                   tpl)):
            if p_dt != t_dt:
                raise RejectedError(
                    f"payload leaf {i} dtype {p_dt} != served "
                    f"{t_dt} — a new signature would recompile (lists "
                    f"arrive float64; cast explicitly)")
            ragged = i == 0 and self.buckets.length is not None
            if (p_shape[1:] if ragged else p_shape) != \
                    (t_shape[1:] if ragged else t_shape):
                raise RejectedError(
                    f"payload leaf {i} shape {p_shape} does not match the "
                    f"served signature {t_shape}"
                    f"{' beyond the length axis' if ragged else ''} — a "
                    f"new signature would recompile")

    # ------------------------------------------------------------ admission --
    def submit(self, data, deadline=None, tenant=None, klass=None,
               trace_parent=None):
        """Admit one request; returns its ``Request`` future.

        Refusals are immediate and explicit: ``ServerClosedError`` while
        draining, ``CircuitOpenError`` while the breaker fast-fails,
        ``RejectedError`` on rate-limit, full queue, or an un-bucketable
        shape, ``TenantThrottledError`` when THIS tenant's QoS bucket is
        dry.  None of them touched the device or consumed queue space.

        ``tenant``/``klass`` are the QoS labels (see ``TenantQoS``): the
        class supplies the default deadline when ``deadline`` is None and
        the resolved request's latency lands in that class's healthz
        stats.

        ``trace_parent`` (a ``telemetry.Span``) continues an existing
        request trace under that span — the fleet router passes its
        dispatch span here so replica-side phases nest under the hop."""
        t0_us = _telemetry.now_us() if _telemetry.ACTIVE else None
        _fault.fire("serving.admit")
        if self._draining.is_set():
            self._bump("rejected")
            raise ServerClosedError(f"{self._name}: draining — "
                                    f"not admitting")
        if not self._ready.is_set():
            self._bump("rejected")
            raise RejectedError(f"{self._name}: not started")
        if not self._batcher.alive():
            self._bump("rejected")
            raise ServerClosedError(f"{self._name}: batch thread is not "
                                    f"running — not admitting")
        if self.breaker.engaged():
            self._bump("rejected")
            raise CircuitOpenError(
                f"{self._name}: circuit open after repeated step failures "
                f"— fast-failing until a probe succeeds")
        # validate BEFORE charging the rate limiter: both checks are pure
        # host work, and an unservable payload must not burn a token a
        # valid client needed (a misbehaving client would otherwise
        # starve everyone at zero served throughput)
        try:
            payload = self.buckets.pad_example(data)
            self._check_signature(payload)
        except RejectedError:
            self._bump("rejected")
            raise
        # the QoS verdict comes AFTER the structural checks (an
        # unservable payload must not burn a tenant token) and BEFORE the
        # global limiter (the per-tenant bucket is the finer sieve)
        try:
            qc = self._qos.classify(tenant=tenant, klass=klass)
        except RejectedError:
            self._bump("shed")
            self._c_shed.increment()
            raise
        if deadline is None:
            deadline = qc.deadline if qc.deadline is not None \
                else self._default_deadline
        if self._limiter is not None and not self._limiter.try_acquire():
            self._qos.refund(tenant, qc)
            self._shed("rate limit exceeded — shedding")
        req = Request(payload, deadline=deadline, tenant=tenant,
                      klass=qc.name)
        # trace BEFORE the offer — the batch thread may pop the request
        # immediately and needs the queue span already open.  A refusal
        # below leaves the request unresolved, so the trace is never
        # exported (only accepted requests yield trees).
        if trace_parent is not None or t0_us is not None:
            _telemetry.begin_request(req, self._name, t0_us=t0_us,
                                     parent=trace_parent)
        try:
            self._batcher.offer(req)
        except ServerClosedError as exc:
            if self._limiter is not None:    # the refusal served no one —
                self._limiter.refund()       # give the token back
            self._qos.refund(tenant, qc)
            _telemetry.abort_request(req, exc)
            self._bump("rejected")
            raise
        except RejectedError as exc:
            if self._limiter is not None:
                self._limiter.refund()
            self._qos.refund(tenant, qc)
            _telemetry.abort_request(req, exc)
            self._shed(str(exc))
        self._qos.track(qc, req)
        self._bump("admitted")
        self._c_depth.set_value(self._batcher.depth())
        return req

    def __call__(self, data, deadline=None, timeout=None, **kw):
        """Blocking convenience: submit + ``result()`` (``tenant`` /
        ``klass`` pass through)."""
        return self.submit(data, deadline=deadline, **kw).result(timeout)

    def _shed(self, msg):
        self._bump("shed")
        self._c_shed.increment()
        raise RejectedError(f"{self._name}: {msg}")

    def _bump(self, key, n=1):
        with self._lock:
            self._stats[key] += n

    def _note_step_failure(self, exc):
        """Remember the most recent step-level failure for ``healthz`` —
        type name + monotonic stamp, never the exception object (holding
        it would pin its traceback, and with it every frame's locals,
        for the life of the server)."""
        with self._lock:
            self._last_error = (type(exc).__name__, time.monotonic())

    # ---------------------------------------------------------- batch thread --
    def _expire(self, req):
        """Deadline passed in queue: resolve WITHOUT device work."""
        self._bump("expired")
        self._c_expired.increment()
        waited = time.monotonic() - req.submitted_at
        req.set_error(DeadlineExceededError(
            f"deadline exceeded after {waited * 1e3:.1f} ms in queue — "
            f"the request never touched the device"))

    def _run_batch(self, group, padded):
        """Execute one padded group on the batch thread: breaker gate →
        ``serving.step`` fault point → apply → per-request splitting with
        the all-finite row guard (a NaN output fails ONE request, not the
        server)."""
        if not self.breaker.allow():
            err = CircuitOpenError(
                f"{self._name}: circuit open — fast-failing queued work")
            for r in group:
                r.set_error(err)
            self._bump("failed", len(group))
            return
        target = padded[0].shape[0]
        step_spans = None
        for r in group:                # device-step span per traced member
            if r.trace is not None:
                if step_spans is None:
                    step_spans = []
                sp = _telemetry.open_span(r, "step", batch=len(group))
                if sp is not None:
                    step_spans.append(sp)
        if step_spans is not None:     # fault firings → span events
            _telemetry.push_current(step_spans)
        sig = (target,) + BucketSpec.signature(group[0].data)
        try:
            _fault.fire("serving.step")
            with _profiler.scope(f"{self._name}.step", cat="serving"):
                out = self._tracked_apply(padded, sig)
        except Exception as exc:      # noqa: BLE001 — resolved per request
            self.breaker.record_failure()
            self._note_step_failure(exc)
            self._c_breaker.set_value(self.breaker.state_code())
            err = _fault.with_context(
                exc, f"{self._name} batch of {len(group)}")
            for r in group:
                r.set_error(err)
            self._bump("failed", len(group))
            return
        finally:
            if step_spans is not None:
                _telemetry.pop_current()
        outs = tuple(_to_np(o) for o in
                     (out if isinstance(out, (tuple, list)) else (out,)))
        if step_spans is not None:     # host realization is the sync point
            for sp in step_spans:
                sp.end()
        bad_dim = [o for o in outs if o.shape[:1] != (target,)]
        if bad_dim:
            # malformed output IS a step failure (a wedged/poisoned
            # executable that cannot serve anyone) — the breaker must see
            # it, or a replica erroring 100% of requests stays "ready"
            # and load balancers keep feeding it
            self.breaker.record_failure()
            self._c_breaker.set_value(self.breaker.state_code())
            err = ValueError(
                f"{self._name}: apply fn returned leading dim "
                f"{bad_dim[0].shape[:1]} for a batch of {target} — serving "
                f"apply fns must be batch-major")
            self._note_step_failure(err)
            for r in group:
                r.set_error(err)
            self._bump("failed", len(group))
            return
        if self._guard:
            from ..parallel.step import all_finite_rows
            mask = all_finite_rows([o[:len(group)] for o in outs])
            # SOME rows bad = poisoned inputs (data fault: neighbours are
            # served, breaker untouched).  EVERY row of a MULTI-request
            # batch bad = nothing served — step-failure territory (a
            # poisoned executable kills whole batches under load).  A
            # single-request batch is excluded: at idle traffic one
            # client's NaN input is indistinguishable from a server fault,
            # and counting it would let one buggy client trip the breaker
            # for the whole replica.
            batch_dead = len(group) > 1 and not mask.any()
        else:
            batch_dead = False
        if batch_dead:
            self.breaker.record_failure()
            self._note_step_failure(NonFiniteOutputError(
                "entirely non-finite multi-request batch"))
        else:
            self.breaker.record_success()
        self._c_breaker.set_value(self.breaker.state_code())
        with self._lock:
            self._stats["batches"] += 1
            self._shapes.add(sig)
        self._c_occupancy.set_value(int(100 * len(group) / target))
        for i, r in enumerate(group):
            if self._guard and not mask[i]:
                r.set_error(NonFiniteOutputError(
                    f"{self._name}: non-finite values in this request's "
                    f"output row — input likely corrupt; batch neighbours "
                    f"were served normally"))
                self._bump("failed")
                continue
            row = tuple(o[i] for o in outs)
            r.set_result(row[0] if len(row) == 1 else row)
            self._bump("completed")
        self._c_depth.set_value(self._batcher.depth())

    def _idle_probe(self):
        """Half-open probing without traffic: while the breaker is open
        and admission fast-fails everything, there may be no request left
        to probe with — so when the backoff expires, push the warmup
        sample through the ``serving.step`` path instead.  Runs on the
        batch thread's idle ticks; never raises."""
        if self._sample is None or self.breaker.state != OPEN:
            return
        if not self.breaker.allow():
            return                       # backoff not elapsed yet
        self._bump("probes")
        try:
            _fault.fire("serving.step")
            self._apply(*self._padded(self._sample, self.buckets.batch[0]))
        except Exception as exc:         # noqa: BLE001 — probe verdicts
            self.breaker.record_failure()
            self._note_step_failure(exc)
        else:
            self.breaker.record_success()
        self._c_breaker.set_value(self.breaker.state_code())

    # --------------------------------------------------------------- health --
    def alive(self):
        """Liveness: the batch thread is running."""
        return self._batcher.alive()

    def ready(self):
        """Readiness: started, warmed up, not draining, breaker not
        fast-failing.  False means "send traffic elsewhere", not "dead"."""
        return (self._ready.is_set() and self.alive()
                and not self._draining.is_set()
                and not self.breaker.engaged())

    def healthz(self):
        """The ``/healthz``-style snapshot a probe endpoint would serve.

        Carries everything a fleet router needs to RANK replicas without
        reaching into private state: ``breaker_state`` (0 closed /
        1 half-open / 2 open — same coding as the profiler counter),
        ``in_flight`` (accepted requests not yet resolved — queued plus
        mid-batch), ``last_error`` (``{"type", "age"}`` of the most
        recent step-level failure, monotonic seconds; ``None`` when the
        replica has never failed a step), and ``classes`` (the per-class
        SLO snapshot — deadline misses, p50/p99 latency — from
        ``TenantQoS.snapshot``; a bare server reports everything under
        ``"default"``).  The snapshot is non-blocking: one short stats
        copy under the server lock, every other field read from its own
        primitive — no device work, no queue waits."""
        with self._lock:
            s = self._stats
            in_flight = (s["admitted"] - s["completed"] - s["failed"]
                         - s["expired"])
            last = self._last_error
        return {"alive": self.alive(), "ready": self.ready(),
                "draining": self._draining.is_set(),
                "breaker": self.breaker.state,
                "breaker_state": self.breaker.state_code(),
                "queue_depth": self._batcher.depth(),
                "in_flight": max(0, in_flight),
                "classes": self._qos.snapshot(),
                "last_error": None if last is None else
                {"type": last[0], "age": time.monotonic() - last[1]}}

    @property
    def stats(self):
        with self._lock:
            out = dict(self._stats)
            out["distinct_shapes"] = len(self._shapes)
        out["queue_depth"] = self._batcher.depth()
        out["breaker"] = self.breaker.state
        return out

    @property
    def distinct_shapes(self):
        """Signatures ever dispatched (warmup included) — the executable
        count the bucket grid bounds; the load-test acceptance reads
        this next to the jit cache size."""
        with self._lock:
            return set(self._shapes)

    def telemetry(self, fmt="json"):
        """The unified metrics exposition (ISSUE 13): one
        ``telemetry.exposition`` payload — counters (the lifecycle
        totals), gauges (queue depth, in-flight, breaker state),
        per-phase latency histograms (``admit``/``queue``/``coalesce``/
        ``step`` span durations, ms), and the per-class SLO rows —
        under the SAME key schema every runtime serves.  ``fmt="prom"``
        renders the Prometheus-style text form.  Non-blocking, same as
        ``healthz``."""
        h = self.healthz()
        with self._lock:
            counters = dict(self._stats)
        gauges = {"queue_depth": h["queue_depth"],
                  "in_flight": h["in_flight"],
                  "breaker_state": h["breaker_state"],
                  "ready": int(h["ready"]), "alive": int(h["alive"]),
                  "draining": int(h["draining"])}
        # the runtime-introspection families (ISSUE 15): jit-cache
        # behavior + stamped memory bytes, same keys on every runtime
        gauges.update(_telemetry.compile_gauges(self._name))
        gauges.update(self._mem_gauges)
        gauges.update(_telemetry.ckpt_gauges())
        snap = _telemetry.registry().snapshot(prefix=f"{self._name}::")
        # every registry gauge under this server's prefix (the profiler
        # counter series: shed/expired/batch_occupancy/...) rides the
        # exposition too — healthz-derived values win on key collision
        for k, v in snap["gauges"].items():
            gauges.setdefault(k, v)
        hist = snap["histograms"]
        for cname, csnap in self._qos.latency_snapshots().items():
            hist[f"class_{cname}_latency_s"] = csnap
        payload = _telemetry.exposition("inference_server", self._name,
                                        counters, gauges, hist,
                                        h["classes"])
        return _telemetry.render(payload, fmt)

    # ---------------------------------------------------------------- drain --
    def drain(self, timeout=None):
        """Graceful shutdown: stop admitting (submits raise
        ``ServerClosedError``), flush every queued and in-flight request
        to a terminal state — result, or an explicit error — then stop
        and join the batch thread.  After ``drain()`` every ``Request``
        ever returned by ``submit`` is ``done()``; an accepted request is
        never silently dropped.  True when the thread exited in time."""
        _fault.fire("serving.drain")
        self._draining.set()
        self._ready.clear()
        ok = self._batcher.drain(timeout)
        self._c_depth.set_value(self._batcher.depth())
        return ok

    close = drain

    def serve_forever(self, poll=0.05):
        """Block until SIGTERM/SIGINT (via ``fault.GracefulExit``), then
        drain — the Cloud-TPU preemption contract on the serving side:
        stop admitting, flush accepted work, exit clean."""
        with _fault.GracefulExit() as g:
            while not g.requested and self.alive():
                time.sleep(poll)
        return self.drain()


def module_apply(module, quantize=None):
    """Adapt a bound ``mx.mod.Module`` into a serving apply fn.

    Feeds batch leaves through ``Module.forward(is_train=False)``; label
    arguments the symbol declares are fed zeros of the batch's size
    (inference heads ignore them — they only shape the executor's traced
    signature).  Each distinct padded signature traces once in the
    executor's jit cache, so the compile count stays bounded by the
    batcher's bucket grid.  The returned fn runs on the batch thread
    only — it is not itself thread-safe.

    ``quantize="int8"`` serves the module's weights post-training
    quantized (``amp.quantize_weight``: symmetric per-channel int8 for
    every float param with ndim >= 2; bias/norm leaves stay full
    precision).  The dequant is folded INSIDE the compiled apply — the
    executable's weight arguments are int8 payloads + f32 scales, so
    the compiled weight buffer is ~4x smaller than the f32 module's
    (the ``serving_mlp_grid_int8`` budget golden's committed headline).
    The jit-cache contract is unchanged: one executable per padded
    signature, still bounded by the bucket grid."""
    if quantize not in (None, "int8"):
        raise ValueError(f"module_apply: quantize={quantize!r} "
                         f"(expected None or 'int8')")
    if not module.binded:
        raise ValueError("module_apply: bind() the module first")
    if quantize == "int8":
        return _module_apply_int8(module)
    from ..io import DataBatch
    from ..ndarray import array as _nd_array

    label_shapes = {n: tuple(module._exec.arg_dict[n].shape[1:])
                    for n in module._label_names
                    if n in module._exec.arg_dict}

    def apply(*leaves):
        b = leaves[0].shape[0]
        label = [_nd_array(np.zeros((b,) + s, np.float32))
                 for s in label_shapes.values()] or None
        module.forward(DataBatch(data=[_nd_array(l) for l in leaves],
                                 label=label), is_train=False)
        outs = [o.asnumpy() for o in module.get_outputs()]
        return outs[0] if len(outs) == 1 else tuple(outs)

    return apply


def _module_apply_int8(module):
    """The ``quantize="int8"`` arm of ``module_apply``: snapshot the
    bound params once, quantize the >=2-D float weights per-channel
    (axis 0 — MXNet ``(units, in_units)`` kernel layout), and trace the
    module's symbol through one jitted fn whose arguments are the int8
    payloads + scales.  Aux states (BatchNorm moving stats) ride along
    full-precision; label args become in-graph zeros."""
    import jax
    import jax.numpy as jnp

    from .. import random as _random
    from ..amp.quantize import dequantize_weight, quantize_weight
    from ..executor import _fwd_fn

    exc = module._exec
    data_names = list(module._data_names)
    label_shapes = {n: tuple(exc.arg_dict[n].shape[1:])
                    for n in module._label_names if n in exc.arg_dict}
    payloads, scales, passthrough = {}, {}, {}
    for n, v in exc.arg_dict.items():
        if n in data_names or n in label_shapes:
            continue
        arr = jnp.asarray(v._data)
        if jnp.issubdtype(arr.dtype, jnp.floating) and arr.ndim >= 2:
            payloads[n], scales[n] = quantize_weight(arr, axis=0)
        else:
            passthrough[n] = arr
    aux_vals = {n: jnp.asarray(v._data) for n, v in exc.aux_dict.items()}
    fwd = _fwd_fn(exc._symbol, training=False)

    @jax.jit
    def qapply(qp, qs, other, aux, key, *leaves):
        b = leaves[0].shape[0]
        args = dict(other)
        for n in qp:
            args[n] = dequantize_weight(qp[n], qs[n], axis=0)
        for n, leaf in zip(data_names, leaves):
            args[n] = leaf
        for n, s in label_shapes.items():
            args[n] = jnp.zeros((b,) + s, jnp.float32)
        outs, _aux_updates = fwd(args, aux, key)
        return tuple(outs)

    def apply(*leaves):
        outs = qapply(payloads, scales, passthrough, aux_vals,
                      _random.next_key(),
                      *[jnp.asarray(np.asarray(l)) for l in leaves])
        outs = [np.asarray(o) for o in outs]
        return outs[0] if len(outs) == 1 else tuple(outs)

    # compile-event stream (ISSUE 15): expose the jit cache so a server
    # over this apply reports real executable growth, not signatures
    # (and the owning jit fn, the concurrent-growth dedupe key)
    apply.jit_cache_size = qapply._cache_size
    apply.jit_cache_owner = qapply
    return apply
