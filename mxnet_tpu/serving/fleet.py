"""ServingFleet: N ``InferenceServer`` replicas behind one front door.

PR 4 made a single server fault-hardened; this module makes the SERVICE
fault-hardened (ISSUE 7).  One process is one blast radius — a wedged
batch loop, a tripped breaker, or a weight reload takes the whole
endpoint down — so production TPU serving is a fleet of replicas behind
a health-aware router (the deployment shape of the Gemma-on-TPU serving
comparison and of TensorFlow Serving's worker fleets, PAPERS.md):

- **health-aware routing** — ``submit()`` ranks replicas by their
  ``healthz()`` snapshot (ready, breaker state, queue depth) plus the
  fleet's own in-flight accounting, picks the least-loaded one, and
  enforces a per-replica in-flight cap.  Replicas whose breaker is OPEN
  or whose batch thread died are *quarantined*: no traffic until a probe
  succeeds, re-probed on the ``fault.backoff_delay`` schedule.
- **failover** — a request a replica ACCEPTED but then failed
  (batch-thread death, breaker trip, post-acceptance shed) is
  re-dispatched to a healthy replica within its original deadline.
  Inference is idempotent, so re-dispatch is safe; admission-level
  refusals (``RejectedError`` out of ``submit``) are never retried —
  shedding is the client's verdict.  Killing a replica under traffic
  drops zero accepted requests.
- **rolling weight updates** — ``WeightUpdater`` watches a
  ``CheckpointManager`` directory (``parallel.checkpoint.wait_for_new``)
  and streams each new snapshot through the fleet one replica at a
  time: quarantine → drain in-flight → hot-swap the param buffers
  (same shapes/dtypes ⇒ the SAME executables — a weight update is a
  pointer swap, never a recompile) → warmup probe → readmit, with
  automatic rollback to the previous weights when the post-swap probe
  fails.  A poisoned snapshot never serves a single client request.
- **fleet lifecycle** — ``drain()`` resolves every accepted request
  fleet-wide then drains replicas concurrently; ``serve_forever()``
  latches SIGTERM via ``fault.GracefulExit``.

Every fleet failure mode is deterministically injectable through the
``fleet.route`` / ``fleet.dispatch`` / ``fleet.swap`` / ``fleet.probe``
fault points.  See ``docs/api.md`` "Serving fleet".
"""
from __future__ import annotations

import logging
import os
import queue
import threading
import time

import numpy as np

from .. import fault as _fault
from .. import profiler as _profiler
from .. import telemetry as _telemetry
from .admission import (DeadlineExceededError, NonFiniteOutputError,
                        RejectedError, Request, ServerClosedError)
from .batcher import BucketSpec
from .breaker import OPEN
from .server import InferenceServer

__all__ = ["ServingFleet", "ReplicaGroup", "HotSwapApply", "WeightUpdater",
           "SnapshotRejectedError", "SnapshotPrunedError",
           "UpdateRolledBackError", "validate_params"]

_logger = logging.getLogger(__name__)


class SnapshotRejectedError(RuntimeError):
    """A weight snapshot failed validation (leaf count / shape / dtype
    drift against the served params, or non-finite values) and was NOT
    applied to any replica.  The caller skips the snapshot — the fleet
    keeps serving the previous weights at full capacity."""


class SnapshotPrunedError(RuntimeError):
    """The snapshot path vanished between discovery and read — retention
    pruned it (``CheckpointManager._retain``) while the updater held the
    name.  STALE, not bad: retention never prunes the newest committed
    snapshot, so a newer one exists — re-poll and apply that instead of
    counting this one as skipped."""


class UpdateRolledBackError(RuntimeError):
    """A rolling update aborted: a post-swap probe failed, the affected
    replica was rolled back to its previous weights, and any replicas
    already updated were rolled back too — the fleet is back on the old
    weights at full capacity.  The snapshot is poisoned in a way
    validation could not see (finite params, non-finite outputs)."""


class HotSwapApply:
    """A serving apply fn with a hot-swappable parameter slot.

    Wraps ``fn(params, *batch_leaves)`` — typically ONE ``jax.jit``
    shared by every replica of a fleet — plus this replica's own
    ``params`` pytree.  ``swap()`` replaces the whole pytree in a single
    attribute store (atomic under the GIL; the update protocol drains
    the replica first anyway) and returns the previous pytree for
    rollback.  As long as the new leaves match the old leaf-for-leaf in
    shape and dtype, the jitted fn keeps hitting the SAME executables:
    a weight update is a pointer swap, never a recompile.

    ``quantizer`` (optional) is the ingest transform for a
    reduced-precision fleet — typically ``amp.Int8Quantizer().quantize``
    with ``fn`` built via ``Int8Quantizer.wrap``.  It maps a
    full-precision training snapshot into this fleet's served
    representation; ``WeightUpdater`` runs incoming snapshots through
    it BEFORE ``validate_params``, so an f32 training job streams
    rolling updates into an int8 fleet instead of tripping the
    dtype-drift rejection.
    """

    def __init__(self, fn, params, quantizer=None):
        self._fn = fn
        self.params = params
        self.quantizer = quantizer
        # compile-event stream (ISSUE 15): expose the shared jit fn's
        # cache so every replica's InferenceServer reports REAL
        # executable growth — replica 1's warmup against executables
        # replica 0 already compiled records hits, not phantom compiles.
        # jit_cache_owner names the SHARED fn (not this per-replica
        # wrapper) so concurrent growth observations across replicas
        # dedupe through one high-water mark.
        cache_size = getattr(fn, "_cache_size", None)
        if callable(cache_size):
            self.jit_cache_size = cache_size
            self.jit_cache_owner = fn

    def __call__(self, *leaves):
        return self._fn(self.params, *leaves)

    def swap(self, new_params):
        """Install ``new_params``; returns the previous pytree."""
        old, self.params = self.params, new_params
        return old


def _param_items(params):
    """``(key, leaf)`` pairs of a params container — dict keys (sorted),
    or positional indices for sequences.  The comparison space of
    ``validate_params``."""
    if isinstance(params, dict):
        return [(k, params[k]) for k in sorted(params)]
    return list(enumerate(params))


def validate_params(new, current):
    """Gate a snapshot BEFORE any replica touches it: same container
    kind and keys as the served params, same shape and dtype
    leaf-for-leaf (anything else would change the executable signature —
    the recompile the whole serving stack exists to prevent), and every
    new value finite (NaN/Inf weights poison every output they touch).
    Raises ``SnapshotRejectedError``; on success returns ``new``
    unchanged — the container shape the apply fn indexes by survives."""
    if isinstance(new, dict) != isinstance(current, dict):
        raise SnapshotRejectedError(
            f"snapshot params are a {type(new).__name__}, the fleet "
            f"serves a {type(current).__name__} — the apply fn's "
            f"indexing would break")
    new_items, cur_items = _param_items(new), _param_items(current)
    if len(new_items) != len(cur_items):
        raise SnapshotRejectedError(
            f"snapshot has {len(new_items)} param leaves, the fleet "
            f"serves {len(cur_items)} — structure drift would recompile")
    for (nk, n), (ck, c) in zip(new_items, cur_items):
        if nk != ck:
            raise SnapshotRejectedError(
                f"snapshot param key {nk!r} != served key {ck!r} — the "
                f"apply fn would read the wrong leaf")
        n_shape, c_shape = tuple(np.shape(n)), tuple(np.shape(c))
        if n_shape != c_shape:
            raise SnapshotRejectedError(
                f"snapshot leaf {nk!r} shape {n_shape} != served "
                f"{c_shape} — a shape change would recompile every "
                f"bucket executable")
        n_dt = np.asarray(n).dtype if not hasattr(n, "dtype") else n.dtype
        c_dt = np.asarray(c).dtype if not hasattr(c, "dtype") else c.dtype
        if n_dt != c_dt:
            raise SnapshotRejectedError(
                f"snapshot leaf {nk!r} dtype {n_dt} != served {c_dt} — a "
                f"dtype change would recompile every bucket executable")
        if not np.all(np.isfinite(np.asarray(n))):
            raise SnapshotRejectedError(
                f"snapshot leaf {nk!r} contains non-finite values — a "
                f"poisoned snapshot must never reach a replica")
    return new


class _Replica:
    """One fleet member.  Every mutable field is guarded by the FLEET's
    lock — the replica's own server has its own synchronisation."""

    __slots__ = ("index", "server", "apply", "group", "in_flight",
                 "quarantined", "manual", "probe_attempts", "next_probe_at",
                 "probing")

    def __init__(self, index, server, apply_fn, group="default"):
        self.index = index
        self.server = server
        self.apply = apply_fn
        self.group = group          # ReplicaGroup name, fixed for life
        self.in_flight = 0          # fleet-dispatched, not yet resolved
        self.quarantined = False
        self.manual = False         # True: an updater owns readmission
        self.probe_attempts = 0
        self.next_probe_at = 0.0
        self.probing = False


class ReplicaGroup:
    """A named partition of a fleet's replicas with its own routing set.

    Groups are how the fleet disaggregates WORKLOADS, not just load:
    requests routed to group "prefill" can never queue behind (or stall)
    group "decode" — the structural interference fix the LLM serving
    exemplars (PAPERS.md, Ragged Paged Attention / Gemma-on-TPU) call
    prefill/decode disaggregation.  A ``QoSClass(group=...)`` pins a
    priority class to a group; ``submit(group=...)`` pins one request.
    Each group carries its own census expectation (the bucket-grid
    executable count a member must have warmed before it may serve) and
    its own capacity arithmetic for the autoscaler.

    Constructed through ``ServingFleet`` (pass ``applies`` as a dict of
    ``{group_name: [apply_fns]}``); this object is the fleet's
    per-group view, exposed via ``ServingFleet.groups``."""

    __slots__ = ("name", "replicas")

    def __init__(self, name):
        self.name = str(name)
        self.replicas = []          # mutated only under the FLEET lock


class ServingFleet:
    """N ``InferenceServer`` replicas behind one ``submit()`` front door.

    ``applies`` is one serving apply fn per replica — for weight-updated
    fleets, ``HotSwapApply`` instances sharing one jitted
    ``fn(params, *leaves)`` (see ``ServingFleet.replicated``) — or a
    dict ``{group_name: [apply_fns]}`` to partition the fleet into named
    ``ReplicaGroup``s with disjoint routing sets.  The fleet builds its
    own replicas (``<name>-r<i>``) so each gets its own breaker, queue,
    and counters; pass ``breaker=`` a FACTORY (callable) when you want
    non-default breaker tuning — a shared instance would couple the
    replicas' failure domains, which is the opposite of a fleet.

    **Dynamic membership (ISSUE 12).**  ``add_replica()`` grows a group
    (spawn → warmup until the bucket-grid census is complete → only then
    join the routing set) and ``retire_replica()`` shrinks it
    (manual-quarantine → drain outstanding fleet work → remove, with the
    retired member's counter series cleared) — both under live traffic
    with zero dropped accepted requests.  ``FleetAutoscaler``
    (``serving.autoscale``) drives them from queue-depth/occupancy/
    deadline-miss signals.

    **Per-tenant QoS.**  Pass ``qos=TenantQoS(...)`` to put priority
    classes and per-tenant token buckets at the front door: an abusive
    tenant sheds with ``TenantThrottledError`` while its neighbours are
    untouched, a class's ``admit_frac`` reserves headroom for the
    classes above it, ``QoSClass(group=...)`` pins a class to a replica
    group, and ``healthz()["classes"]`` reports per-class deadline-miss
    and p50/p99 latency.

    Failure matrix (what a client sees):

    - routed + served        → result
    - no ready replica / all at the in-flight cap → ``RejectedError`` at
      ``submit`` (admission-level; never retried — retry another cell)
    - replica died / breaker tripped after acceptance → transparent
      re-dispatch; an error surfaces only when every healthy replica has
      been tried or the deadline passed
    - deadline passed (queue, failover wait) → ``DeadlineExceededError``
    - non-finite output row → ``NonFiniteOutputError`` (data fault —
      deterministic, so never re-dispatched)

    Thread contract (mxlint-gated): fleet state lives behind
    ``self._lock`` (plain field reads/writes only — health reads,
    ``fault.fire`` and replica calls happen OUTSIDE it); the router
    thread and client threads share work through ``queue.Queue`` /
    ``Event``s; per-replica state is fleet-lock-guarded fields on
    ``_Replica``.
    """

    _TICK = 0.02             # router housekeeping cadence

    def __init__(self, applies, *, buckets=(1, 2, 4, 8), sample=None,
                 name="Fleet", default_deadline=None, max_inflight=None,
                 max_redispatch=None, probe_base_delay=0.05,
                 probe_max_delay=2.0, probe_jitter=0.25,
                 probe_deadline=5.0, breaker=None, max_queue=128,
                 qos=None, memory_report=None, **server_kw):
        if isinstance(applies, dict):
            group_map = {str(g): list(fns) for g, fns in applies.items()}
        else:
            group_map = {"default": list(applies)}
        n_total = sum(len(fns) for fns in group_map.values())
        if n_total == 0:
            raise ValueError("ServingFleet: need at least one replica")
        self._name = name
        self.buckets = buckets if isinstance(buckets, BucketSpec) \
            else BucketSpec(buckets)
        self._sample = sample
        # live memory gauges (ISSUE 15): one stamped costguard report
        # describes every replica (same executables fleet-wide)
        self._mem_gauges = _telemetry.memory_gauges(memory_report)
        self._default_deadline = default_deadline
        self._qos = qos
        if qos is not None:
            for qc in qos.classes.values():
                if qc.group is not None and qc.group not in group_map:
                    raise ValueError(
                        f"ServingFleet: QoS class {qc.name!r} pins group "
                        f"{qc.group!r}, fleet has {sorted(group_map)}")
        # cap = one replica's total capacity: its queue plus one full
        # batch in flight.  Beyond that the replica would shed anyway —
        # the fleet's cap just makes the verdict immediate and keeps the
        # ranking honest.
        self._max_inflight = int(max_inflight) if max_inflight is not None \
            else int(max_queue) + self.buckets.max_batch
        self._max_redispatch = int(max_redispatch) \
            if max_redispatch is not None else 2 * n_total + 2
        self._probe_base = float(probe_base_delay)
        self._probe_max = float(probe_max_delay)
        self._probe_jitter = float(probe_jitter)
        self._probe_deadline = float(probe_deadline)
        self._breaker = breaker          # factory/instance, reused by scale-up
        self._max_queue = int(max_queue)
        self._server_kw = dict(server_kw)
        self.replicas = []
        self.groups = {g: ReplicaGroup(g) for g in group_map}
        self._next_index = 0
        for gname, fns in group_map.items():
            for apply_fn in fns:
                rep = self._build_replica(apply_fn, gname,
                                          self._next_index)
                self._next_index += 1
                self.replicas.append(rep)
                self.groups[gname].replicas.append(rep)
        self._lock = threading.Lock()
        self._stats = {"admitted": 0, "completed": 0, "failed": 0,
                       "expired": 0, "shed": 0, "rejected": 0,
                       "redispatched": 0, "resumed": 0, "probes": 0,
                       "swaps": 0, "rollbacks": 0, "scale_ups": 0,
                       "retired": 0}
        self._outstanding = 0
        self._retry_q = queue.Queue()
        self._started = threading.Event()
        self._draining = threading.Event()
        self._stop = threading.Event()
        self._router = threading.Thread(target=self._router_loop,
                                        name=f"{name}-router", daemon=True)
        self._c_ready = _profiler.Counter(None, f"{name}::ready_replicas")
        self._c_quar = _profiler.Counter(None, f"{name}::quarantined")
        self._c_redisp = _profiler.Counter(None, f"{name}::redispatched")
        self._c_out = _profiler.Counter(None, f"{name}::outstanding")
        self._c_swaps = _profiler.Counter(None, f"{name}::swaps")
        self._c_rollbacks = _profiler.Counter(None, f"{name}::rollbacks")

    def _build_replica(self, apply_fn, group, idx):
        """One new ``_Replica`` (server + breaker + counters) under the
        given fleet-unique index.  Does NOT insert it into the routing
        set — construction-time callers append directly, ``add_replica``
        appends only after warmup completes."""
        brk = self._breaker() if callable(self._breaker) else self._breaker
        srv = InferenceServer(
            apply_fn, buckets=self.buckets, sample=self._sample,
            breaker=brk, max_queue=self._max_queue,
            name=f"{self._name}-r{idx}", **self._server_kw)
        return _Replica(idx, srv, apply_fn, group=group)

    @property
    def grid_census(self):
        """Executables the bucket grid allows — the per-group warmup
        completeness bar: a scale-up replica joins the routing set only
        once its server has this many distinct warmed signatures (with a
        shared jitted fn they are jit-cache HITS, so growing the fleet
        never grows the fleet-wide executable census)."""
        n_len = 1 if self.buckets.length is None else len(self.buckets.length)
        return len(self.buckets.batch) * n_len

    @classmethod
    def replicated(cls, fn, params, n, quantizer=None, **kw):
        """A fleet of ``n`` replicas of one jitted ``fn(params,
        *batch_leaves)``, each with its own hot-swappable ``params``
        slot (initially shared refs — a rolling update re-points them
        one replica at a time).  One jit cache serves the whole fleet,
        so the executable census of the bucket grid covers ALL replicas,
        not each.  ``quantizer`` (see ``HotSwapApply``) makes this an
        int8 fleet that keeps accepting f32 training snapshots."""
        return cls([HotSwapApply(fn, list(params), quantizer=quantizer)
                    for _ in range(n)], **kw)

    # ------------------------------------------------------------ lifecycle --
    def start(self, warmup=None):
        """Start every replica (warmup per ``InferenceServer.start`` —
        with a shared jitted fn only the first replica compiles; the
        rest hit its cache), then the router thread."""
        if self._draining.is_set():
            raise ServerClosedError(f"{self._name}: already drained")
        started = []
        try:
            for rep in self._members():
                rep.server.start(warmup=warmup)
                started.append(rep)
        except Exception:
            # a failed bring-up must not leak the replicas that DID
            # start (their batch threads would outlive the fleet)
            for rep in started:
                rep.server.drain(timeout=5)
            raise
        if not self._started.is_set():
            self._started.set()
            self._router.start()
        return self

    def __enter__(self):
        if not self._started.is_set():
            self.start()
        return self

    def __exit__(self, *exc):
        self.drain()
        return False

    # ------------------------------------------------------------ admission --
    def _headroom_check(self, qc, group):
        """The class's ``admit_frac`` reservation: the class admits only
        while TOTAL in-flight load (all classes) on the (group's) live
        capacity is under its fraction — the top ``1 - admit_frac`` is
        reserved exclusively for higher classes.  Raises
        ``RejectedError`` when the threshold is already met."""
        if qc.admit_frac >= 1.0:
            return
        with self._lock:
            reps = self.replicas if group is None \
                else self.groups[group].replicas
            live = [rep for rep in reps if not rep.quarantined]
            used = sum(rep.in_flight for rep in live)
        capacity = max(1, len(live)) * self._max_inflight
        if used >= qc.admit_frac * capacity:
            raise RejectedError(
                f"{self._name}: class {qc.name!r} is at its admit_frac "
                f"({qc.admit_frac:.2f}) share of capacity "
                f"({used}/{capacity} in flight) — shedding to preserve "
                f"headroom for higher classes")

    def submit(self, data, deadline=None, tenant=None, klass=None,
               group=None):
        """Route one request to the best replica; returns its fleet-side
        ``Request`` future (failover is transparent — the future resolves
        exactly once, whichever replica ends up serving it).

        ``tenant``/``klass`` are the QoS labels (active when the fleet
        was built with ``qos=``): the class supplies the default
        deadline, may pin a ``ReplicaGroup``, and its ``admit_frac``
        headroom reservation is enforced here.  ``group`` pins this one
        request to a named group (routing and failover stay inside it).

        Refusals are immediate: ``ServerClosedError`` while draining,
        ``TenantThrottledError`` for an over-rate tenant,
        ``RejectedError`` when no ready replica has in-flight headroom.
        An admission-level refusal never touched any replica's queue and
        is never retried by the fleet."""
        t0_us = _telemetry.now_us() if _telemetry.ACTIVE else None
        _fault.fire("fleet.route")
        if self._draining.is_set():
            self._count("rejected")
            raise ServerClosedError(f"{self._name}: draining — "
                                    f"not admitting")
        if not self._started.is_set():
            self._count("rejected")
            raise RejectedError(f"{self._name}: not started")
        qc = None
        if self._qos is not None:
            try:
                qc = self._qos.classify(tenant=tenant, klass=klass)
            except RejectedError:
                self._count("shed")
                raise
            if group is None:
                group = qc.group
            if deadline is None:
                deadline = qc.deadline
        if group is not None and group not in self.groups:
            if qc is not None:
                self._qos.refund(tenant, qc)
            self._count("rejected")
            raise RejectedError(f"{self._name}: unknown replica group "
                               f"{group!r} — have {sorted(self.groups)}")
        if deadline is None:
            deadline = self._default_deadline
        freq = Request(data, deadline=deadline, tenant=tenant,
                       klass=None if qc is None else qc.name)
        try:
            if qc is not None:
                self._headroom_check(qc, group)
        except RejectedError:
            self._qos.refund(tenant, qc)
            self._count("shed")
            raise
        with self._lock:
            self._stats["admitted"] += 1
            self._outstanding += 1
        # trace from the fleet's front door: no queue phase (routing is
        # synchronous; waits between failover hops get their own spans)
        if t0_us is not None:
            _telemetry.begin_request(freq, self._name, t0_us=t0_us,
                                     queue=False)
        try:
            self._dispatch(freq, group, frozenset(), attempts=0,
                           from_router=False)
        except BaseException:
            # refusal accounting lives in shed/rejected (outside the
            # admitted == completed+failed+expired invariant) — the
            # exception TYPE carries the deadline-vs-shed distinction
            with self._lock:
                self._stats["admitted"] -= 1
                self._outstanding -= 1
                self._stats["shed"] += 1
            if qc is not None:
                self._qos.refund(tenant, qc)
            _telemetry.abort_request(freq)
            raise
        if qc is not None:
            self._qos.track(qc, freq)
        self._c_out.set_value(self.outstanding)
        return freq

    def __call__(self, data, deadline=None, timeout=None, **kw):
        """Blocking convenience: submit + ``result()`` (``tenant`` /
        ``klass`` / ``group`` pass through)."""
        return self.submit(data, deadline=deadline, **kw).result(timeout)

    @property
    def outstanding(self):
        """Accepted fleet requests not yet resolved."""
        with self._lock:
            return self._outstanding

    def _count(self, key, n=1):
        with self._lock:
            self._stats[key] += n

    # -------------------------------------------------------------- routing --
    def _remaining(self, freq):
        """Seconds left on the request's ORIGINAL deadline (None =
        unbounded); <= 0 means expired."""
        if freq.deadline is None:
            return None
        return freq.deadline - time.monotonic()

    def _ranked(self, excluded, group=None):
        """Ready, unquarantined, under-cap replicas of ``group`` (None =
        every group), least-loaded first: ranked on (fleet in-flight,
        replica queue depth) — both read from the replica's public
        ``healthz`` snapshot and the fleet's own books, never from
        private server state."""
        with self._lock:
            reps = self.replicas if group is None \
                else self.groups[group].replicas
            snap = [(rep, rep.in_flight, rep.quarantined)
                    for rep in reps if rep.index not in excluded]
        cands = []
        for rep, in_flight, quarantined in snap:
            if quarantined or in_flight >= self._max_inflight:
                continue
            h = rep.server.healthz()
            if not h["ready"]:
                continue
            cands.append((in_flight, h["queue_depth"], rep.index, rep))
        cands.sort(key=lambda c: c[:3])
        return [c[3] for c in cands]

    def _dispatch(self, freq, group, excluded, attempts, from_router,
                  resume=None):
        """Hand ``freq`` to the best replica of its group and register
        the completion callback.  True when a replica accepted it.  When
        none can: front-door callers get the admission verdict as a
        raise; the router gets False and keeps the request pending.

        ``resume`` is a ``SequenceSnapshot`` salvaged off a failed
        generation replica (ISSUE 19): when set and the target replica
        supports ``submit_resume``, the redispatch carries the tokens
        already generated — failover costs the remaining tokens, not a
        restart from scratch."""
        remaining = self._remaining(freq)
        if remaining is not None and remaining <= 0:
            # the deadline verdict, not an admission one: a client must
            # never read "retry elsewhere" on a request whose GLOBAL
            # deadline has passed
            raise DeadlineExceededError(
                f"{self._name}: deadline already passed at routing time")
        last_refusal = None
        for rep in self._ranked(excluded, group):
            # reserve the slot under the lock BEFORE submitting — two
            # client threads racing the same replica must not both slip
            # under the cap
            with self._lock:
                if rep.quarantined or rep.in_flight >= self._max_inflight:
                    continue
                rep.in_flight += 1
            dspan = None
            if freq.trace is not None:
                # the hop span: replica-side phases nest under it, and
                # the wait since the previous hop closes here
                _telemetry.end_span(freq, "failover")
                dspan = _telemetry.open_span(freq, "dispatch",
                                             replica=f"r{rep.index}")
            try:
                _fault.fire("fleet.dispatch")
                can_resume = resume is not None \
                    and hasattr(rep.server, "submit_resume")
                if can_resume:
                    # replica-side tracing stays suppressed either way:
                    # submit_resume has no trace_parent seam, and a
                    # partial replica-only tree would fail audit
                    with _telemetry.suppress():
                        rreq = rep.server.submit_resume(
                            resume, deadline=remaining)
                    self._count("resumed")
                elif dspan is None and _telemetry.ACTIVE:
                    # the sampling decision was made at the front door —
                    # an unsampled fleet request must not be re-sampled
                    # into a partial replica-only tree
                    with _telemetry.suppress():
                        rreq = rep.server.submit(freq.data,
                                                 deadline=remaining)
                else:
                    rreq = rep.server.submit(freq.data, deadline=remaining,
                                             trace_parent=dspan)
            except RejectedError as exc:
                with self._lock:
                    rep.in_flight -= 1
                if dspan is not None:
                    dspan.end(error=type(exc).__name__)
                last_refusal = exc
                continue
            except BaseException as exc:
                with self._lock:
                    rep.in_flight -= 1
                if dspan is not None:
                    dspan.end(error=type(exc).__name__)
                raise
            rreq.add_done_callback(
                lambda r, _rep=rep, _g=group, _ex=excluded, _at=attempts:
                self._on_replica_done(freq, _g, _rep, _ex, _at, r))
            return True
        if from_router:
            return False
        if last_refusal is not None:
            raise RejectedError(
                f"{self._name}: every ready replica refused "
                f"({last_refusal}) — shedding")
        raise RejectedError(
            f"{self._name}: no ready replica with in-flight headroom — "
            f"shedding")

    def _on_replica_done(self, freq, group, rep, excluded, attempts, rreq):
        """Replica-side resolution (runs on the replica's batch thread,
        or on the refusing thread).  Success and terminal errors resolve
        the fleet future; retryable failures go back to the router."""
        with self._lock:
            rep.in_flight -= 1
        err = rreq.exception(timeout=0)          # already resolved
        if err is None:
            self._finish(freq, result=rreq.result(0))
            return
        if isinstance(err, (DeadlineExceededError, NonFiniteOutputError)) \
                or self._stop.is_set():
            # deadline is global; a NaN output is the INPUT's fault and
            # will reproduce on any replica — never re-dispatch either
            self._finish(freq, error=err)
            return
        if freq.trace is not None:
            # the hop failed retryably: the wait until the next dispatch
            # (or the terminal verdict) is failover time, attributed
            _telemetry.open_span(freq, "failover",
                                 from_replica=f"r{rep.index}",
                                 error=type(err).__name__)
        self._retry_q.put((freq, group, frozenset(excluded) | {rep.index},
                           attempts + 1, err))

    def _finish(self, freq, result=None, error=None):
        if error is None:
            freq.set_result(result)
            key = "completed"
        else:
            freq.set_error(error)
            key = "expired" if isinstance(error, DeadlineExceededError) \
                else "failed"
        with self._lock:
            self._stats[key] += 1
            self._outstanding -= 1

    # ---------------------------------------------------------- router thread --
    def _router_loop(self):
        """Failover + quarantine housekeeping: re-dispatches failed-over
        requests, watches replica health, schedules quarantine probes.
        Exits only when the fleet stops — and never with a pending
        request unresolved."""
        pending = []
        try:
            while True:
                try:
                    item = self._retry_q.get(timeout=self._TICK)
                except queue.Empty:
                    item = None
                if item is not None:
                    pending.append(item)
                while True:          # drain whatever else arrived
                    try:
                        pending.append(self._retry_q.get_nowait())
                    except queue.Empty:
                        break
                pending = self._service_pending(pending)
                self._health_scan()
                if self._stop.is_set() and not pending \
                        and self._retry_q.empty():
                    return
        finally:
            # crashed or stopping: strand nothing
            leftovers = list(pending)
            while True:
                try:
                    leftovers.append(self._retry_q.get_nowait())
                except queue.Empty:
                    break
            for freq, _g, _ex, _at, err in leftovers:
                if not freq.done():
                    self._finish(freq, error=ServerClosedError(
                        f"{self._name}: fleet stopped before this request "
                        f"could be re-dispatched (last replica error: "
                        f"{err!r})"))

    def _service_pending(self, pending):
        """One pass over the failover backlog.  Returns what is still
        waiting for a routable replica."""
        still = []
        for entry in pending:
            freq, group, excluded, attempts, last_err = entry
            if freq.done():
                continue
            if freq.expired():
                self._finish(freq, error=DeadlineExceededError(
                    f"deadline exceeded during fleet re-dispatch (last "
                    f"replica error: {last_err!r})"))
                continue
            if attempts > self._max_redispatch:
                self._finish(freq, error=last_err)
                continue
            try:
                # a generation replica that died with salvaged tokens
                # left the snapshot on its terminal error — the next
                # replica resumes instead of regenerating (ISSUE 19)
                ok = self._dispatch(freq, group, excluded, attempts,
                                    from_router=True,
                                    resume=getattr(last_err, "snapshot",
                                                   None))
            except Exception as exc:    # injected fleet.dispatch fault —
                self._finish(freq, error=exc)   # resolved, never dropped
                continue
            if ok:
                self._count("redispatched")
                self._c_redisp.increment()
                continue
            if self._draining.is_set() and not self._any_ready(group):
                self._finish(freq, error=ServerClosedError(
                    f"{self._name}: draining with no ready replica — "
                    f"request not served (last replica error: "
                    f"{last_err!r})"))
                continue
            if not self._group_alive(group):
                # every batch thread this request may route to is dead:
                # nothing in-process can ever serve it again — a
                # deadline-less request must resolve, not hang until
                # someone thinks to call drain()
                self._finish(freq, error=ServerClosedError(
                    f"{self._name}: every routable replica batch thread "
                    f"is dead — request not served (last replica error: "
                    f"{last_err!r})"))
                continue
            if excluded:
                # nothing OUTSIDE the excluded set can take it right now:
                # open the set back up (an excluded replica may have
                # healed) and bill one attempt for the failed pass, so a
                # request that keeps failing everywhere stays bounded by
                # max_redispatch instead of spinning forever
                excluded, attempts = frozenset(), attempts + 1
            still.append((freq, group, excluded, attempts, last_err))
        return still

    def _members(self, group=None):
        """Membership snapshot (list copy under the lock — replicas may
        be retired or added from other threads at any time)."""
        with self._lock:
            return list(self.replicas if group is None
                        else self.groups[group].replicas)

    def _any_ready(self, group=None):
        with self._lock:
            reps = list(self.replicas if group is None
                        else self.groups[group].replicas)
            quarantined = {rep.index for rep in reps if rep.quarantined}
        return any(rep.server.ready() for rep in reps
                   if rep.index not in quarantined)

    def _group_alive(self, group=None):
        return any(rep.server.alive() for rep in self._members(group))

    # ------------------------------------------------------------ quarantine --
    def _health_scan(self):
        """Router-tick health pass: quarantine replicas that died or
        tripped OPEN; schedule probes for auto-quarantined ones."""
        now = time.monotonic()
        n_ready, n_quar = 0, 0
        for rep in self._members():
            with self._lock:
                quarantined = rep.quarantined
                manual, probing = rep.manual, rep.probing
                next_at = rep.next_probe_at
            if not quarantined:
                if rep.server.ready():
                    n_ready += 1
                if not self._draining.is_set():
                    dead = not rep.server.alive()
                    tripped = rep.server.breaker.state == OPEN
                    if dead or tripped:
                        self.quarantine(
                            rep, manual=False,
                            reason="batch thread dead" if dead
                            else "breaker OPEN")
                        n_quar += 1
                continue
            n_quar += 1
            if manual or probing or now < next_at \
                    or self._draining.is_set():
                continue
            self._probe(rep)
        self._c_ready.set_value(n_ready)
        self._c_quar.set_value(n_quar)

    def quarantine(self, rep, manual=True, reason="manual"):
        """Take one replica out of the routing set.  ``manual=True``
        (the updater's mode) suppresses auto-readmission — the caller
        owns the replica until ``readmit``; ``manual=False`` hands it to
        the router's probe schedule."""
        rep = self._resolve(rep)
        with self._lock:
            already = rep.quarantined
            rep.quarantined = True
            rep.manual = bool(manual)
            if not already:
                rep.probe_attempts = 0
                rep.next_probe_at = time.monotonic() + _fault.backoff_delay(
                    1, self._probe_base, self._probe_max,
                    self._probe_jitter)
        if not already:
            _logger.warning("%s: replica r%d quarantined (%s)",
                            self._name, rep.index, reason)
        return rep

    def readmit(self, rep):
        """Put a quarantined replica back in the routing set."""
        rep = self._resolve(rep)
        with self._lock:
            rep.quarantined = False
            rep.manual = False
            rep.probe_attempts = 0
            rep.probing = False

    def _resolve(self, rep):
        """A replica by its fleet-unique ``index`` (NOT list position —
        retire/add shifts positions, indices are forever) or by object."""
        if not isinstance(rep, int):
            return rep
        for r in self._members():
            if r.index == rep:
                return r
        raise KeyError(f"{self._name}: no replica with index {rep} "
                       f"(retired?)")

    def wait_idle(self, rep, timeout=None, poll=0.01):
        """Block until a replica has zero fleet-dispatched work in
        flight (quarantine it first, or new work keeps arriving).  True
        when idle within ``timeout``."""
        rep = self._resolve(rep)
        t_end = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                idle = rep.in_flight == 0
            if idle:
                return True
            if t_end is not None and time.monotonic() >= t_end:
                return False
            time.sleep(poll)

    def _probe(self, rep):
        """One quarantine probe, non-blocking: push the warmup sample
        through the replica's full serving path and judge it from the
        completion callback.  Without a ``sample`` the fleet can only
        watch ``ready()`` (the replica's own idle probe does the
        healing)."""
        with self._lock:
            rep.probing = True
            self._stats["probes"] += 1
        ok_now = None
        try:
            _fault.fire("fleet.probe")
            if self._sample is None:
                ok_now = rep.server.ready()
            else:
                # infrastructure traffic, not a client request — a
                # probe's tree would pollute the per-phase histograms
                with _telemetry.suppress():
                    rreq = rep.server.submit(self._sample,
                                             deadline=self._probe_deadline)
        except Exception:        # refused (engaged breaker, dead server,
            ok_now = False       # injected fleet.probe fault): not healed
        if ok_now is not None:
            self._probe_verdict(rep, ok_now)
            return
        rreq.add_done_callback(
            lambda r: self._probe_verdict(
                rep, r.exception(0) is None and rep.server.ready()))

    def _probe_verdict(self, rep, ok):
        with self._lock:
            rep.probing = False
            if ok and not rep.manual:
                rep.quarantined = False
                rep.probe_attempts = 0
                readmitted = True
            else:
                rep.probe_attempts += 1
                rep.next_probe_at = time.monotonic() + _fault.backoff_delay(
                    rep.probe_attempts + 1, self._probe_base,
                    self._probe_max, self._probe_jitter)
                readmitted = False
        if readmitted:
            _logger.warning("%s: replica r%d readmitted after probe",
                            self._name, rep.index)

    # --------------------------------------------------- elastic membership --
    def add_replica(self, apply_fn=None, group="default", warmup=None):
        """Grow ``group`` by one replica: spawn → warmup until the
        bucket-grid census is complete → join the routing set.  The new
        replica serves NO traffic before its warmup census completes —
        it is not a fleet member until the final append, so the router,
        ``healthz`` and failover cannot see a half-warmed server.  With
        a shared jitted fn the warmup compiles nothing new (every bucket
        signature is a jit-cache hit): scaling up never grows the
        fleet-wide executable census.  Returns the new ``_Replica``.

        Raises ``ServerClosedError`` while draining and ``ValueError``
        when ``apply_fn`` is omitted and the group has no live member to
        clone (cloning needs the ``HotSwapApply`` protocol: the clone
        shares the jitted fn and starts on the group's CURRENT params,
        quantizer included)."""
        _fault.fire("fleet.scale_up")
        if self._draining.is_set():
            raise ServerClosedError(f"{self._name}: draining — not "
                                    f"scaling up")
        group = str(group)
        with self._lock:
            grp = self.groups.get(group)
            peers = [] if grp is None else list(grp.replicas)
        if apply_fn is None:
            tpl = next((p.apply for p in peers if p.server.alive()), None)
            if tpl is None or not hasattr(tpl, "swap"):
                raise ValueError(
                    f"{self._name}: add_replica(group={group!r}) needs "
                    f"apply_fn= — no live HotSwapApply peer to clone")
            params = dict(tpl.params) if isinstance(tpl.params, dict) \
                else list(tpl.params)
            apply_fn = HotSwapApply(tpl._fn, params,
                                    quantizer=tpl.quantizer)
        with self._lock:
            idx = self._next_index
            self._next_index += 1
        rep = self._build_replica(apply_fn, group, idx)
        started = self._started.is_set()
        if started:
            # warmup (the only place a compile could happen) runs OUTSIDE
            # the fleet lock and BEFORE membership — a stalled compile
            # delays the scale-up, never a live request
            rep.server.start(warmup=warmup)
            if self._sample is not None \
                    and len(rep.server.distinct_shapes) < self.grid_census:
                rep.server.drain(timeout=5)
                raise RuntimeError(
                    f"{self._name}: new replica r{rep.index} warmed "
                    f"{len(rep.server.distinct_shapes)} of "
                    f"{self.grid_census} bucket signatures — refusing to "
                    f"admit a census-incomplete replica")
        with self._lock:
            if self._draining.is_set():
                admit = False
            else:
                admit = True
                if group not in self.groups:
                    self.groups[group] = ReplicaGroup(group)
                self.groups[group].replicas.append(rep)
                self.replicas.append(rep)
                self._stats["scale_ups"] += 1
        if not admit:
            rep.server.drain(timeout=5)
            raise ServerClosedError(f"{self._name}: drained during "
                                    f"scale-up — replica discarded")
        _logger.warning("%s: replica r%d added to group %r",
                        self._name, rep.index, group)
        return rep

    def retire_replica(self, rep, timeout=30.0, force=False):
        """Shrink the fleet by one replica, dropping zero accepted
        requests: manual-quarantine (no new dispatches) → wait for its
        fleet-dispatched work to resolve (served, or failed over by the
        router) → drain its server → remove it from the routing set,
        ``healthz`` and ``stats`` → clear its profiler counter series
        (``profiler.counters_clear``) so a long-lived autoscaled process
        does not accrete dead replicas' gauges.

        Refuses (``ValueError``) to retire the last live replica of its
        group unless ``force=True`` — an accepted request must always
        have somewhere to resolve.  If the replica's in-flight work does
        not drain within ``timeout`` the retire ABORTS: the replica is
        readmitted and a ``RuntimeError`` raises (nothing was removed)."""
        _fault.fire("fleet.retire")
        rep = self._resolve(rep)
        with self._lock:
            if rep not in self.replicas:
                raise KeyError(f"{self._name}: replica r{rep.index} is "
                               f"not a fleet member")
            candidates = [r for r in self.groups[rep.group].replicas
                          if r is not rep and not r.quarantined]
        peers = [r for r in candidates if r.server.alive()]
        if not peers and not force and not self._draining.is_set():
            raise ValueError(
                f"{self._name}: r{rep.index} is the last live replica of "
                f"group {rep.group!r} — retiring it would strand traffic "
                f"(force=True overrides)")
        self.quarantine(rep, manual=True, reason="retire")
        if not self.wait_idle(rep, timeout=timeout):
            self.readmit(rep)
            raise RuntimeError(
                f"{self._name}: r{rep.index} still had fleet work in "
                f"flight after {timeout}s — retire aborted, replica "
                f"readmitted")
        rep.server.drain(timeout=timeout)
        with self._lock:
            self.replicas.remove(rep)
            self.groups[rep.group].replicas.remove(rep)
            self._stats["retired"] += 1
        # the retired member's counter series would otherwise report its
        # last values forever (and a later add_replica reusing nothing —
        # indices are unique — would still leak one series per cycle)
        _profiler.counters_clear(f"{self._name}-r{rep.index}::")
        _logger.warning("%s: replica r%d retired from group %r",
                        self._name, rep.index, rep.group)
        return rep

    def scaling_signals(self, group=None):
        """The autoscaler's input snapshot for ``group`` (None = whole
        fleet): live membership, readiness, queue depth, occupancy of
        the live in-flight capacity, and the cumulative per-class
        deadline-miss count (the policy diffs it per tick).  Non-blocking
        reads only — safe on a control-loop cadence."""
        reps = self._members(group)
        with self._lock:
            view = [(rep.quarantined, rep.in_flight) for rep in reps]
        ready = depth = 0
        for rep, (quarantined, _) in zip(reps, view):
            if not quarantined and rep.server.ready():
                ready += 1
                depth += rep.server.healthz()["queue_depth"]
        outstanding = sum(in_flight for _, in_flight in view)
        capacity = max(1, ready) * self._max_inflight
        misses = 0
        if self._qos is not None:
            misses = sum(s["deadline_miss"]
                         for s in self._qos.snapshot().values())
        return {"replicas": len(reps), "ready": ready,
                "outstanding": outstanding, "queue_depth": depth,
                "occupancy": outstanding / capacity,
                "deadline_miss": misses}

    # --------------------------------------------------------------- health --
    def alive(self):
        """Liveness: any replica's batch thread is running."""
        return any(rep.server.alive() for rep in self._members())

    def ready(self):
        """Readiness: started, not draining, and at least one
        unquarantined replica is ready."""
        return (self._started.is_set() and not self._draining.is_set()
                and self._any_ready())

    def healthz(self):
        """Fleet probe snapshot: fleet verdicts plus each replica's own
        ``healthz`` extended with the fleet's view of it (``quarantined``,
        fleet-tracked ``fleet_in_flight``), per-``ReplicaGroup`` rollups,
        and the fleet-level per-class SLO snapshot (``classes`` — present
        whenever the fleet has a ``qos=`` policy).  Membership is live:
        a retired replica's row disappears, a scale-up's appears only
        once it joined the routing set."""
        with self._lock:
            view = [(rep, rep.in_flight, rep.quarantined, rep.group)
                    for rep in self.replicas]
            group_names = list(self.groups)
            outstanding = self._outstanding
        replicas = {}
        groups = {g: {"replicas": [], "ready_replicas": 0,
                      "quarantined": 0, "in_flight": 0,
                      "census": self.grid_census} for g in group_names}
        for rep, in_flight, quarantined, gname in view:
            h = rep.server.healthz()
            h["quarantined"] = quarantined
            h["fleet_in_flight"] = in_flight
            h["group"] = gname
            replicas[f"r{rep.index}"] = h
            g = groups.setdefault(
                gname, {"replicas": [], "ready_replicas": 0,
                        "quarantined": 0, "in_flight": 0,
                        "census": self.grid_census})
            g["replicas"].append(f"r{rep.index}")
            g["in_flight"] += in_flight
            if quarantined:
                g["quarantined"] += 1
            elif h["ready"]:
                g["ready_replicas"] += 1
        return {"alive": self.alive(), "ready": self.ready(),
                "draining": self._draining.is_set(),
                "outstanding": outstanding,
                "ready_replicas": sum(
                    1 for h in replicas.values()
                    if h["ready"] and not h["quarantined"]),
                "groups": groups,
                "classes": {} if self._qos is None
                else self._qos.snapshot(),
                "replicas": replicas}

    @property
    def stats(self):
        """Fleet-level accounting.  ``admitted == completed + failed +
        expired`` once drained — an accepted request always lands in
        exactly one terminal bucket."""
        with self._lock:
            out = dict(self._stats)
            out["outstanding"] = self._outstanding
        out["replicas"] = {f"r{rep.index}": rep.server.stats
                           for rep in self._members()}
        return out

    def telemetry(self, fmt="json"):
        """The unified metrics exposition (ISSUE 13), fleet-wide: the
        router's own counters plus every replica's exposition AGGREGATED
        (counters/gauges summed under a ``replica_`` prefix, per-phase
        latency histograms merged bucket-wise — ``queue_ms`` here is the
        whole fleet's queue distribution) and the fleet-level per-class
        SLO rows.  State-code gauges where a sum is meaningless
        (``breaker_state``) aggregate as the WORST replica's value
        instead.  Same ``telemetry.exposition`` key schema as every
        other runtime; ``fmt="prom"`` renders Prometheus-style text."""
        reps = self._members()
        with self._lock:
            counters = dict(self._stats)
            outstanding = self._outstanding
            quar = [rep.quarantined for rep in reps]
        rpayloads = [rep.server.telemetry() for rep in reps]
        agg = _telemetry.merge_payloads(rpayloads)
        # sum(state codes) of 3 replicas can't tell one-open from
        # three-half-open — the degraded-replica signal telemetry
        # exists for; report the worst state across the fleet
        states = [p["gauges"]["breaker_state"] for p in rpayloads
                  if "breaker_state" in p.get("gauges", {})]
        if states:
            agg["gauges"]["breaker_state"] = max(states)
        counters.update({f"replica_{k}": v
                         for k, v in agg["counters"].items()})
        gauges = {"outstanding": outstanding,
                  "replicas": len(reps),
                  "quarantined": sum(1 for q in quar if q),
                  "ready_replicas": sum(
                      1 for rep, q in zip(reps, quar)
                      if not q and rep.server.ready()),
                  "ready": int(self.ready()), "alive": int(self.alive()),
                  "draining": int(self._draining.is_set())}
        # the runtime-introspection families (ISSUE 15): the fleet's own
        # compile site (replica sites ride the replica_ prefix) + the
        # stamped memory bytes
        gauges.update(_telemetry.compile_gauges(self._name))
        gauges.update(self._mem_gauges)
        # snapshot-stream health (ISSUE 17): the fleet is the CONSUMER
        # end of the checkpoint stream (WeightUpdater), so verify
        # failures / skip counts surface here too
        gauges.update(_telemetry.ckpt_gauges())
        gauges.update({f"replica_{k}": v
                       for k, v in agg["gauges"].items()})
        # fleet-routed traces are born under the FLEET's name, so their
        # per-phase histograms (queue/step/dispatch/failover) live under
        # this prefix — replica expositions only carry front-door-to-
        # replica traffic; merge both views
        hists = dict(agg["histograms"])
        own = _telemetry.registry().snapshot(
            prefix=f"{self._name}::")["histograms"]
        if self._qos is not None:      # fleet-level per-class latency —
            for cname, snap in self._qos.latency_snapshots().items():
                own[f"class_{cname}_latency_s"] = snap
        for k, v in own.items():
            hists[k] = v if k not in hists \
                else _telemetry.merge_snapshots([hists[k], v])
        payload = _telemetry.exposition(
            "serving_fleet", self._name, counters, gauges, hists,
            {} if self._qos is None else self._qos.snapshot())
        return _telemetry.render(payload, fmt)

    def stamp_memory_report(self, report):
        """Stamp a costguard-style memory report onto the fleet's
        ``mem_*`` exposition gauges (see
        ``InferenceServer.stamp_memory_report``; one report describes
        every replica — they share the executables)."""
        self._mem_gauges = _telemetry.memory_gauges(report)
        return self._mem_gauges

    # ---------------------------------------------------------------- drain --
    def drain(self, timeout=None):
        """Graceful fleet shutdown: stop admitting, let every accepted
        request reach a terminal state (replicas keep serving their
        queues; the router keeps failing work over while any replica is
        ready), then drain all replicas CONCURRENTLY and stop the
        router.  True when everything resolved and every thread exited
        in time."""
        self._draining.set()
        t_end = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                n = self._outstanding
            if n == 0:
                break
            if t_end is not None and time.monotonic() >= t_end:
                break
            time.sleep(self._TICK)
        threads = [threading.Thread(target=rep.server.drain,
                                    name=f"{self._name}-drain-r{rep.index}")
                   for rep in self._members()]
        for t in threads:
            t.start()
        for t in threads:
            t.join(None if t_end is None
                   else max(0.1, t_end - time.monotonic()))
        self._stop.set()
        if self._started.is_set():
            self._router.join(None if t_end is None
                              else max(0.1, t_end - time.monotonic()))
        self._c_out.set_value(self.outstanding)
        ok = self.outstanding == 0 and not self._router.is_alive() \
            and not any(rep.server.alive() for rep in self._members())
        return ok

    close = drain

    def serve_forever(self, poll=0.05):
        """Block until SIGTERM/SIGINT (``fault.GracefulExit``), then
        drain the whole fleet — the preemption contract, one tier up."""
        with _fault.GracefulExit() as g:
            while not g.requested and self.alive():
                time.sleep(poll)
        return self.drain()


class WeightUpdater:
    """Streams training snapshots into a live fleet, zero downtime.

    Watches a checkpoint directory (a ``parallel.CheckpointManager``
    instance or a plain path written by one) through
    ``checkpoint.wait_for_new``, validates each new snapshot against the
    currently-served params (``validate_params`` — shape/dtype identity
    so executables survive, all-finite so poison never ships), then
    rolls it across the fleet one replica at a time.  A fleet whose
    applies carry a ``quantizer`` (int8 serving via
    ``amp.Int8Quantizer``) re-quantizes each full-precision snapshot
    into the served representation BEFORE validation — an f32 training
    job streams into a reduced-precision fleet without recompiles::

        quarantine → drain in-flight → hot-swap params → probe → readmit

    The fleet never loses more than one replica of capacity, and a
    request never sees a half-updated replica.  A probe failure rolls
    the replica (and any replicas already updated) back to the previous
    weights and raises ``UpdateRolledBackError`` — the fleet returns to
    full ready capacity on the old weights.  A DEAD replica (batch
    thread gone) is skipped, not fatal: it cannot serve, and a wedged
    update would be a second outage on top of the replica loss.  Replica
    apply fns must expose the ``HotSwapApply`` protocol (``params`` +
    ``swap()``).
    """

    def __init__(self, fleet, source=None, *, prefix="ckpt", poll=0.25,
                 last_seen=None, probe_deadline=10.0, drain_timeout=30.0):
        self.fleet = fleet
        directory = getattr(source, "directory", source)
        self._directory = None if directory is None else str(directory)
        self._prefix = getattr(source, "prefix", prefix)
        self._poll = float(poll)
        self._probe_deadline = float(probe_deadline)
        self._drain_timeout = float(drain_timeout)
        if last_seen is None and self._directory is not None:
            # the fleet was (typically) just initialized from the newest
            # snapshot — re-applying it would roll every replica through
            # a quarantine/drain/probe cycle for a no-op.  Stream only
            # snapshots committed AFTER this point; pass last_seen=0 (or
            # any older step) to force-apply what is already there.
            from ..parallel.checkpoint import list_checkpoints
            cks = list_checkpoints(self._directory, self._prefix)
            last_seen = cks[-1][0] if cks else None
        self.last_seen = last_seen
        self.applied = 0         # snapshots fully rolled out
        self.skipped = 0         # snapshots refused or rolled back
        self._stop = threading.Event()
        self._thread = None
        for rep in fleet.replicas:
            if not hasattr(rep.apply, "swap"):
                raise ValueError(
                    "WeightUpdater: replica apply fns must expose the "
                    "HotSwapApply protocol (.params + .swap) — build the "
                    "fleet with ServingFleet.replicated or HotSwapApply")
        if fleet._sample is None:
            raise ValueError(
                "WeightUpdater: the fleet needs a sample payload — the "
                "post-swap probe is what stands between a bad snapshot "
                "and live traffic")

    # ------------------------------------------------------------- updates --
    def update(self, snapshot):
        """Apply one snapshot fleet-wide.  ``snapshot`` is a checkpoint
        path (v1 ``save_train_step`` layout) or an already-loaded params
        sequence.  Raises ``SnapshotRejectedError`` (nothing touched) or
        ``UpdateRolledBackError`` (fleet restored to previous weights)."""
        if isinstance(snapshot, (str, os.PathLike)):
            from ..parallel.checkpoint import (CheckpointCorruptError,
                                               load_snapshot_params)
            try:
                params, _names = load_snapshot_params(str(snapshot))
            except FileNotFoundError as exc:
                # retention pruned the path after discovery: stale, not
                # bad — NOT counted in skipped (nothing was wrong with
                # the snapshot; a newer one is committed)
                raise SnapshotPrunedError(
                    f"snapshot {snapshot} pruned by retention before it "
                    f"could be read — re-poll for the newer one") from exc
            except CheckpointCorruptError as exc:
                # v1.1 integrity verdict (digest/size/container damage):
                # rejected BEFORE validate_params, before any replica
                # sees a byte of it
                self.skipped += 1
                raise SnapshotRejectedError(
                    f"snapshot {snapshot} failed integrity verification "
                    f"({exc}) — not applied to any replica") from exc
        else:
            params = snapshot            # container kind is validated
        members = self.fleet._members()
        if not members:
            raise UpdateRolledBackError(
                "no replica to update — the fleet retired them all")
        quantizer = getattr(members[0].apply, "quantizer", None)
        if quantizer is not None:
            # reduced-precision fleet: snapshots arrive full-precision
            # from the training job — re-quantize into the served
            # representation BEFORE validation, so validate_params
            # compares like for like and an f32 rolling update into an
            # int8 fleet is routine, not a dtype-drift rejection
            try:
                params = quantizer(params)
            except Exception as exc:
                self.skipped += 1
                raise SnapshotRejectedError(
                    f"snapshot failed the fleet's quantizer ({exc}) — "
                    f"not applied to any replica") from exc
        try:
            new_params = validate_params(params, members[0].apply.params)
        except SnapshotRejectedError:
            self.skipped += 1
            raise
        done = []                      # [(replica, its previous params)]
        try:
            live = [rep for rep in members if rep.server.alive()]
            if not live:
                raise UpdateRolledBackError(
                    "no live replica to update — the fleet is down")
            for rep in members:
                if rep not in live:
                    # a dead replica cannot serve (it is quarantined and
                    # its probes fail) — aborting the WHOLE update for it
                    # would wedge weight streaming on the first replica
                    # loss; it gets a fresh snapshot when it returns
                    _logger.warning(
                        "%s updater: skipping dead replica r%d",
                        self.fleet._name, rep.index)
                    continue
                try:
                    done.append((rep, self._swap_one(rep, new_params)))
                except Exception:
                    # a replica RETIRED (an autoscaler shrinking
                    # mid-update) or DEAD (killed after the liveness
                    # snapshot) out from under the roll is the
                    # dead-replica case, not a snapshot fault: it cannot
                    # serve, so its probe failure proves nothing about
                    # the weights — skip it, keep rolling.  A replica
                    # that is still a live member re-raises: that IS the
                    # snapshot (or replica) telling us something.
                    with self.fleet._lock:
                        member = rep in self.fleet.replicas
                    if member and rep.server.alive():
                        raise
                    _logger.warning(
                        "%s updater: replica r%d retired or died "
                        "mid-update — skipped", self.fleet._name,
                        rep.index)
        except Exception as exc:
            self.skipped += 1
            self.fleet._count("rollbacks")
            self.fleet._c_rollbacks.increment()
            for rep, old in reversed(done):
                try:
                    self._swap_one(rep, old)
                except Exception:      # noqa: BLE001 — the replica stays
                    pass               # quarantined; the rollback goes on
            if isinstance(exc, UpdateRolledBackError):
                raise
            raise UpdateRolledBackError(
                f"rolling update aborted and rolled back: {exc}") from exc
        self.applied += 1
        self.fleet._count("swaps")
        self.fleet._c_swaps.increment()
        return len(done)

    def _swap_one(self, rep, new_params):
        """One replica through the full protocol; returns its previous
        params.  On probe failure the replica is rolled back in place
        (and re-probed — only a verified replica is readmitted)."""
        _fault.fire("fleet.swap")
        self.fleet.quarantine(rep, manual=True, reason="weight update")
        swapped, old = False, None
        try:
            if not self.fleet.wait_idle(rep, timeout=self._drain_timeout):
                raise UpdateRolledBackError(
                    f"replica r{rep.index} still had in-flight work after "
                    f"{self._drain_timeout}s — update aborted before any "
                    f"swap")
            old = rep.apply.swap(dict(new_params)
                                 if isinstance(new_params, dict)
                                 else list(new_params))
            swapped = True
            self._probe(rep)
        except Exception as exc:
            if swapped:
                rep.apply.swap(old)
                try:
                    self._probe(rep)
                except Exception:
                    # even the OLD weights fail the probe: the replica
                    # itself is sick — leave it quarantined and hand it
                    # to the router's auto-probe schedule
                    with self.fleet._lock:
                        rep.manual = False
                    raise UpdateRolledBackError(
                        f"replica r{rep.index}: post-swap probe failed "
                        f"AND the rollback probe failed — replica left "
                        f"quarantined ({exc})") from exc
            self.fleet.readmit(rep)
            if isinstance(exc, UpdateRolledBackError):
                raise
            raise UpdateRolledBackError(
                f"replica r{rep.index}: post-swap probe failed — rolled "
                f"back to previous weights ({exc})") from exc
        self.fleet.readmit(rep)
        return old

    def _probe(self, rep):
        """Warmup probe through the replica's full serving path; raises
        unless the replica returns an all-finite result in time."""
        _fault.fire("fleet.probe")
        self.fleet._count("probes")
        with _telemetry.suppress():    # infrastructure, untraced
            rreq = rep.server.submit(self.fleet._sample,
                                     deadline=self._probe_deadline)
        out = rreq.result(self._probe_deadline + 1.0)
        leaves = out if isinstance(out, (tuple, list)) else (out,)
        for leaf in leaves:
            if not np.all(np.isfinite(np.asarray(leaf))):
                raise UpdateRolledBackError(
                    f"replica r{rep.index}: probe output is non-finite")

    # --------------------------------------------------------------- watch --
    def poll_once(self, timeout=0.0):
        """Check the directory once (blocking up to ``timeout`` for a
        new snapshot); applies the newest unseen one.  Returns its
        ``num_update`` or None.  A snapshot that fails (validation or
        rollback) is marked seen — a poisoned file must not be retried
        on every poll — and the error propagates.  A path PRUNED between
        discovery and read is stale, not bad: logged, ``None`` returned,
        and the next poll picks up the newer snapshot retention kept."""
        if self._directory is None:
            raise ValueError("WeightUpdater: no watch directory — "
                             "construct with source=")
        from ..parallel.checkpoint import wait_for_new
        found = wait_for_new(self._directory, last_seen=self.last_seen,
                             timeout=timeout, prefix=self._prefix,
                             poll=min(self._poll, 0.05))
        if found is None:
            return None
        num_update, path = found
        self.last_seen = num_update
        try:
            self.update(path)
        except SnapshotPrunedError as exc:
            _logger.info("%s updater: %s", self.fleet._name, exc)
            return None
        return num_update

    def start(self):
        """Watch the directory from a background thread; each new
        snapshot rolls out as it commits.  Failed snapshots are logged
        and skipped — the watcher never dies on a bad file."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._watch_loop,
            name=f"{self.fleet._name}-updater", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout=None):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
        return self._thread is None or not self._thread.is_alive()

    def _watch_loop(self):
        while not self._stop.is_set():
            try:
                self.poll_once(timeout=self._poll)
            except (SnapshotRejectedError, UpdateRolledBackError) as exc:
                _logger.warning("%s updater: snapshot skipped: %s",
                                self.fleet._name, exc)
            except Exception as exc:   # noqa: BLE001 — the watcher must
                _logger.warning(       # outlive transient I/O errors
                    "%s updater: poll failed (%s) — retrying",
                    self.fleet._name, exc)
