"""Shared helpers: dtype names, registries, errors.

TPU-native re-design of the reference's dmlc-core helpers
(ref: 3rdparty/dmlc-core/include/dmlc/{logging,parameter}.h — LOG/CHECK,
dmlc::Parameter).  Here the dtype table replaces mshadow's type_flag_ and the
registry replaces dmlc::Registry.
"""
from __future__ import annotations

import numpy as np

__all__ = ["MXNetError", "dtype_np", "dtype_name", "string_types", "numeric_types"]


class MXNetError(RuntimeError):
    """Framework error type (ref: include/mxnet/c_api.h — MXGetLastError)."""


string_types = (str,)
numeric_types = (float, int, np.generic)

# Canonical dtype name table (ref: include/mxnet/tensor_blob.h — type_flag_).
_DTYPE_ALIASES = {
    "float32": np.float32,
    "float64": np.float64,
    "float16": np.float16,
    "bfloat16": None,  # resolved lazily via ml_dtypes to avoid import cycles
    "uint8": np.uint8,
    "int8": np.int8,
    "int16": np.int16,
    "int32": np.int32,
    "int64": np.int64,
    "bool": np.bool_,
}


def dtype_np(dtype):
    """Normalise a dtype spec (string / np.dtype / python type) to np.dtype."""
    if dtype is None:
        return np.dtype(np.float32)
    if isinstance(dtype, str):
        if dtype == "bfloat16":
            import ml_dtypes  # ships with jax

            return np.dtype(ml_dtypes.bfloat16)
        if dtype in _DTYPE_ALIASES:
            return np.dtype(_DTYPE_ALIASES[dtype])
    return np.dtype(dtype)


def dtype_name(dtype) -> str:
    """Inverse of :func:`dtype_np` — canonical string name."""
    return np.dtype(dtype).name


class Registry:
    """Minimal name->object registry (ref: dmlc::Registry pattern)."""

    def __init__(self, kind: str):
        self.kind = kind
        self._entries = {}

    def register(self, name, obj=None, aliases=()):
        def _do(o):
            key = name.lower()
            if key in self._entries and self._entries[key] is not o:
                raise MXNetError(f"duplicate {self.kind} registration: {name}")
            self._entries[key] = o
            for a in aliases:
                self._entries[a.lower()] = o
            return o

        if obj is None:
            return _do
        return _do(obj)

    def get(self, name):
        try:
            return self._entries[name.lower()]
        except KeyError:
            raise MXNetError(
                f"unknown {self.kind} '{name}'; known: {sorted(self._entries)}"
            ) from None

    def __contains__(self, name):
        return name.lower() in self._entries

    def keys(self):
        return sorted(self._entries)


_native_lib_cache: dict = {}


def load_native_lib(so_name: str, source_cc: str):
    """dlopen a native core from mxnet_tpu/_lib, building it via ``make -C
    src`` first if the shared object is missing (ref: libmxnet.so loading
    in python/mxnet/base.py _load_lib).  Returns the ctypes CDLL or None —
    callers fall back to their pure-Python twin.  Shared by recordio and
    the storage pool so the build bootstrap lives in one place."""
    import ctypes
    import os
    import subprocess

    if so_name in _native_lib_cache:  # memoized, incl. failures (None) —
        return _native_lib_cache[so_name]  # never re-runs `make` per call

    pkg = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(pkg, "_lib", so_name)
    src = os.path.join(os.path.dirname(pkg), "src")
    cc_path = os.path.join(src, source_cc)
    stale = False
    if os.path.exists(path) and os.path.exists(cc_path):
        # rebuild when the source outran the artifact — a stale .so from
        # before an ABI extension would otherwise fail at symbol lookup
        stale = os.path.getmtime(cc_path) > os.path.getmtime(path)
    if (not os.path.exists(path) or stale) and os.path.exists(cc_path):
        try:
            subprocess.run(["make", "-C", src], capture_output=True,
                           timeout=120, check=False)
        except Exception:
            pass
    lib = None
    if os.path.exists(path):
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            lib = None
    _native_lib_cache[so_name] = lib
    return lib
