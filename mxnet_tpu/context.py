"""Device context model.

TPU-native equivalent of the reference's ``Context`` (ref: include/mxnet/base.h
— struct Context, Context::CPU/GPU).  A Context names a logical device;
placement is realised through JAX's device objects / shardings rather than CUDA
device ids.  ``mx.tpu()`` is the headline context; ``mx.cpu()`` maps to the XLA
CPU backend; ``mx.gpu()`` is accepted for API compatibility and resolves to an
accelerator if one exists.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["Context", "cpu", "tpu", "gpu", "cpu_pinned", "current_context",
           "num_tpus", "num_gpus", "gpu_memory_info"]

_tls = threading.local()


def _accelerator_devices():
    """Local (addressable) devices of the default (non-cpu) backend, or []
    if the default is cpu.

    Uses ``jax.local_devices()`` — never the global ``jax.devices()`` — so
    that under ``jax.distributed`` each rank resolves onto a device it can
    actually address (device_put to a non-addressable device raises)."""
    devs = jax.local_devices()
    if devs and devs[0].platform != "cpu":
        return devs
    return []


class Context:
    """A logical device. Usable as a context manager like the reference's.

    device_type in {'cpu', 'tpu', 'gpu', 'cpu_pinned', 'cpu_shared'}; 'gpu' and
    the pinned/shared cpu flavours are compat aliases that resolve onto the
    accelerator / cpu backends respectively.
    """

    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "cpu_shared", 6: "tpu"}
    devstr2type = {v: k for k, v in devtype2str.items()}

    def __init__(self, device_type, device_id: int = 0):
        if isinstance(device_type, Context):
            device_type, device_id = device_type.device_type, device_type.device_id
        if device_type not in self.devstr2type:
            raise ValueError(f"unknown device type {device_type!r}")
        self.device_type = device_type
        self.device_id = int(device_id)

    # -- resolution -------------------------------------------------------
    @property
    def device(self):
        """Resolve to a concrete jax.Device (fallback-tolerant for CI hosts)."""
        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            try:
                devs = jax.local_devices(backend="cpu")
            except RuntimeError:
                devs = jax.local_devices()
            return devs[min(self.device_id, len(devs) - 1)]
        accel = _accelerator_devices()
        if accel:
            return accel[min(self.device_id, len(accel) - 1)]
        # No accelerator on this host (e.g. CPU-only test run): fall back.
        return jax.local_devices()[0]

    @property
    def real_device_type(self) -> str:
        return self.device.platform

    # -- protocol ---------------------------------------------------------
    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    def __enter__(self):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self)
        return self

    def __exit__(self, *exc):
        _tls.stack.pop()

    # MXNet API compat
    def empty_cache(self):
        """Free cached device memory (pool is managed by PJRT; best-effort)."""
        import gc

        gc.collect()

    def memory_info(self):
        """Device memory statistics from PJRT (the storage-manager
        introspection surface; ref: storage.cc GetMemoryPoolInfo /
        mx.context.gpu_memory_info).  Keys follow PJRT's memory_stats
        (bytes_in_use, peak_bytes_in_use, bytes_limit, ...); CPU backends
        without stats return the framework-side storage accounting only
        (mxnet_tpu/storage.py)."""
        stats = self.device.memory_stats()
        out = dict(stats) if stats else {}
        from . import storage
        out["framework_live_bytes"] = storage.live_bytes(str(self))
        out["framework_peak_bytes"] = storage.stats(str(self))["peak_bytes"]
        return out

    @classmethod
    def default_ctx(cls):
        return current_context()


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def gpu(device_id: int = 0) -> Context:
    """Compat alias: resolves to the accelerator backend on TPU hosts."""
    return Context("gpu", device_id)


def num_tpus() -> int:
    return len(_accelerator_devices())


def num_gpus() -> int:
    # API-compat: on a TPU host there are no CUDA devices.
    try:
        return len(jax.devices("gpu"))
    except RuntimeError:
        return 0


def gpu_memory_info(device_id: int = 0):
    """(free, total) bytes for the accelerator (ref: mx.context.
    gpu_memory_info; 'gpu' meaning the accelerator backend here)."""
    stats = Context("tpu", device_id).memory_info()
    total = stats.get("bytes_limit", 0)
    used = stats.get("bytes_in_use", 0)
    if not total:
        # PJRT plugin reports no memory_stats (axon tunnel): fall back to
        # the configured HBM capacity minus framework-accounted live bytes.
        # tpu(N)/gpu(N) are compat aliases for the same accelerator, so sum
        # both accounting keys.
        from . import config, storage
        total = int(config.get("MXNET_TPU_HBM_CAPACITY_MB")) << 20
        used = (storage.live_bytes(f"tpu({device_id})")
                + storage.live_bytes(f"gpu({device_id})"))
    return (max(0, total - used), total)


def current_context() -> Context:
    stack = getattr(_tls, "stack", None)
    if stack:
        return stack[-1]
    return tpu(0) if _accelerator_devices() else cpu(0)
