"""Custom-operator escape hatch.

ref: python/mxnet/operator.py — class CustomOp / CustomOpProp +
operator.register; src/operator/custom/custom.cc.  Users subclass CustomOp
(forward/backward over NDArrays), describe shapes/types in a CustomOpProp,
register under a name, and call ``mx.nd.Custom(..., op_type=name)``.

TPU-native notes: the custom body runs eagerly in Python (like the
reference, whose custom ops always run on the engine's Python thread and
break graph fusion).  Under autograd the user's ``backward`` is spliced
into the tape; under jit tracing, custom ops raise — wrap the hot path in
a registered op (ops/registry.py) instead if it must compile."""
from __future__ import annotations

import jax

from .ndarray import NDArray
from . import autograd as _autograd

__all__ = ["CustomOp", "CustomOpProp", "register", "get"]

_REGISTRY = {}


class CustomOp:
    """ref: operator.CustomOp — override forward() and backward()."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """ref: CustomOp.assign — honour the write/add/null request."""
        if req in ("write", "inplace", None):
            dst._data = src._data if isinstance(src, NDArray) else src
        elif req == "add":
            dst._data = dst._data + (src._data if isinstance(src, NDArray)
                                     else src)
        elif req == "null":
            pass
        else:
            raise ValueError(f"unknown req {req!r}")


class CustomOpProp:
    """ref: operator.CustomOpProp — shapes/dtypes/arity metadata."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def create_operator(self, ctx, shapes, dtypes):
        raise NotImplementedError


def register(name):
    """ref: mx.operator.register — decorator over a CustomOpProp class."""

    def _reg(prop_cls):
        _REGISTRY[name] = prop_cls
        return prop_cls

    return _reg


def get(name):
    return _REGISTRY[name]


def invoke_custom(*inputs, op_type, **kwargs):
    """Run a registered custom op (the ``nd.Custom`` entry point)."""
    if op_type not in _REGISTRY:
        raise ValueError(
            f"custom op {op_type!r} is not registered "
            f"(known: {sorted(_REGISTRY)})")
    if any(isinstance(getattr(a, "_data", a), jax.core.Tracer)
           for a in inputs):
        raise TypeError(
            f"custom op {op_type!r} cannot run under jit tracing — custom "
            f"Python bodies execute eagerly (register a real op in "
            f"ops/registry.py for a compilable kernel)")
    prop = _REGISTRY[op_type](**kwargs)
    in_shapes = [list(a.shape) for a in inputs]
    _, out_shapes, _ = prop.infer_shape(in_shapes)
    in_types = [a.dtype for a in inputs]
    _, out_types, _ = prop.infer_type(in_types)
    # reference contract: create_operator receives the INPUT shapes/dtypes
    op = prop.create_operator(None, in_shapes, in_types)

    from . import ndarray as nd
    outs = [nd.zeros(tuple(s), dtype=t)
            for s, t in zip(out_shapes, out_types)]
    with _autograd.pause():
        op.forward(_autograd.is_training(), ["write"] * len(outs),
                   list(inputs), outs, [])

    if _autograd.is_recording():
        in_list = list(inputs)
        out_list = list(outs)

        def _pull(cts):
            in_grads = [nd.zeros(a.shape, dtype=a.dtype) for a in in_list]
            out_grads = [NDArray(c) for c in cts]
            with _autograd.pause():
                op.backward(["write"] * len(in_grads), out_grads, in_list,
                            out_list, in_grads, [])
            return [g._data for g in in_grads]

        node = _autograd.TapeNode(in_list, out_list, _pull,
                                  name=f"Custom:{op_type}")
        _autograd.append_node(node)
    return outs if len(outs) > 1 else outs[0]
