"""Weight initializers.

ref: python/mxnet/initializer.py — class Initializer and the registry of
Xavier/MSRAPrelu/Orthogonal/... . TPU-native: initializers produce values via
the framework PRNG (threefry key splits, reproducible under seed()) and return
jax arrays; `InitDesc`-style attribute dispatch is kept so layers can request
special inits by parameter name suffix (ref: Initializer.__call__ dispatching
on name endings like "weight"/"bias"/"gamma"/"beta").
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .base import dtype_np
from . import random as _random

__all__ = [
    "Initializer", "Zero", "One", "Constant", "Uniform", "Normal", "TruncNorm",
    "Xavier", "MSRAPrelu", "Orthogonal", "LSTMBias", "Bilinear", "register",
    "create",
]

_REGISTRY = {}


def register(klass):
    """ref: python/mxnet/initializer.py — @register decorator."""
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(init, **kwargs):
    """Create an initializer from an instance / name / None."""
    if init is None:
        return Uniform(0.07)
    if isinstance(init, Initializer):
        return init
    if isinstance(init, str):
        name = init.lower()
        # common plural/alias forms used throughout the reference's layers
        aliases = {"zeros": "zero", "ones": "one", "gaussian": "normal"}
        name = aliases.get(name, name)
        if name not in _REGISTRY:
            raise ValueError(f"unknown initializer '{init}'")
        return _REGISTRY[name](**kwargs)
    raise TypeError(f"cannot create initializer from {init!r}")


class Initializer:
    """Base initializer (ref: python/mxnet/initializer.py — class Initializer)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, name: str, shape, dtype="float32"):
        """Dispatch on parameter-name suffix like the reference does."""
        if name.endswith("gamma") or name.endswith("running_var") or name.endswith("var"):
            return self._init_one(shape, dtype)
        if name.endswith("beta") or name.endswith("running_mean") or name.endswith("mean"):
            return self._init_zero(shape, dtype)
        if name.endswith("bias"):
            return self._init_zero(shape, dtype)
        return self.init_array(shape, dtype)

    # The actual strategy for "weight-like" params; subclasses override.
    def init_array(self, shape, dtype="float32"):
        raise NotImplementedError

    def _init_zero(self, shape, dtype):
        return jnp.zeros(shape, dtype_np(dtype))

    def _init_one(self, shape, dtype):
        return jnp.ones(shape, dtype_np(dtype))

    def __repr__(self):
        return f"{type(self).__name__}({self._kwargs})"


@register
class Zero(Initializer):
    def init_array(self, shape, dtype="float32"):
        return jnp.zeros(shape, dtype_np(dtype))


@register
class One(Initializer):
    def init_array(self, shape, dtype="float32"):
        return jnp.ones(shape, dtype_np(dtype))


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def init_array(self, shape, dtype="float32"):
        return jnp.full(shape, self.value, dtype_np(dtype))

    # constants apply to every suffix
    def __call__(self, name, shape, dtype="float32"):
        return self.init_array(shape, dtype)


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def init_array(self, shape, dtype="float32"):
        key = _random.next_key()
        return jax.random.uniform(key, shape, jnp.float32,
                                  minval=-self.scale, maxval=self.scale).astype(dtype_np(dtype))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def init_array(self, shape, dtype="float32"):
        key = _random.next_key()
        return (jax.random.normal(key, shape, jnp.float32) * self.sigma).astype(dtype_np(dtype))


@register
class TruncNorm(Initializer):
    """Truncated normal at ±2σ (ref: gluonnlp TruncNorm — BERT's init)."""

    def __init__(self, mean=0.0, stdev=0.01):
        super().__init__(mean=mean, stdev=stdev)
        self.mean = mean
        self.stdev = stdev

    def init_array(self, shape, dtype="float32"):
        key = _random.next_key()
        x = jax.random.truncated_normal(key, -2.0, 2.0, shape, jnp.float32)
        return (x * self.stdev + self.mean).astype(dtype_np(dtype))


def _fan(shape, factor_type):
    """fan_in/fan_out with conv receptive-field scaling (ref: Xavier._init_weight)."""
    hw_scale = 1.0
    if len(shape) < 2:
        fan_in = fan_out = shape[0] if shape else 1
        return fan_in, fan_out
    if len(shape) > 2:
        hw_scale = float(np.prod(shape[2:]))
    fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
    return fan_in, fan_out


@register
class Xavier(Initializer):
    """ref: python/mxnet/initializer.py — class Xavier."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type, magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def init_array(self, shape, dtype="float32"):
        fan_in, fan_out = _fan(shape, self.factor_type)
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise ValueError("factor_type must be avg/in/out")
        scale = math.sqrt(self.magnitude / max(factor, 1e-12))
        key = _random.next_key()
        if self.rnd_type == "uniform":
            a = jax.random.uniform(key, shape, jnp.float32, minval=-scale, maxval=scale)
        elif self.rnd_type == "gaussian":
            a = jax.random.normal(key, shape, jnp.float32) * scale
        else:
            raise ValueError("rnd_type must be uniform/gaussian")
        return a.astype(dtype_np(dtype))


@register
class MSRAPrelu(Xavier):
    """ref: class MSRAPrelu — He init with slope correction."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Orthogonal(Initializer):
    """ref: class Orthogonal — SVD-orthogonalised gaussian."""

    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def init_array(self, shape, dtype="float32"):
        nout = shape[0]
        nin = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        key = _random.next_key()
        if self.rand_type == "uniform":
            tmp = jax.random.uniform(key, (nout, nin), jnp.float32, minval=-1.0, maxval=1.0)
        else:
            tmp = jax.random.normal(key, (nout, nin), jnp.float32)
        u, _, v = jnp.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == (nout, nin) else v
        return (self.scale * q.reshape(shape)).astype(dtype_np(dtype))


@register
class LSTMBias(Initializer):
    """ref: class LSTMBias — forget-gate bias set to a constant (default 1)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def __call__(self, name, shape, dtype="float32"):
        b = np.zeros(shape, dtype_np(dtype))
        n = shape[0] // 4  # [i, f, g, o] cuDNN gate order (see ops/rnn.py)
        b[n:2 * n] = self.forget_bias
        return jnp.asarray(b)

    init_array = __call__  # pragma: no cover - name-independent


@register
class Bilinear(Initializer):
    """ref: class Bilinear — upsampling deconv weights."""

    def init_array(self, shape, dtype="float32"):
        weight = np.zeros(shape, dtype_np("float32"))
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        return jnp.asarray(weight, dtype_np(dtype))
