#!/usr/bin/env python
"""im2rec: pack an image folder or .lst file into RecordIO.

ref: tools/im2rec.py — two modes:
  list generation:  python tools/im2rec.py --list prefix image_root
  packing:          python tools/im2rec.py prefix image_root [--resize N]

.lst format (tab-separated): index, label, relative path — identical to the
reference, so existing lists work unchanged.  Packing writes prefix.rec +
prefix.idx through the native recordio core.
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def make_list(prefix, root, recursive=True, train_ratio=1.0, shuffle=True):
    paths = []
    classes = {}
    for dirpath, dirnames, filenames in sorted(os.walk(root)):
        dirnames.sort()
        for fn in sorted(filenames):
            if fn.lower().endswith(EXTS):
                rel = os.path.relpath(os.path.join(dirpath, fn), root)
                cls = os.path.dirname(rel) or "."
                label = classes.setdefault(cls, len(classes))
                paths.append((rel, label))
        if not recursive:
            break
    if shuffle:
        random.seed(100)
        random.shuffle(paths)
    n_train = int(len(paths) * train_ratio)
    splits = [("", paths)] if train_ratio >= 1.0 else \
        [("_train", paths[:n_train]), ("_val", paths[n_train:])]
    for suffix, items in splits:
        with open(f"{prefix}{suffix}.lst", "w") as f:
            for i, (rel, label) in enumerate(items):
                f.write(f"{i}\t{label}\t{rel}\n")
    print(f"wrote {len(paths)} entries over {len(classes)} classes")


def read_list(path):
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) >= 3:
                yield int(parts[0]), float(parts[1]), parts[-1]


def pack(prefix, root, resize=0, quality=95, color=1, raw=False):
    from mxnet_tpu import recordio
    import numpy as np
    from PIL import Image

    lst = prefix + ".lst"
    if not os.path.exists(lst):
        make_list(prefix, root)
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    count = 0
    for idx, label, rel in read_list(lst):
        p = os.path.join(root, rel)
        try:
            img = Image.open(p)
            img = img.convert("RGB" if color else "L")
            if resize:
                short = min(img.size)
                scale = resize / short
                img = img.resize((max(1, round(img.size[0] * scale)),
                                  max(1, round(img.size[1] * scale))))
            header = recordio.IRHeader(0, label, idx, 0)
            # --raw: store pre-decoded uint8 pixels — the loader then does
            # memcpy+crop instead of JPEG decode (pack with --resize to
            # bound record size; bytes-for-CPU trade for TPU feed rate)
            fmt = ".raw" if raw else ".jpg"
            rec.write_idx(idx, recordio.pack_img(
                header, np.asarray(img), quality=quality, img_fmt=fmt))
            count += 1
        except Exception as e:  # noqa: BLE001 - skip bad images like the ref
            print(f"skipping {p}: {e}", file=sys.stderr)
    rec.close()
    print(f"packed {count} images into {prefix}.rec")


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("prefix")
    p.add_argument("root")
    p.add_argument("--list", action="store_true", help="generate .lst only")
    p.add_argument("--resize", type=int, default=0)
    p.add_argument("--quality", type=int, default=95)
    p.add_argument("--train-ratio", type=float, default=1.0)
    p.add_argument("--no-shuffle", action="store_true")
    p.add_argument("--gray", action="store_true")
    p.add_argument("--raw", action="store_true",
                   help="store pre-decoded uint8 pixels (pair with --resize)")
    a = p.parse_args(argv)
    if a.list:
        make_list(a.prefix, a.root, train_ratio=a.train_ratio,
                  shuffle=not a.no_shuffle)
    else:
        pack(a.prefix, a.root, resize=a.resize, quality=a.quality,
             color=0 if a.gray else 1, raw=a.raw)


if __name__ == "__main__":
    main()
