"""Normalized cost reports of compiled XLA programs.

The only trustworthy performance instrument in this environment is
static analysis of the compiled program (PERF.md: the axon tunnel
memoizes executions and breaks profiler traces; ``lower().compile()``
then ``cost_analysis()`` is the methodology behind the 51.4 → 44.2 GB
traffic fix).  This module turns one compiled executable into a
*normalized report* — FLOPs, bytes accessed, compiled-buffer memory,
entry-computation instruction counts by category, donation coverage —
and merges per-executable reports into one per-entry-point record that
``budget.py`` diffs against committed goldens.

Nothing here ever executes a step: the inputs are AOT ``Lowered`` /
``Compiled`` objects (``TrainStep.lower()`` or ``jax.jit(f).lower``),
so the whole pipeline runs under ``JAX_PLATFORMS=cpu`` in tier-1.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional

#: bump when the report schema or extraction logic changes — it keys the
#: report cache AND is recorded in budget goldens, so a stale cached
#: report (or a golden from an older schema) can never pass silently
REPORT_VERSION = "1.2"

# HloModule header attribute stamped by the SPMD partitioner: how many
# devices one copy of this program spans (1 when absent — a
# single-device or replicated program)
_NUM_PARTITIONS_RE = re.compile(r"\bnum_partitions=(\d+)")

# entry-computation instruction line:  ``%name = SHAPE opcode(...)``.
# SHAPE is either a bare token (f32[8,16]{1,0}) or a tuple type — which
# contains spaces but no nested parens in optimized entry HLO.  Group 1
# is the result shape (the collective-payload accounting reads it),
# group 2 the opcode.
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*"
    r"(\([^()]*\)|\S+)\s+"
    r"([a-z][a-z0-9\-]*)\(")

# one typed buffer inside a (possibly tuple) shape: ``f32[8,16]{1,0}``
_SHAPE_TOK = re.compile(r"\b(pred|[a-z]+\d+)\[([0-9,]*)\]")

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "f8e4m3fn": 1, "f8e5m2": 1,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}


def _shape_bytes(shape_text: str) -> int:
    total = 0
    for dt, dims in _SHAPE_TOK.findall(shape_text):
        unit = _DTYPE_BYTES.get(dt)
        if unit is None:
            continue
        n = 1
        for d in dims.split(","):
            if d.strip():
                n *= int(d)
        total += unit * n
    return total

# one input/output alias entry on the HloModule header line:
# ``{0}: (5, {}, may-alias)`` — the parameter number is group 1
_ALIAS_RE = re.compile(r"\((\d+), \{\}, (?:may|must)-alias\)")

#: opcode → category.  Anything unlisted is "other"; the categories are
#: the traffic-relevant families from PERF.md's entry-computation
#: accounting table (convs, fusions, copies, collectives, ...).
_CATEGORY = {
    "convolution": "convolution",
    "dot": "dot",
    "fusion": "fusion",
    "custom-call": "custom-call",
    "all-reduce": "collective", "all-reduce-start": "collective",
    "all-reduce-done": "collective", "all-gather": "collective",
    "all-gather-start": "collective", "all-gather-done": "collective",
    "reduce-scatter": "collective", "all-to-all": "collective",
    "collective-permute": "collective",
    "collective-broadcast": "collective",
    "copy": "copy", "copy-start": "copy", "copy-done": "copy",
    "reduce": "reduce", "reduce-window": "reduce",
}
CATEGORIES = ("convolution", "dot", "fusion", "custom-call", "collective",
              "copy", "reduce", "other")


@dataclasses.dataclass
class Program:
    """One AOT-lowered program unit of an entry point (a TrainStep has
    one; a serving bucket grid has one per padded signature)."""
    name: str
    lowered: object          # jax ``Lowered``
    n_args: int              # flattened input leaf count (donation denom.)
    meta: Optional[dict] = None


def _entry_lines(hlo_text: str):
    """Lines of the ENTRY computation only — fusion subcomputations
    repeat every fused elementwise op and would drown the categories
    that matter (PERF.md counts the entry computation)."""
    inside = False
    for line in hlo_text.splitlines():
        if line.startswith("ENTRY "):
            inside = True
            continue
        if inside:
            if line.startswith("}"):
                return
            yield line


def instruction_counts(hlo_text: str) -> Dict[str, int]:
    counts = {c: 0 for c in CATEGORIES}
    total = 0
    for line in _entry_lines(hlo_text):
        m = _INSTR_RE.match(line)
        if not m:
            continue
        total += 1
        counts[_CATEGORY.get(m.group(2), "other")] += 1
    counts["total"] = total
    return counts


def collective_payload_bytes(hlo_text: str) -> int:
    """Summed result-shape bytes of the ENTRY computation's collective
    instructions — the gradient/weight *wire* traffic of the program,
    the number ISSUE 8's quantized collectives exist to shrink.  Async
    pairs count once (the ``*-start`` half is skipped; its ``-done``
    carries the payload), and a tuple-shaped result (the CPU backend's
    all-to-all form) sums its per-peer buffers."""
    total = 0
    for line in _entry_lines(hlo_text):
        m = _INSTR_RE.match(line)
        if not m:
            continue
        op = m.group(2)
        if _CATEGORY.get(op) != "collective" or op.endswith("-start"):
            continue
        total += _shape_bytes(m.group(1))
    return total


def donation_counts(hlo_text: str, n_args: int) -> Dict[str, int]:
    """Donated-parameter coverage from the ``input_output_alias`` header
    attribute: which inputs XLA actually reuses as outputs.  This is the
    *post-compile truth* — a donate_argnums entry the compiler could not
    use does not count."""
    donated = set()
    for line in hlo_text.splitlines():
        if line.startswith("HloModule"):
            donated.update(int(p) for p in _ALIAS_RE.findall(line))
            break
    return {"donated_args": len(donated), "total_args": int(n_args)}


def program_num_partitions(hlo_text: str) -> int:
    """How many devices one copy of this program spans — the SPMD
    partitioner stamps ``num_partitions=N`` on the HloModule header.
    1 when absent: a single-device (or trivially replicated) program."""
    for line in hlo_text.splitlines():
        if line.startswith("HloModule"):
            m = _NUM_PARTITIONS_RE.search(line)
            return int(m.group(1)) if m else 1
    return 1


def unit_report(compiled, n_args: int) -> dict:
    """Normalized report of ONE compiled executable.

    Post-SPMD HLO is the PER-DEVICE program: shapes are shard shapes,
    ``memory_analysis`` accounts one device's buffers.  The
    ``per_device`` section makes that semantic explicit (and budgetable
    — a sharded entry commits that these numbers scale as 1/shards),
    alongside the device count the partitioner stamped."""
    costs = compiled.cost_analysis()
    if isinstance(costs, list):
        costs = costs[0] if costs else {}
    text = compiled.as_text()
    mem = {}
    try:
        ma = compiled.memory_analysis()
        peak = (ma.argument_size_in_bytes + ma.output_size_in_bytes
                + ma.temp_size_in_bytes - ma.alias_size_in_bytes)
        mem = {"argument_bytes": int(ma.argument_size_in_bytes),
               "output_bytes": int(ma.output_size_in_bytes),
               "temp_bytes": int(ma.temp_size_in_bytes),
               "alias_bytes": int(ma.alias_size_in_bytes),
               "generated_code_bytes": int(ma.generated_code_size_in_bytes),
               "peak_bytes": int(peak)}
    except Exception:   # noqa: BLE001 — some backends can't account memory
        mem = {}        # absent, not fabricated: the diff skips it
    wire = float(collective_payload_bytes(text))
    per_device = {"n_devices": program_num_partitions(text),
                  "collective_bytes": wire}
    if mem:
        per_device["argument_bytes"] = mem["argument_bytes"]
        per_device["peak_bytes"] = mem["peak_bytes"]
    return {
        "n_executables": 1,
        "flops": float(costs.get("flops", 0.0)),
        "bytes_accessed": float(costs.get("bytes accessed", 0.0)),
        "transcendentals": float(costs.get("transcendentals", 0.0)),
        "collective_bytes": wire,
        "memory": mem,
        "per_device": per_device,
        "donation": donation_counts(text, n_args),
        "instructions": instruction_counts(text),
    }


def merge_reports(units: List[dict]) -> dict:
    """One entry-point report from its per-executable unit reports.

    Additive metrics (flops, bytes, instruction counts, donation
    counts, executable count) sum — the grid's total traffic budget.
    Memory is the **max** over units: executables run one at a time, so
    the budgetable figure is the worst single program, not a fictitious
    sum."""
    if not units:
        raise ValueError("merge_reports: no unit reports")
    out = {
        "n_executables": sum(u["n_executables"] for u in units),
        "flops": sum(u["flops"] for u in units),
        "bytes_accessed": sum(u["bytes_accessed"] for u in units),
        "transcendentals": sum(u["transcendentals"] for u in units),
        "collective_bytes": sum(u.get("collective_bytes", 0.0)
                                for u in units),
        "memory": {},
        "donation": {
            "donated_args": sum(u["donation"]["donated_args"]
                                for u in units),
            "total_args": sum(u["donation"]["total_args"] for u in units),
        },
        "instructions": {
            k: sum(u["instructions"].get(k, 0) for u in units)
            for k in CATEGORIES + ("total",)
        },
    }
    mems = [u["memory"] for u in units if u["memory"]]
    if mems:
        out["memory"] = {k: max(m.get(k, 0) for m in mems)
                         for k in mems[0]}
    # per-device numbers merge like memory: executables run one at a
    # time, so the budgetable per-device figure is the worst single
    # program on one device, not a sum across the grid
    pds = [u.get("per_device") for u in units]
    pds = [p for p in pds if p]
    if pds:
        # key UNION, not pds[0]'s keys: one unit whose memory_analysis
        # failed (its per_device carries only n_devices+collective)
        # must not silently un-gate the byte metrics the others report
        keys = set().union(*(p.keys() for p in pds))
        out["per_device"] = {k: max(p.get(k, 0) for p in pds)
                             for k in sorted(keys)}
    return out


def report_for_programs(programs: List[Program], root=None,
                        use_cache: bool = False, cache_dir=None) -> dict:
    """Compile each program unit (or hit the report cache) and merge.

    The cache key is a hash of the **lowered HLO text** — any change to
    the model, the step plumbing, or jax itself changes the text, so a
    cached report can never go stale against the code (the same
    soundness argument as mxlint's content-hash cache, one level up the
    stack: lowering is cheap and always runs; only the expensive
    XLA compile + extraction is memoized).  ``.costguard_cache/`` under
    ``root``; writes are atomic and best-effort."""
    import jax

    cache = None
    if use_cache and root is not None:
        from pathlib import Path

        from tools.analysis.cache import FileCache
        sig = (f"costguard-{REPORT_VERSION}-jax{jax.__version__}-"
               f"{jax.default_backend()}-{jax.device_count()}d")
        cache = FileCache(Path(root),
                          cache_dir or Path(root) / ".costguard_cache",
                          signature=sig)
    units = []
    for prog in programs:
        text = prog.lowered.as_text()
        key = rec = None
        if cache is not None:
            key = cache.key(prog.name, text.encode("utf-8"))
            rec = cache.get(prog.name, key)
        if rec is not None:
            units.append(rec["report"])
            continue
        u = unit_report(prog.lowered.compile(), prog.n_args)
        units.append(u)
        if cache is not None:
            cache.put(prog.name, key, {"relpath": prog.name, "report": u})
    return merge_reports(units)
