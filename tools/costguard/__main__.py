"""costguard CLI: ``python -m tools.costguard [target ...]``.

Exit code 0 = every selected entry point within budget (and no stale
goldens), 1 = regression / missing budget / census mismatch, 2 = usage.

Targets are entry-point names, or paths — a path selects every
registered entry point whose builder is defined under it, so the
documented gate invocation ``python -m tools.costguard mxnet_tpu/``
(the builders live in ``tools/costguard``, which models the mxnet_tpu
zoo — path targets also match the models' own package) audits the whole
registered surface.  No target = everything.

Environment: forces ``JAX_PLATFORMS=cpu`` with an 8-device virtual mesh
unless the caller already chose a platform — budgets are recorded
against exactly this bring-up (same as tests/conftest.py), and goldens
only *gate* in a matching backend/device-count environment.
"""
from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path


def _env_bringup():
    """Same pre-jax-import bring-up as tests/conftest.py — must run
    before anything imports jax."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if os.environ["JAX_PLATFORMS"] == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.costguard",
        description="compiled-program cost budgets + recompile audit "
                    "(docs/analysis.md \"Cost budgets\")")
    parser.add_argument("targets", nargs="*", default=[],
                        help="entry-point names and/or paths (a path "
                             "selects the entries defined under it); "
                             "default: every registered entry point")
    parser.add_argument("--format", choices=("human", "json"),
                        default="human", dest="fmt")
    parser.add_argument("--list", action="store_true",
                        help="list registered entry points and exit")
    parser.add_argument("--root", default=None,
                        help="repo root for goldens/cache (default: cwd)")
    parser.add_argument("--no-cache", action="store_true",
                        help="disable the .costguard_cache/ report cache "
                             "(always recompile)")
    parser.add_argument("--cache-dir", default=None,
                        help="cache directory (default: "
                             "<root>/.costguard_cache)")
    args = parser.parse_args(argv)

    _env_bringup()
    from . import entrypoints, run_check

    if args.list:
        for name in entrypoints.names():
            doc = (entrypoints._REGISTRY[name].__doc__ or "").strip()
            print(f"{name:24s} {doc.splitlines()[0] if doc else ''}")
        return 0

    root = Path(args.root).resolve() if args.root else Path.cwd()
    selected = []
    for t in args.targets:
        if t in entrypoints.names():
            selected.append(t)
            continue
        p = Path(t)
        if p.exists():
            rp = p.resolve()
            hits = [n for n in entrypoints.names()
                    if _selects_entry(n, rp, root)]
            selected.extend(h for h in hits if h not in selected)
            if not hits:
                print(f"# note: no registered entry point under {t}",
                      file=sys.stderr)
            continue
        parser.error(f"{t!r} is neither a registered entry point nor a "
                     f"path (see --list)")
    if args.targets and not selected:
        # nothing to build, but the reverse check (orphaned goldens) is
        # selection-independent and still part of the exit-0 contract
        print("costguard: no registered entry points under the given "
              "targets — auditing goldens only", file=sys.stderr)
    result = run_check(entries=selected if args.targets else None,
                       root=root, use_cache=not args.no_cache,
                       cache_dir=args.cache_dir)
    if args.fmt == "json":
        print(result.to_json())
    else:
        print(result.render())
    return 0 if result.ok else 1


def _selects_entry(name: str, path: Path, root: Path) -> bool:
    """Does a path target cover entry ``name``?  Either the entry's own
    builder file is under the path, or the path contains the mxnet_tpu
    package — ``python -m tools.costguard mxnet_tpu/`` must audit the
    zoo entries even though the builder FILES live in tools/ (every
    registered entry budgets that package's models)."""
    from .entrypoints import source_of
    if source_of(name).is_relative_to(path):
        return True
    pkg = (root / "mxnet_tpu").resolve()
    return pkg == path or pkg.is_relative_to(path)


if __name__ == "__main__":
    sys.exit(main())
