"""Budgeted entry points: the named model/step/serving configurations
whose compiled-program costs are committed as goldens.

Each entry point is a builder that LOWERS its program(s) without ever
executing a step (``TrainStep.lower(sample)`` / ``jax.jit(f).lower``),
so budgets compute under ``JAX_PLATFORMS=cpu`` in tier-1.  Registration
is the budget *contract*: mxlint's ``unbudgeted-entrypoint`` rule fails
the gate when a registered name has no golden under
``tests/goldens/budgets/``, and the costguard CLI fails on goldens whose
registration disappeared — the two directions of "every audited surface
stays audited".

CPU-vs-TPU caveat (PERF.md): byte counts from the CPU backend are not
comparable to TPU's.  Goldens record their backend + device count and
are only *gated* in a matching environment; a TPU run of the same entry
points is an audit, not a gate.
"""
from __future__ import annotations

import dataclasses
import inspect
from pathlib import Path
from typing import Callable, Dict, List

from .census import executable_census, grid_signatures
from .report import Program

_REGISTRY: Dict[str, Callable] = {}


@dataclasses.dataclass
class EntryBuild:
    """What a builder returns: the lowered program units, the static
    executable census, and the metadata the golden records."""
    name: str
    meta: dict
    programs: List[Program]
    census: int


def entrypoint(name: str):
    """Register a budgeted entry point (decorator).  The literal name is
    what mxlint's ``unbudgeted-entrypoint`` facts extract — keep it a
    string literal, and matching ``tests/goldens/budgets/<name>.json``."""
    def deco(fn):
        if name in _REGISTRY:
            raise ValueError(f"entrypoint {name!r} registered twice")
        _REGISTRY[name] = fn
        fn.entrypoint_name = name
        return fn
    return deco


def names() -> List[str]:
    return sorted(_REGISTRY)


def build(name: str, **overrides) -> EntryBuild:
    if name not in _REGISTRY:
        raise KeyError(f"unknown entry point {name!r} "
                       f"(registered: {names()})")
    import time
    t0 = time.perf_counter()
    eb = _REGISTRY[name](**overrides)
    _note_compile_events(eb, (time.perf_counter() - t0) * 1e3)
    return eb


def _note_compile_events(eb: EntryBuild, total_ms: float) -> None:
    """ISSUE 15: the costguard builders are one of the compile paths the
    telemetry compile-event stream covers — one event per lowered
    program unit at site ``costguard::<entry>``, so
    ``sum(events) == the entry's census`` holds here exactly like it
    does for the runtime jit caches.  No-op while the tracer is dark;
    never fails a build."""
    try:
        from mxnet_tpu import telemetry
        if not telemetry.ACTIVE:
            return
        per_ms = round(total_ms / max(1, len(eb.programs)), 3)
        for prog in eb.programs:
            telemetry.compile_event(f"costguard::{eb.name}",
                                    key=prog.name, ms=per_ms)
    except Exception:  # noqa: BLE001 — observability never fails a build
        pass


def source_of(name: str) -> Path:
    """The file defining an entry point's builder — what lets the CLI
    map a path argument (``python -m tools.costguard mxnet_tpu/``) onto
    the entry points whose models live under it."""
    fn = _REGISTRY[name]
    return Path(inspect.getsourcefile(fn)).resolve()


def _mesh_and_opt(opt_name="sgd", dp=None, **opt_kw):
    """Default: every visible device on one ``dp`` axis.  ``dp=N`` pins
    the mesh to the first N devices — the 1-device CONTROL of the
    per-device-scaling golden pairs uses ``dp=1``."""
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import parallel
    if dp is None:
        mesh = parallel.make_mesh(dp=-1)
    else:
        mesh = parallel.make_mesh(dp=dp, devices=jax.devices()[:dp])
    return mesh, mx.optimizer.create(opt_name, **opt_kw)


def resnet50_train_step(batch=8, fused=False, layout="NHWC",
                        grad_reduce="f32"):
    """The headline ResNet-50 train step, AOT only — shared by the
    ``resnet50_nhwc_train`` budget entry and ``benchmark/hlo_costs.py``
    (which parameterizes batch/fused for the fused-conv A/B).  Returns
    ``(step, x, y)`` with the sample batch as HOST arrays: nothing is
    placed or executed until the caller decides."""
    import ml_dtypes
    import numpy as np

    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1

    net = resnet50_v1(layout=layout, fused=fused)
    net.initialize()
    net.cast("bfloat16")
    mesh, opt = _mesh_and_opt("sgd", learning_rate=0.1, momentum=0.9,
                              wd=1e-4)
    step = parallel.TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                              opt, mesh=mesh, grad_reduce=grad_reduce)
    x = np.zeros((batch, 224, 224, 3), ml_dtypes.bfloat16)
    y = np.zeros((batch,), np.int32)
    return step, x, y


def _train_step_build(name, step, x, y, meta) -> EntryBuild:
    import jax

    lowered = step.lower(x, y)
    n_args = len(jax.tree.leaves(step._last_avals))
    meta = dict(meta, backend_note=(
        "CPU-backend byte counts are NOT comparable to TPU's (PERF.md); "
        "this golden gates the compile boundary, not on-chip traffic"))
    return EntryBuild(name=name, meta=meta, census=executable_census(step),
                      programs=[Program(name, lowered, n_args)])


@entrypoint("resnet50_nhwc_train")
def build_resnet50_nhwc_train(batch=8):
    """ResNet-50 v1 NHWC bf16 train step (fwd+bwd+SGD momentum, one XLA
    program on the dp mesh) — the PERF.md headline workload."""
    step, x, y = resnet50_train_step(batch=batch)
    return _train_step_build(
        "resnet50_nhwc_train", step, x, y,
        {"model": "resnet50_v1", "layout": "NHWC", "dtype": "bfloat16",
         "precision": "bf16", "batch": batch,
         "optimizer": "sgd(momentum=0.9, wd=1e-4)", "sharded": True})


def _mnist_mlp_step(batch=64, dtype="float32", grad_reduce="f32",
                    dp=None):
    """The examples/train_mnist_mlp.py recipe: 784-128-10 MLP train
    step, f32, SGD momentum — shared by the f32 entry, its
    ``grad_reduce="int8"`` sibling (same model, same sample batch, so
    the two goldens diff leaf-for-leaf), and the ``dp=1`` unsharded
    control of the per-device-scaling pair."""
    import ml_dtypes
    import numpy as np

    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Dense(128, activation="relu", in_units=784),
            nn.Dense(10, in_units=128))
    net.initialize()
    if dtype != "float32":
        net.cast(dtype)
    mesh, opt = _mesh_and_opt("sgd", dp=dp, learning_rate=0.1,
                              momentum=0.9)
    step = parallel.TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                              opt, mesh=mesh, grad_reduce=grad_reduce)
    np_dtype = ml_dtypes.bfloat16 if dtype == "bfloat16" else np.float32
    x = np.zeros((batch, 784), np_dtype)
    y = np.zeros((batch,), np.int32)
    return step, x, y


@entrypoint("mnist_mlp_train")
def build_mnist_mlp_train(batch=64, dtype="float32"):
    step, x, y = _mnist_mlp_step(batch=batch, dtype=dtype)
    return _train_step_build(
        "mnist_mlp_train", step, x, y,
        {"model": "mlp 784-128-10", "dtype": dtype, "precision": "f32",
         "batch": batch, "optimizer": "sgd(momentum=0.9)", "sharded": True,
         "dp_shards": int(step.mesh.devices.size)})


@entrypoint("mnist_mlp_train_dp1")
def build_mnist_mlp_train_dp1(batch=64, dtype="float32"):
    """``mnist_mlp_train`` pinned to a 1-device ``dp`` mesh: the
    UNSHARDED control of the dp per-device-scaling pair.  The committed
    contract — asserted by tests/test_costguard.py::
    test_dp_sharded_per_device_byte_budget — is that the dp=8 entry's
    per-device ``argument_bytes`` drop by ~7/8 of the batch bytes vs
    this control (params are replicated on a pure-dp mesh, so ONLY the
    batch shard scales — exactly what "per-device bytes ∝ 1/shards for
    the sharded tensors" means here)."""
    step, x, y = _mnist_mlp_step(batch=batch, dtype=dtype, dp=1)
    return _train_step_build(
        "mnist_mlp_train_dp1", step, x, y,
        {"model": "mlp 784-128-10", "dtype": dtype, "precision": "f32",
         "batch": batch, "optimizer": "sgd(momentum=0.9)",
         "sharded": False, "dp_shards": 1})


@entrypoint("mnist_mlp_train_gradq_int8")
def build_mnist_mlp_train_gradq_int8(batch=64, dtype="float32"):
    """``mnist_mlp_train`` with ``grad_reduce="int8"``: the explicit
    shard_map gradient-reduction stage (quantize → all_to_all /
    all_gather of int8 payloads → dequantize) replacing the implicit
    f32 all-reduce.  The committed contract vs the f32 golden —
    asserted by tests/test_costguard.py::test_gradq_int8_collective_
    byte_budget — is >= 25% fewer ``collective_bytes``.  NB on the CPU
    backend ``bytes_accessed``/``flops`` go UP (int8 + stochastic
    rounding are emulated); the wire payload is what this entry
    budgets.  (ResNet-50 was measured too: its master grads are
    already bf16, so the int8 modeled-payload win there is marginal —
    the f32-gradient MLP is the honest A/B.)"""
    step, x, y = _mnist_mlp_step(batch=batch, dtype=dtype,
                                 grad_reduce="int8")
    return _train_step_build(
        "mnist_mlp_train_gradq_int8", step, x, y,
        {"model": "mlp 784-128-10", "dtype": dtype, "precision": "int8",
         "batch": batch, "optimizer": "sgd(momentum=0.9)",
         "grad_reduce": "int8", "sharded": True})


def _serving_mlp_grid_build(name, batch_buckets, length_buckets, features,
                            dtype, quantize):
    """One jitted MLP apply lowered at EVERY padded (batch, length)
    signature the ``BucketSpec`` admits — the whole executable space an
    ``InferenceServer`` on this spec can ever compile.  The params are
    ARGUMENTS of the jitted fn (the ``fleet.HotSwapApply`` serving
    shape: a weight update is a pointer swap), so the compiled weight
    buffer is visible in ``memory.argument_bytes`` — the metric the
    int8 variant commits a >= 25% reduction on.  n_executables in the
    golden == the static census == the runtime jit-cache count
    (tests/test_serving.py, tests/test_quantize.py)."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.serving import BucketSpec

    spec = BucketSpec(batch=batch_buckets, length=length_buckets)
    hidden, out = 64, 16
    dt = jnp.dtype(dtype)
    params = [jnp.zeros((features, hidden), dt), jnp.zeros((hidden,), dt),
              jnp.zeros((hidden, out), dt), jnp.zeros((out,), dt)]

    def fwd(p, x):                     # x: (batch, length, features)
        h = jnp.tanh(x @ p[0] + p[1])
        return h @ p[2] + p[3]

    meta = {"model": f"mlp {features}-{hidden}-{out} apply",
            "dtype": dtype, "precision": "int8" if quantize else "f32",
            "batch_buckets": list(spec.batch),
            "length_buckets": list(spec.length)}
    if quantize:
        # the int8 serving shape: per-channel PTQ payload/scale pairs as
        # the compiled program's weight arguments, dequant folded inside
        from mxnet_tpu.amp import Int8Quantizer
        quantizer = Int8Quantizer(axis=1)      # x @ w: out-features last
        params = quantizer.quantize(params)
        apply = jax.jit(quantizer.wrap(fwd))
        meta["weights"] = "int8 per-channel PTQ (amp.Int8Quantizer)"
    else:
        apply = jax.jit(fwd)
    p_avals = [jax.ShapeDtypeStruct(p.shape, p.dtype) for p in params]
    programs = []
    for b, L in grid_signatures(spec):
        aval = jax.ShapeDtypeStruct((b, L, features), dt)
        # mxlint: disable=jit-in-loop -- this loop IS the census: one
        # lower per bucket signature, bounded by the static grid, and
        # the expensive compile is memoized by the report cache
        lowered = apply.lower(p_avals, aval)
        programs.append(Program(f"{name}/b{b}_l{L}", lowered,
                                n_args=len(params) + 1))
    return EntryBuild(name=name, meta=meta, programs=programs,
                      census=executable_census(spec))


def _llm_parts(vocab=256, n_layers=2, n_heads=8, head_dim=4, d_ff=64,
               n_slots=8, n_pages=64, page_size=16, pages_per_seq=16):
    """Shared pieces of the LLM serving entry points: the tiny causal
    LM's param avals (``jax.eval_shape`` — zero device work) and the
    fixed decode-grid geometry.  ``n_pages * page_size`` (1024 cache
    tokens) is HALF of ``n_slots * pages_per_seq * page_size`` (2048) —
    the pool is deliberately oversubscribed 2:1 against the worst case,
    which is exactly the HBM the paged design reclaims and the
    ``llm_decode_step`` vs ``llm_decode_step_dense`` golden pair
    commits (>= 40% fewer decode-step argument bytes, gated by
    tests/test_costguard.py::test_llm_paged_kv_byte_budget).  The head
    layout is 8 heads x 4 (``d_model`` 32, same pool bytes as the
    original 2 x 16) so ``llm_decode_step_tp8`` shards the IDENTICAL
    model/geometry 8 ways — the tp pair diffs like-for-like."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.gluon.model_zoo.causal_lm import (CausalLMConfig,
                                                     init_causal_lm)

    cfg = CausalLMConfig(vocab_size=vocab, n_layers=n_layers,
                         n_heads=n_heads, head_dim=head_dim, d_ff=d_ff)
    p_avals = jax.eval_shape(lambda: init_causal_lm(cfg, 0))
    geom = {"n_slots": n_slots, "n_pages": n_pages,
            "page_size": page_size, "pages_per_seq": pages_per_seq,
            "max_context": pages_per_seq * page_size}
    sds = jax.ShapeDtypeStruct
    slot_avals = {
        "tokens": sds((n_slots,), jnp.int32),
        "lengths": sds((n_slots,), jnp.int32),
        "active": sds((n_slots,), jnp.bool_),
        "tables": sds((n_slots, pages_per_seq), jnp.int32),
        "cow_src": sds((n_slots,), jnp.int32),
        "cow_dst": sds((n_slots,), jnp.int32),
        "seeds": sds((n_slots,), jnp.uint32),
        "temps": sds((n_slots,), jnp.float32),
        "topks": sds((n_slots,), jnp.int32),
    }
    return cfg, p_avals, geom, slot_avals


def _n_leaves(*trees):
    import jax
    return sum(len(jax.tree.leaves(t)) for t in trees)


@entrypoint("llm_decode_step")
def build_llm_decode_step():
    """THE continuous-batching decode executable (serving/generate.py):
    one token for every in-flight sequence over the fixed slot grid,
    K/V held in the shared paged pool addressed by page tables.  Its
    census is 1 by construction — every traffic mix runs this program —
    and its ``memory.argument_bytes`` is the paged-KV headline the
    golden pair below commits."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.serving.generate import build_decode_step

    cfg, p_avals, g, s = _llm_parts()
    pool = jax.ShapeDtypeStruct(
        (cfg.n_layers, g["n_pages"], g["page_size"], cfg.n_heads,
         cfg.head_dim), jnp.float32)
    step = jax.jit(build_decode_step(cfg, g["page_size"], "jnp"),
                   donate_argnums=(1, 2))
    lowered = step.lower(p_avals, pool, pool, s["tokens"], s["lengths"],
                         s["active"], s["tables"], s["cow_src"],
                         s["cow_dst"], s["seeds"], s["temps"], s["topks"])
    n_args = _n_leaves(p_avals) + 2 + 9
    meta = {"model": f"causal_lm {cfg.vocab_size}v {cfg.n_layers}L "
                     f"{cfg.n_heads}h{cfg.head_dim}", "kv": "paged",
            "precision": "f32", **g}
    return EntryBuild(name="llm_decode_step", meta=meta, census=1,
                      programs=[Program("llm_decode_step", lowered,
                                        n_args)])


def _llm_decode_step_tp(name, collectives, shards=8):
    """Shared builder of the tensor-parallel decode entries (ISSUE 14):
    the IDENTICAL model, pool geometry, and slot grid as
    ``llm_decode_step``, lowered ONCE over a tp mesh — head-sharded
    pools, Megatron column/row weights, per-layer activation
    all-reduces in the ``collectives`` wire format.  Census stays 1:
    sharding is a lowering property, not a new executable."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu import parallel
    from mxnet_tpu.serving.generate import build_decode_step

    cfg, p_avals, g, s = _llm_parts()
    mesh = parallel.make_mesh(tp=shards, devices=jax.devices()[:shards])
    pool = jax.ShapeDtypeStruct(
        (cfg.n_layers, g["n_pages"], g["page_size"], cfg.n_heads,
         cfg.head_dim), jnp.float32)
    step = jax.jit(build_decode_step(cfg, g["page_size"], "jnp",
                                     mesh=mesh, tp_collectives=collectives),
                   donate_argnums=(1, 2))
    lowered = step.lower(p_avals, pool, pool, s["tokens"], s["lengths"],
                         s["active"], s["tables"], s["cow_src"],
                         s["cow_dst"], s["seeds"], s["temps"], s["topks"])
    n_args = _n_leaves(p_avals) + 2 + 9
    meta = {"model": f"causal_lm {cfg.vocab_size}v {cfg.n_layers}L "
                     f"{cfg.n_heads}h{cfg.head_dim}", "kv": "paged",
            "precision": "int8" if collectives == "int8" else "f32",
            "sharded": True, "tp_shards": shards,
            "tp_collectives": collectives, **g}
    return EntryBuild(name=name, meta=meta, census=1,
                      programs=[Program(name, lowered, n_args)])


@entrypoint("llm_decode_step_tp8")
def build_llm_decode_step_tp8():
    """The tensor-parallel decode executable at tp=8, f32 collectives:
    head-parallel paged attention (each device owns 1 of 8 head shards
    of BOTH pools) + column/row-sharded projections/FFN with the two
    Megatron all-reduces per layer.  The committed contract vs
    ``llm_decode_step`` — asserted by tests/test_costguard.py::
    test_tp_sharded_decode_per_device_pool_byte_budget — is per-device
    ``argument_bytes`` down by 7/8 of the pool + sharded weight bytes
    (±2%): per-device KV-pool HBM ∝ 1/shards, the ISSUE 14 headline."""
    return _llm_decode_step_tp("llm_decode_step_tp8", "f32")


@entrypoint("llm_decode_step_tp8_q8")
def build_llm_decode_step_tp8_q8():
    """``llm_decode_step_tp8`` with ``tp_collectives="int8"``: the
    per-layer activation all-reduces run through the chunked int8
    quantize/all_to_all/all_gather machinery (parallel.quantize, the
    EQuARX trade — decode is latency-bound on collective bytes).  The
    committed contract vs the f32 sibling — asserted by
    tests/test_costguard.py::test_tp_decode_int8_collective_byte_budget
    — is >= 25% fewer per-device ``collective_bytes`` over the same
    model, mesh, and executable census."""
    return _llm_decode_step_tp("llm_decode_step_tp8_q8", "int8")


@entrypoint("llm_decode_step_dense")
def build_llm_decode_step_dense():
    """The dense max-length-cache decode variant: identical model, slot
    grid, and sampling, but every slot owns a full ``max_context``
    cache stripe.  Committed as the golden the paged entry is diffed
    against — the pair IS the structural-HBM-win regression floor
    (PR 8 pattern: the win itself is gated, not just each side)."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.serving.generate import build_dense_decode_step

    cfg, p_avals, g, s = _llm_parts()
    cache = jax.ShapeDtypeStruct(
        (cfg.n_layers, g["n_slots"], g["max_context"], cfg.n_heads,
         cfg.head_dim), jnp.float32)
    step = jax.jit(build_dense_decode_step(cfg, g["max_context"]),
                   donate_argnums=(1, 2))
    lowered = step.lower(p_avals, cache, cache, s["tokens"], s["lengths"],
                         s["active"], s["seeds"], s["temps"], s["topks"])
    n_args = _n_leaves(p_avals) + 2 + 6
    meta = {"model": f"causal_lm {cfg.vocab_size}v {cfg.n_layers}L "
                     f"{cfg.n_heads}h{cfg.head_dim}",
            "kv": "dense max-length", "precision": "f32", **g}
    return EntryBuild(name="llm_decode_step_dense", meta=meta, census=1,
                      programs=[Program("llm_decode_step_dense", lowered,
                                        n_args)])


@entrypoint("llm_verify_step")
def build_llm_verify_step(spec_k=3, spec_window=16):
    """THE speculative-decoding verify executable (ISSUE 16): the draft
    LM proposes ``spec_k`` tokens per slot and the target model scores
    all ``spec_k + 1`` flattened lanes in this ONE program — the census
    of a speculative server is the non-speculative census plus exactly
    this entry.  Draft params ride along as ordinary arguments (a
    1-layer sibling of the target config, same vocab), so
    ``argument_bytes`` prices the full speculation tax."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.gluon.model_zoo.causal_lm import (draft_config,
                                                     init_causal_lm)
    from mxnet_tpu.serving.generate import build_verify_step

    cfg, p_avals, g, s = _llm_parts()
    dcfg = draft_config(cfg, n_layers=1)
    d_avals = jax.eval_shape(lambda: init_causal_lm(dcfg, 0))
    pool = jax.ShapeDtypeStruct(
        (cfg.n_layers, g["n_pages"], g["page_size"], cfg.n_heads,
         cfg.head_dim), jnp.float32)
    sds = jax.ShapeDtypeStruct
    step = jax.jit(build_verify_step(cfg, dcfg, g["page_size"], spec_k,
                                     spec_window, "jnp"),
                   donate_argnums=(2, 3))
    lowered = step.lower(
        p_avals, d_avals, pool, pool, s["tokens"],
        sds((g["n_slots"], spec_window), jnp.int32),
        sds((g["n_slots"],), jnp.int32), s["lengths"], s["active"],
        s["tables"], s["cow_src"], s["cow_dst"], s["seeds"], s["temps"],
        s["topks"])
    n_args = _n_leaves(p_avals, d_avals) + 2 + 11
    meta = {"model": f"causal_lm {cfg.vocab_size}v {cfg.n_layers}L "
                     f"{cfg.n_heads}h{cfg.head_dim}",
            "draft": f"causal_lm {dcfg.vocab_size}v {dcfg.n_layers}L "
                     f"{dcfg.n_heads}h{dcfg.head_dim}",
            "kv": "paged", "precision": "f32", "spec_k": spec_k,
            "spec_window": spec_window, **g}
    return EntryBuild(name="llm_verify_step", meta=meta, census=1,
                      programs=[Program("llm_verify_step", lowered,
                                        n_args)])


def _llm_admission(name, n_pages, shared_prefix_len, prompt_len=192,
                   max_new=64):
    """Shared builder of the prefix-sharing admission golden pair: the
    IDENTICAL decode program and slot grid, lowered over a pool sized
    to admit the same worst-case traffic with and without CoW prefix
    sharing.  Admission charges only NON-shared pages
    (``prefix_admission_plan``), so at a 90%-shared prefix the shared
    pool shrinks to sink + one resident prefix + charged pages per
    slot — the committed ``argument_bytes`` gap IS the
    page-bytes-per-sequence win, and the plan in ``meta`` pins the
    >= 2x admissible-concurrency multiplier at fixed pool size."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.serving.generate import (build_decode_step,
                                            prefix_admission_plan)

    cfg, p_avals, g, s = _llm_parts(n_pages=n_pages)
    plan = prefix_admission_plan(n_pages, g["page_size"], prompt_len,
                                 max_new, shared_prefix_len)
    pool = jax.ShapeDtypeStruct(
        (cfg.n_layers, g["n_pages"], g["page_size"], cfg.n_heads,
         cfg.head_dim), jnp.float32)
    step = jax.jit(build_decode_step(cfg, g["page_size"], "jnp"),
                   donate_argnums=(1, 2))
    lowered = step.lower(p_avals, pool, pool, s["tokens"], s["lengths"],
                         s["active"], s["tables"], s["cow_src"],
                         s["cow_dst"], s["seeds"], s["temps"], s["topks"])
    n_args = _n_leaves(p_avals) + 2 + 9
    meta = {"model": f"causal_lm {cfg.vocab_size}v {cfg.n_layers}L "
                     f"{cfg.n_heads}h{cfg.head_dim}", "kv": "paged",
            "precision": "f32", "prompt_len": prompt_len,
            "max_new": max_new,
            "shared_prefix_len": shared_prefix_len, **plan, **g}
    return EntryBuild(name=name, meta=meta, census=1,
                      programs=[Program(name, lowered, n_args)])


@entrypoint("llm_admission_unshared")
def build_llm_admission_unshared():
    """Unshared admission baseline: every sequence is charged its full
    worst case (16 pages: 192-token prompt + 64 new at page_size 16),
    so the 8-slot grid needs a 128-page pool (n_pages 129 with the
    sink).  ``meta.admissible_unshared`` = 8."""
    return _llm_admission("llm_admission_unshared", n_pages=129,
                          shared_prefix_len=176)


@entrypoint("llm_admission_shared")
def build_llm_admission_shared():
    """The 90%-shared-prefix sibling: 176 of 192 prompt tokens are a
    common system prefix (11 full pages resident ONCE), so admission
    charges 5 pages per sequence and the same 8-slot worst case fits in
    sink + 16 + 7x5 = 52 pages.  Diffed against
    ``llm_admission_unshared`` by tests/test_costguard.py — the
    committed floors are argument-bytes ratio and the >= 2x
    admissible-concurrency multiplier at the FIXED 128-page pool
    (``prefix_admission_plan(129, 16, 192, 64, 176)`` admits 23 shared
    vs 8 unshared)."""
    return _llm_admission("llm_admission_shared", n_pages=52,
                          shared_prefix_len=176)


@entrypoint("llm_prefill_grid")
def build_llm_prefill_grid(batch_buckets=(1, 2), length_buckets=(32, 64)):
    """The prompt-prefill side of the LLM serving census: ONE jitted
    prefill program lowered at every padded (batch, length) bucket the
    ``GenerationServer``'s BucketSpec admits.  Together with
    ``llm_decode_step`` this is the ENTIRE executable space of the
    serving loop — runtime jit caches are asserted equal to this census
    under mixed-length traffic in tests/test_generate.py."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.serving import BucketSpec
    from mxnet_tpu.serving.generate import build_prefill_step

    cfg, p_avals, g, s = _llm_parts()
    spec = BucketSpec(batch=batch_buckets, length=length_buckets)
    pool = jax.ShapeDtypeStruct(
        (cfg.n_layers, g["n_pages"], g["page_size"], cfg.n_heads,
         cfg.head_dim), jnp.float32)
    step = jax.jit(build_prefill_step(cfg, g["page_size"]),
                   donate_argnums=(1, 2))
    sds = jax.ShapeDtypeStruct
    programs = []
    for b, L in grid_signatures(spec):
        # mxlint: disable=jit-in-loop -- this loop IS the census: one
        # lower per bucket signature, bounded by the static grid, and
        # the expensive compile is memoized by the report cache
        lowered = step.lower(
            p_avals, pool, pool, sds((b, L), jnp.int32),
            sds((b,), jnp.int32), sds((b,), jnp.bool_),
            sds((b, g["pages_per_seq"]), jnp.int32),
            sds((b,), jnp.uint32),
            sds((b,), jnp.float32), sds((b,), jnp.int32))
        programs.append(Program(f"llm_prefill_grid/b{b}_l{L}", lowered,
                                n_args=_n_leaves(p_avals) + 2 + 7))
    meta = {"model": f"causal_lm {cfg.vocab_size}v {cfg.n_layers}L "
                     f"{cfg.n_heads}h{cfg.head_dim}",
            "precision": "f32", "batch_buckets": list(spec.batch),
            "length_buckets": list(spec.length), **g}
    return EntryBuild(name="llm_prefill_grid", meta=meta,
                      programs=programs,
                      census=executable_census(spec))


@entrypoint("serving_mlp_grid")
def build_serving_mlp_grid(batch_buckets=(1, 2, 4), length_buckets=(8, 16),
                           features=32, dtype="float32"):
    """The f32 serving bucket grid (see ``_serving_mlp_grid_build``).
    NB the dtype knob exists for on-TPU audits (bf16 serving, ROADMAP
    item 2), but the committed golden is f32: on the CPU backend bf16
    compute is EMULATED via converts and *costs* bytes rather than
    saving them — the PERF.md caveat, visible in the numbers."""
    return _serving_mlp_grid_build("serving_mlp_grid", batch_buckets,
                                   length_buckets, features, dtype,
                                   quantize=False)


def tp_mlp_apply(shards, features=256, hidden=1024, batch=8):
    """The tensor-parallel MLP apply the TP golden pair budgets — and
    the exact collective shape ROADMAP item 1's sharded FFN uses:
    ``w1`` column-sharded over ``tp`` (hidden split), ``w2``
    row-sharded (the partial products), one all-reduce restoring the
    replicated output — the standard two-collective-per-layer Megatron
    layout collapsed to its one-layer core.  Returns ``(apply, avals,
    mesh)`` with the jitted apply carrying the shardings, so tests can
    EXECUTE it (census == runtime jit-cache proof) while the entry
    points only lower it."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from mxnet_tpu import parallel

    mesh = parallel.make_mesh(tp=shards, devices=jax.devices()[:shards])

    def fwd(w1, b1, w2, b2, x):
        h = jax.nn.gelu(x @ w1 + b1)
        return h @ w2 + b2

    def sh(*spec):
        return NamedSharding(mesh, PartitionSpec(*spec))

    apply = jax.jit(fwd,
                    in_shardings=(sh(None, "tp"), sh("tp"),
                                  sh("tp", None), sh(), sh()),
                    out_shardings=sh())
    avals = [jax.ShapeDtypeStruct(s, jnp.float32)
             for s in ((features, hidden), (hidden,),
                       (hidden, features), (features,),
                       (batch, features))]
    return apply, avals, mesh


def _tp_mlp_build(name, shards, features=256, hidden=1024, batch=8):
    apply, avals, _mesh = tp_mlp_apply(shards, features=features,
                                       hidden=hidden, batch=batch)
    lowered = apply.lower(*avals)
    meta = {"model": f"mlp {features}-{hidden}-{features} apply",
            "dtype": "float32", "precision": "f32", "batch": batch,
            "tp_shards": shards, "sharded": shards > 1,
            "layout": "w1 column-sharded / w2 row-sharded over tp; "
                      "activations replicated; one all-reduce on the "
                      "output"}
    return EntryBuild(name=name, meta=meta, census=1,
                      programs=[Program(name, lowered, n_args=5)])


@entrypoint("mlp_apply_tp8")
def build_mlp_apply_tp8(shards=8):
    """Tensor-parallel (tp=8) MLP apply: weights sharded column/row over
    the mesh, output restored by ONE all-reduce.  The committed
    contract vs ``mlp_apply_tp1`` — asserted by tests/test_costguard.py
    ::test_tp_sharded_per_device_byte_budget — is per-device
    ``argument_bytes`` ∝ 1/shards for the sharded weights (>= 70% below
    the unsharded control at tp=8), with the all-reduce visible in
    ``per_device.collective_bytes`` — the literal gate ROADMAP item 1
    (tensor-parallel decode) lands on top of."""
    return _tp_mlp_build("mlp_apply_tp8", shards)


@entrypoint("mlp_apply_tp1")
def build_mlp_apply_tp1():
    """The tp=1 control of the TP golden pair: identical model and
    batch on a 1-device mesh — full weight bytes per device, zero
    collectives.  Exists so the TP win is a diff of two COMMITTED
    goldens (the PR 8 pattern), not a number recomputed at test time."""
    return _tp_mlp_build("mlp_apply_tp1", 1)


@entrypoint("serving_mlp_grid_int8")
def build_serving_mlp_grid_int8(batch_buckets=(1, 2, 4),
                                length_buckets=(8, 16), features=32,
                                dtype="float32"):
    """``serving_mlp_grid`` with int8 post-training weight quantization:
    same model, same bucket grid, but the compiled programs take int8
    payloads + f32 per-channel scales as their weight arguments (the
    ``amp.Int8Quantizer.wrap`` fold).  The committed contract vs the
    f32 golden — asserted by tests/test_costguard.py::test_serving_
    int8_weight_buffer_budget — is >= 25% less compiled weight-buffer
    memory (``memory.argument_bytes``)."""
    return _serving_mlp_grid_build("serving_mlp_grid_int8", batch_buckets,
                                   length_buckets, features, dtype,
                                   quantize=True)
