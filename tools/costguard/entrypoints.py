"""Budgeted entry points: the named model/step/serving configurations
whose compiled-program costs are committed as goldens.

Each entry point is a builder that LOWERS its program(s) without ever
executing a step (``TrainStep.lower(sample)`` / ``jax.jit(f).lower``),
so budgets compute under ``JAX_PLATFORMS=cpu`` in tier-1.  Registration
is the budget *contract*: mxlint's ``unbudgeted-entrypoint`` rule fails
the gate when a registered name has no golden under
``tests/goldens/budgets/``, and the costguard CLI fails on goldens whose
registration disappeared — the two directions of "every audited surface
stays audited".

CPU-vs-TPU caveat (PERF.md): byte counts from the CPU backend are not
comparable to TPU's.  Goldens record their backend + device count and
are only *gated* in a matching environment; a TPU run of the same entry
points is an audit, not a gate.
"""
from __future__ import annotations

import dataclasses
import inspect
from pathlib import Path
from typing import Callable, Dict, List

from .census import executable_census, grid_signatures
from .report import Program

_REGISTRY: Dict[str, Callable] = {}


@dataclasses.dataclass
class EntryBuild:
    """What a builder returns: the lowered program units, the static
    executable census, and the metadata the golden records."""
    name: str
    meta: dict
    programs: List[Program]
    census: int


def entrypoint(name: str):
    """Register a budgeted entry point (decorator).  The literal name is
    what mxlint's ``unbudgeted-entrypoint`` facts extract — keep it a
    string literal, and matching ``tests/goldens/budgets/<name>.json``."""
    def deco(fn):
        if name in _REGISTRY:
            raise ValueError(f"entrypoint {name!r} registered twice")
        _REGISTRY[name] = fn
        fn.entrypoint_name = name
        return fn
    return deco


def names() -> List[str]:
    return sorted(_REGISTRY)


def build(name: str, **overrides) -> EntryBuild:
    if name not in _REGISTRY:
        raise KeyError(f"unknown entry point {name!r} "
                       f"(registered: {names()})")
    return _REGISTRY[name](**overrides)


def source_of(name: str) -> Path:
    """The file defining an entry point's builder — what lets the CLI
    map a path argument (``python -m tools.costguard mxnet_tpu/``) onto
    the entry points whose models live under it."""
    fn = _REGISTRY[name]
    return Path(inspect.getsourcefile(fn)).resolve()


def _mesh_and_opt(opt_name="sgd", **opt_kw):
    import jax  # noqa: F401 — imported for side-effectful backend init

    import mxnet_tpu as mx
    from mxnet_tpu import parallel
    mesh = parallel.make_mesh(dp=-1)
    return mesh, mx.optimizer.create(opt_name, **opt_kw)


def resnet50_train_step(batch=8, fused=False, layout="NHWC"):
    """The headline ResNet-50 train step, AOT only — shared by the
    ``resnet50_nhwc_train`` budget entry and ``benchmark/hlo_costs.py``
    (which parameterizes batch/fused for the fused-conv A/B).  Returns
    ``(step, x, y)`` with the sample batch as HOST arrays: nothing is
    placed or executed until the caller decides."""
    import ml_dtypes
    import numpy as np

    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1

    net = resnet50_v1(layout=layout, fused=fused)
    net.initialize()
    net.cast("bfloat16")
    mesh, opt = _mesh_and_opt("sgd", learning_rate=0.1, momentum=0.9,
                              wd=1e-4)
    step = parallel.TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                              opt, mesh=mesh)
    x = np.zeros((batch, 224, 224, 3), ml_dtypes.bfloat16)
    y = np.zeros((batch,), np.int32)
    return step, x, y


def _train_step_build(name, step, x, y, meta) -> EntryBuild:
    import jax

    lowered = step.lower(x, y)
    n_args = len(jax.tree.leaves(step._last_avals))
    meta = dict(meta, backend_note=(
        "CPU-backend byte counts are NOT comparable to TPU's (PERF.md); "
        "this golden gates the compile boundary, not on-chip traffic"))
    return EntryBuild(name=name, meta=meta, census=executable_census(step),
                      programs=[Program(name, lowered, n_args)])


@entrypoint("resnet50_nhwc_train")
def build_resnet50_nhwc_train(batch=8):
    """ResNet-50 v1 NHWC bf16 train step (fwd+bwd+SGD momentum, one XLA
    program on the dp mesh) — the PERF.md headline workload."""
    step, x, y = resnet50_train_step(batch=batch)
    return _train_step_build(
        "resnet50_nhwc_train", step, x, y,
        {"model": "resnet50_v1", "layout": "NHWC", "dtype": "bfloat16",
         "batch": batch, "optimizer": "sgd(momentum=0.9, wd=1e-4)"})


@entrypoint("mnist_mlp_train")
def build_mnist_mlp_train(batch=64, dtype="float32"):
    """The examples/train_mnist_mlp.py recipe: 784-128-10 MLP train
    step, f32, SGD momentum."""
    import ml_dtypes
    import numpy as np

    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Dense(128, activation="relu", in_units=784),
            nn.Dense(10, in_units=128))
    net.initialize()
    if dtype != "float32":
        net.cast(dtype)
    mesh, opt = _mesh_and_opt("sgd", learning_rate=0.1, momentum=0.9)
    step = parallel.TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                              opt, mesh=mesh)
    np_dtype = ml_dtypes.bfloat16 if dtype == "bfloat16" else np.float32
    x = np.zeros((batch, 784), np_dtype)
    y = np.zeros((batch,), np.int32)
    return _train_step_build(
        "mnist_mlp_train", step, x, y,
        {"model": "mlp 784-128-10", "dtype": dtype, "batch": batch,
         "optimizer": "sgd(momentum=0.9)"})


@entrypoint("serving_mlp_grid")
def build_serving_mlp_grid(batch_buckets=(1, 2, 4), length_buckets=(8, 16),
                           features=32, dtype="float32"):
    """A serving bucket grid: one jitted MLP apply lowered at EVERY
    padded (batch, length) signature a ``BucketSpec((1,2,4), (8,16))``
    admits — the whole executable space an ``InferenceServer`` on this
    spec can ever compile.  n_executables in the golden == the static
    census == the runtime jit-cache count (tests/test_serving.py).
    NB the dtype knob exists for on-TPU audits (bf16 serving, ROADMAP
    item 2), but the committed golden is f32: on the CPU backend bf16
    compute is EMULATED via converts and *costs* bytes rather than
    saving them — the PERF.md caveat, visible in the numbers."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.serving import BucketSpec

    spec = BucketSpec(batch=batch_buckets, length=length_buckets)
    hidden, out = 64, 16
    dt = jnp.dtype(dtype)
    w1 = jnp.zeros((features, hidden), dt)
    b1 = jnp.zeros((hidden,), dt)
    w2 = jnp.zeros((hidden, out), dt)
    b2 = jnp.zeros((out,), dt)

    @jax.jit
    def apply(x):                      # (batch, length, features)
        h = jnp.tanh(x @ w1 + b1)
        return h @ w2 + b2

    programs = []
    for b, L in grid_signatures(spec):
        aval = jax.ShapeDtypeStruct((b, L, features), dt)
        # mxlint: disable=jit-in-loop -- this loop IS the census: one
        # lower per bucket signature, bounded by the static grid, and
        # the expensive compile is memoized by the report cache
        lowered = apply.lower(aval)
        programs.append(Program(f"serving_mlp_grid/b{b}_l{L}",
                                lowered, n_args=1))
    return EntryBuild(
        name="serving_mlp_grid",
        meta={"model": f"mlp {features}-{hidden}-{out} apply",
              "dtype": dtype,
              "batch_buckets": list(spec.batch),
              "length_buckets": list(spec.length)},
        programs=programs, census=executable_census(spec))
