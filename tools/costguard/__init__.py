"""costguard — compiled-program cost budgets and recompile audit.

The static-analysis instrument for the compile boundary (ISSUE 6):
mxlint gates the Python-source surface; costguard gates what XLA
actually compiled.  It lowers each registered entry point (model train
step / serving bucket grid) WITHOUT executing a step, extracts a
normalized report — FLOPs, bytes accessed, compiled-buffer memory,
entry-instruction categories, donation coverage, executable count —
and diffs it against committed per-model budget goldens
(``tests/goldens/budgets/*.json``) with per-metric relative tolerances.
The static executable census makes "traffic can never trigger a
recompile" a checked invariant rather than a comment.

Usage (CLI)::

    python -m tools.costguard                    # audit all entry points
    python -m tools.costguard mxnet_tpu/         # entries defined under a path
    python -m tools.costguard mnist_mlp_train --format json
    python -m tools.costguard --list

Usage (API, what tests/test_costguard.py drives)::

    from tools import costguard
    result = costguard.run_check(root=repo_root)
    assert result.ok, result.render()

Budgets regenerate via ``python tests/goldens/budgets/regen_budgets.py``
(review the diff like source).  Docs: docs/analysis.md "Cost budgets".
"""
from .budget import (DEFAULT_TOLERANCES, CheckResult, EntryResult,
                     MetricRow, check_entry, diff_report, environment,
                     golden_path, load_golden, run_check)
from .census import executable_census, grid_signatures
from .entrypoints import EntryBuild, build, entrypoint, names, source_of
from .report import (REPORT_VERSION, Program, collective_payload_bytes,
                     instruction_counts, merge_reports,
                     report_for_programs, unit_report)

__all__ = [
    "DEFAULT_TOLERANCES", "CheckResult", "EntryResult", "MetricRow",
    "check_entry", "diff_report", "environment", "golden_path",
    "load_golden", "run_check",
    "executable_census", "grid_signatures",
    "EntryBuild", "build", "entrypoint", "names", "source_of",
    "REPORT_VERSION", "Program", "collective_payload_bytes",
    "instruction_counts", "merge_reports", "report_for_programs",
    "unit_report",
]
