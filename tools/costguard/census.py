"""Static executable census: how many XLA programs a configuration can
EVER compile.

The serving stack's central availability invariant — "traffic can never
trigger a recompile" (PR 4's signature pinning + warmup) — is only
checkable if the jit-signature space is enumerable *statically*.  It
is: a ``serving.BucketSpec`` admits exactly ``len(batch) × len(length)``
padded signatures; a ``TrainStep``/``EvalStep`` pins one signature per
(data, label) tree; ``module_apply`` traces once per padded signature,
i.e. its server's grid.  This module does that enumeration, and the
budget gate asserts ``census == n_executables`` in every committed
golden — turning the comment into a checked invariant
(``tests/test_serving.py`` additionally asserts census == the runtime
jit-cache count under real bucket-grid traffic).
"""
from __future__ import annotations

__all__ = ["grid_signatures", "executable_census"]


def grid_signatures(spec):
    """The full padded (batch_bucket, length_bucket) signature grid of a
    ``serving.BucketSpec`` — ``length`` is ``None`` when the spec does
    no length bucketing.  Every request an ``InferenceServer`` built on
    ``spec`` can ever dispatch lands on exactly one of these."""
    lengths = spec.length if spec.length is not None else (None,)
    return [(b, L) for b in spec.batch for L in lengths]


def _is_bucket_spec(c) -> bool:
    try:
        from mxnet_tpu.serving.batcher import BucketSpec
    except ImportError:
        return False
    return isinstance(c, BucketSpec)


def _is_step(c) -> bool:
    try:
        from mxnet_tpu.parallel.step import EvalStep, TrainStep
    except ImportError:
        return False
    return isinstance(c, (TrainStep, EvalStep))


def executable_census(*components) -> int:
    """Count the distinct XLA executables a set of components can
    compile:

    - ``serving.BucketSpec`` → its full signature grid (also the census
      of a ``module_apply``-backed server built on that spec);
    - ``TrainStep`` / ``EvalStep`` → 1 (one pinned signature; feeding a
      second signature is a *re*compile these budgets exist to catch);
    - ``int`` → that many known-extra signatures (e.g. a warmup probe
      shape outside the grid).
    """
    n = 0
    for c in components:
        if isinstance(c, bool):
            raise TypeError("executable_census: bool is not a count")
        if isinstance(c, int):
            if c < 0:
                raise ValueError("executable_census: negative count")
            n += c
        elif _is_bucket_spec(c):
            n += len(grid_signatures(c))
        elif _is_step(c):
            n += 1
        else:
            raise TypeError(
                f"executable_census: cannot enumerate signatures of "
                f"{type(c).__name__!r} (expected BucketSpec, TrainStep, "
                f"EvalStep, or int)")
    return n
