"""Budget goldens: committed per-entry-point cost reports with
per-metric relative tolerances, and the diff/check machinery the tier-1
gate and the CLI share.

A golden (``tests/goldens/budgets/<entry>.json``) commits the full
normalized report plus the environment it was recorded in.  The check
re-lowers + re-compiles the entry point and compares metric by metric:

- within tolerance → ok;
- above budget beyond tolerance → **REGRESSION**, the gate fails;
- below budget beyond tolerance → also fails, as a *stale budget*: an
  improvement must be ratcheted into the golden
  (``python tests/goldens/budgets/regen_budgets.py``) so the next
  regression is measured from the new floor, not the old slack.

Goldens gate only in a matching environment (backend + device count):
CPU byte counts are not TPU byte counts (PERF.md), so a TPU run of the
same entries reports without gating.
"""
from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Dict, List, Optional

from . import entrypoints
from .report import REPORT_VERSION, report_for_programs

GOLDEN_SUBDIR = Path("tests") / "goldens" / "budgets"

#: dotted metric → relative tolerance.  Tight where the number is
#: structural (executable count, donation coverage, conv/collective
#: instruction counts are exact properties of the program), loose where
#: the compiler has latitude (fusion decisions, buffer assignment).
DEFAULT_TOLERANCES: Dict[str, float] = {
    "flops": 0.01,
    "bytes_accessed": 0.02,
    "transcendentals": 0.05,
    "collective_bytes": 0.02,
    "n_executables": 0.0,
    "memory.peak_bytes": 0.25,
    "memory.argument_bytes": 0.02,
    "donation.donated_args": 0.0,
    "donation.total_args": 0.0,
    "instructions.total": 0.20,
    "instructions.convolution": 0.0,
    "instructions.collective": 0.0,
    "instructions.dot": 0.15,
    "instructions.fusion": 0.25,
    "instructions.custom-call": 0.25,
    "instructions.copy": 0.50,
    # per-device view of the worst single executable: how many devices
    # one program spans is structural (exact), its shard-local bytes
    # follow the memory tolerances — the ∝ 1/shards contract of a
    # sharded entry lives here.  The byte rows deliberately MIRROR the
    # memory.* rows (same values, same tolerances — keep them in sync):
    # per_device is the committed semantic unit of the sharded pairs,
    # memory the raw extraction; only n_devices and the max-vs-sum
    # collective_bytes carry new information today
    "per_device.n_devices": 0.0,
    "per_device.argument_bytes": 0.02,
    "per_device.peak_bytes": 0.25,
    "per_device.collective_bytes": 0.02,
}


@dataclasses.dataclass
class MetricRow:
    metric: str
    budget: float
    actual: float
    rel: float              # (actual - budget) / budget
    tol: float
    ok: bool

    def render(self) -> str:
        mark = "ok  " if self.ok else "FAIL"
        if self.actual != self.actual:       # NaN: budgeted, not reported
            return (f"  [{mark}] {self.metric:28s} "
                    f"budget={self.budget:>14.6g} actual=<missing>  "
                    f"<- the fresh report has no such metric "
                    f"(extraction failed?) — a budgeted metric may not "
                    f"silently stop being gated")
        verdict = ""
        if not self.ok:
            verdict = ("  <- REGRESSION over budget" if self.rel > 0 else
                       "  <- beats budget: ratchet the golden "
                       "(regen_budgets.py)")
        return (f"  [{mark}] {self.metric:28s} budget={self.budget:>14.6g} "
                f"actual={self.actual:>14.6g} ({self.rel:+.2%} vs "
                f"±{self.tol:.1%}){verdict}")


@dataclasses.dataclass
class EntryResult:
    name: str
    report: Optional[dict] = None
    golden: Optional[dict] = None
    rows: List[MetricRow] = dataclasses.field(default_factory=list)
    census: Optional[int] = None
    problems: List[str] = dataclasses.field(default_factory=list)
    gated: bool = True      # False = environment mismatch, report-only

    @property
    def ok(self) -> bool:
        return not self.problems and all(r.ok for r in self.rows)

    def to_dict(self) -> dict:
        return {"name": self.name, "ok": self.ok, "gated": self.gated,
                "census": self.census, "problems": list(self.problems),
                "rows": [{k: _json_num(v) for k, v in
                          dataclasses.asdict(r).items()}
                         for r in self.rows],
                "report": self.report}


def _json_num(v):
    """Strict-JSON-safe value: failure rows carry NaN (budgeted metric
    missing) and ±inf (zero-budget regression), which RFC-8259 parsers
    reject — exactly when the report matters most.  None / "inf" are
    the wire forms."""
    if isinstance(v, float):
        if v != v:
            return None
        if v == float("inf") or v == float("-inf"):
            return "inf" if v > 0 else "-inf"
    return v


def golden_path(name: str, root) -> Path:
    return Path(root) / GOLDEN_SUBDIR / f"{name}.json"


def device_count_guard(golden: dict, n_devices: int,
                       name: str) -> Optional[str]:
    """Why a SHARDED golden must not be regenerated right now, or None.

    A sharded entry's contract IS its per-device scaling — regenerating
    it from an environment whose visible device count differs from the
    committed golden's (a shell without the
    ``--xla_force_host_platform_device_count`` bring-up, a 1-chip TPU
    VM) would silently commit a 1-device "sharded" budget that gates
    nothing.  ``regen_budgets.py`` refuses; delete the golden first if
    the device-count change is intentional."""
    if not (golden.get("meta") or {}).get("sharded"):
        return None
    old = golden.get("n_devices")
    if old is not None and int(old) != int(n_devices):
        return (f"{name}: refusing to regenerate a SHARDED golden "
                f"recorded with {old} visible device(s) from an "
                f"environment with {n_devices} — its per-device byte "
                f"contract depends on the shard count.  Re-run under "
                f"the recorded bring-up (XLA_FLAGS=--xla_force_host_"
                f"platform_device_count={old}, the tests/conftest.py "
                f"environment), or delete the golden first if the "
                f"device-count change is intentional")
    return None


def load_golden(name: str, root) -> Optional[dict]:
    p = golden_path(name, root)
    if not p.exists():
        return None
    return json.loads(p.read_text(encoding="utf-8"))


def _lookup(report: dict, dotted: str):
    cur = report
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def diff_report(report: dict, golden: dict) -> List[MetricRow]:
    """Per-metric comparison of a fresh report against a golden's.
    Tolerances: golden ``tolerances`` override ``DEFAULT_TOLERANCES``
    per metric.  A metric absent from the GOLDEN is skipped (the golden
    is the committed, visible contract — it never budgeted that
    number); a budgeted metric absent from the FRESH report FAILS: an
    extraction path going dark (e.g. ``memory_analysis`` breaking on a
    backend change) must not quietly stop gating what the golden
    commits."""
    tols = dict(DEFAULT_TOLERANCES)
    tols.update(golden.get("tolerances") or {})
    budget_rep = golden["report"]
    rows = []
    for metric, tol in sorted(tols.items()):
        b, a = _lookup(budget_rep, metric), _lookup(report, metric)
        if b is None:
            continue
        if a is None:
            rows.append(MetricRow(metric=metric, budget=float(b),
                                  actual=float("nan"), rel=float("inf"),
                                  tol=tol, ok=False))
            continue
        b, a = float(b), float(a)
        if b == 0.0:
            rel = 0.0 if a == 0.0 else float("inf")
        else:
            rel = (a - b) / b
        rows.append(MetricRow(metric=metric, budget=b, actual=a, rel=rel,
                              tol=tol, ok=abs(rel) <= tol))
    return rows


def environment() -> dict:
    import jax
    return {"backend": jax.default_backend(),
            "n_devices": jax.device_count(),
            "jax_version": jax.__version__,
            "report_version": REPORT_VERSION}


def check_entry(name: str, root, use_cache: bool = False,
                cache_dir=None) -> EntryResult:
    """Build + lower + compile one entry point and judge it against its
    golden.  Never executes a step."""
    res = EntryResult(name=name)
    built = entrypoints.build(name)
    res.census = built.census
    res.report = report_for_programs(built.programs, root=root,
                                     use_cache=use_cache,
                                     cache_dir=cache_dir)
    if res.report["n_executables"] != built.census:
        res.problems.append(
            f"executable census mismatch: the signature space enumerates "
            f"{built.census} executables but the build lowered "
            f"{res.report['n_executables']} — a program exists outside "
            f"the declared signature grid (recompile hazard)")
    golden = load_golden(name, root)
    if golden is None:
        res.problems.append(
            f"no committed budget golden at {golden_path(name, root)} — "
            f"a registered entry point must carry a budget "
            f"(tests/goldens/budgets/regen_budgets.py writes one)")
        return res
    res.golden = golden
    env = environment()
    if golden.get("report_version") != REPORT_VERSION:
        res.problems.append(
            f"golden schema {golden.get('report_version')!r} != analyzer "
            f"schema {REPORT_VERSION!r} — regenerate the goldens")
        return res
    if (golden.get("backend"), golden.get("n_devices")) != \
            (env["backend"], env["n_devices"]):
        res.gated = False     # audit-only: numbers are not comparable
        return res
    if golden["report"].get("n_executables") != built.census:
        res.problems.append(
            f"budgeted executable count "
            f"{golden['report'].get('n_executables')} != static census "
            f"{built.census} — the golden no longer matches the "
            f"signature grid")
    res.rows = diff_report(res.report, golden)
    return res


@dataclasses.dataclass
class CheckResult:
    entries: List[EntryResult]
    stale_goldens: List[str]

    @property
    def ok(self) -> bool:
        return not self.stale_goldens and all(e.ok for e in self.entries)

    def to_json(self) -> str:
        return json.dumps(
            {"ok": self.ok, "stale_goldens": list(self.stale_goldens),
             "entries": [e.to_dict() for e in self.entries]},
            indent=2, sort_keys=True, allow_nan=False)

    def render(self) -> str:
        out = []
        for e in self.entries:
            status = "ok" if e.ok else "FAIL"
            scope = "" if e.gated else \
                " (environment != golden's: report-only, not gated)"
            out.append(f"[{status}] {e.name}: "
                       f"{e.report['n_executables']} executable(s), "
                       f"census {e.census}{scope}")
            for p in e.problems:
                out.append(f"  [FAIL] {p}")
            for r in e.rows:
                out.append(r.render())
        for name in self.stale_goldens:
            out.append(f"[FAIL] stale golden: tests/goldens/budgets/"
                       f"{name}.json has no registered entry point — "
                       f"delete it or restore the registration")
        out.append(f"costguard: "
                   f"{sum(1 for e in self.entries if e.ok)}/"
                   f"{len(self.entries)} entry points within budget"
                   + ("" if self.ok else " — CHECK FAILED"))
        return "\n".join(out)


def run_check(entries=None, root=None, use_cache: bool = False,
              cache_dir=None) -> CheckResult:
    """The whole audit: every selected entry point against its golden,
    plus the reverse direction — goldens whose registration is gone.
    ``entries=None`` selects everything; an explicit empty list audits
    no entry but still runs the (selection-independent) reverse
    check."""
    root = Path(root) if root is not None else Path.cwd()
    selected = entrypoints.names() if entries is None else list(entries)
    results = [check_entry(n, root, use_cache=use_cache,
                           cache_dir=cache_dir) for n in selected]
    # the reverse check is selection-independent: a golden whose
    # registration is GONE is stale no matter which subset this run
    # audits — every invocation (incl. the documented
    # `python -m tools.costguard mxnet_tpu/` path form) must see it
    stale = []
    gdir = root / GOLDEN_SUBDIR
    if gdir.is_dir():
        registered = set(entrypoints.names())
        stale = sorted(p.stem for p in gdir.glob("*.json")
                       if p.stem not in registered)
    return CheckResult(entries=results, stale_goldens=stale)
