"""mxlint — project static analysis for trace-safety, thread-safety,
donation hazards, and registry/docs consistency.

Usage (CLI)::

    python -m tools.analysis mxnet_tpu/            # human output, exit 1
    python -m tools.analysis mxnet_tpu/ --json     # machine output
    python -m tools.analysis --list-rules

Usage (API, what tests/test_mxlint.py drives)::

    from tools.analysis import analyze, Config
    findings = analyze(["mxnet_tpu/"], root=repo_root)
    assert not [f for f in findings if not f.suppressed]

Rules are documented in docs/analysis.md; suppression is
``# mxlint: disable=RULE -- justification`` (justification required).
"""
from .core import (BAD_SUPPRESSION, ENGINE_VERSION, Config, Finding,
                   ModuleInfo, Rule, ProjectRule, analyze, default_rules,
                   exit_code, summarize, to_json)
from .sarif import to_sarif

__all__ = ["BAD_SUPPRESSION", "ENGINE_VERSION", "Config", "Finding",
           "ModuleInfo", "Rule", "ProjectRule", "analyze",
           "default_rules", "exit_code", "summarize", "to_json",
           "to_sarif"]
