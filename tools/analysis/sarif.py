"""SARIF 2.1.0 output for mxlint findings.

SARIF (Static Analysis Results Interchange Format) is what CI
annotation tooling ingests — GitHub code scanning, Azure DevOps, the
VS Code SARIF viewer all consume it natively, so
``python -m tools.analysis --format sarif mxnet_tpu/`` plugs the gate
into PR annotations without a custom adapter.

The envelope is deliberately minimal and DETERMINISTIC: no timestamps,
no absolute paths (URIs are the repo-relative paths the engine already
reports, ``/``-separated per the spec), findings in the engine's sorted
order — so the golden-file test in tests/test_mxlint.py can compare
bytes, and ``chaos_check --mode lint`` can assert cached re-runs are
byte-identical.

Suppressed findings are carried as SARIF ``suppressions`` entries
(``kind: inSource`` with the justification) rather than dropped — the
same audit-trail stance as ``--json``.
"""
from __future__ import annotations

import json
from typing import Iterable, List, Optional

_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
           "master/Schemata/sarif-schema-2.1.0.json")

_LEVELS = {"error": "error", "warning": "warning", "info": "note"}


def _uri(path: str) -> str:
    return path.replace("\\", "/")


def to_sarif(findings, rules: Optional[Iterable] = None,
             tool_version: Optional[str] = None,
             tool_name: str = "mxlint") -> str:
    """Serialize findings as a SARIF 2.1.0 log (a JSON string).

    ``tool_name`` names the SARIF driver: "mxlint" (default) or
    "hloguard" — the structural HLO lint reuses this envelope so both
    gates feed the same CI annotation tooling."""
    if tool_version is None:
        from .core import ENGINE_VERSION
        tool_version = ENGINE_VERSION
    if rules is None:
        from .core import default_rules
        rules = default_rules()

    rule_meta: List[dict] = []
    seen = set()
    for r in rules:
        if r.id in seen:
            continue
        seen.add(r.id)
        rule_meta.append({
            "id": r.id,
            "shortDescription": {"text": r.description or r.id},
            "defaultConfiguration": {
                "level": _LEVELS.get(r.default_severity, "error")},
        })
    rule_meta.sort(key=lambda m: m["id"])
    rule_index = {m["id"]: i for i, m in enumerate(rule_meta)}

    results = []
    for f in findings:
        res = {
            "ruleId": f.rule,
            "level": _LEVELS.get(f.severity, "error"),
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": _uri(f.path)},
                    "region": {"startLine": max(1, f.line),
                               "startColumn": max(1, f.col)},
                },
            }],
        }
        if f.rule in rule_index:
            res["ruleIndex"] = rule_index[f.rule]
        if f.suppressed:
            res["suppressions"] = [{
                "kind": "inSource",
                "justification": f.justification or "",
            }]
        results.append(res)

    log = {
        "$schema": _SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": tool_name,
                "informationUri": "docs/analysis.md",
                "version": tool_version,
                "rules": rule_meta,
            }},
            "columnKind": "unicodeCodePoints",
            "results": results,
        }],
    }
    return json.dumps(log, indent=2, sort_keys=True)
