"""Per-function control-flow graphs for the mxlint dataflow engine.

PR 3's rules were per-function AST *walks*: they see syntax, not paths.
The bug classes this PR gates — a lock held across a blocking call, a
resource acquired and then leaked when an exception exits the function
early, taint that crosses a helper call — are properties of *paths*
through a function, so the engine needs a real CFG: nodes are
statements (plus a few synthetic markers), edges are ``normal`` or
``exception``, and ``try``/``except``/``finally``, ``with`` blocks,
loops, ``break``/``continue`` and early ``return`` are all modeled.

Design notes (kept deliberately boring — this runs in tier-1 CI):

- One node per statement.  Compound statements get a node for their
  header (the ``if`` test, the loop header, the ``with`` enter) and
  their bodies are sub-graphs.
- ``finally`` bodies (and the synthetic ``__exit__`` of ``with``) are
  DUPLICATED per continuation — one copy on the fall-through path, one
  on the exceptional path, one per early ``return``/``break``/
  ``continue`` that crosses them.  Duplication keeps every path
  explicit, which is what the leak rule needs; function bodies in this
  tree are small enough that the blow-up is irrelevant.
- Exception edges are added from any statement that *can plausibly
  raise* (``raise``/``assert``, or anything containing a call or a
  subscript) to the innermost handler, else to ``raise_exit`` — the
  function's exceptional exit.  This is the approximation that makes
  "can exit via exception without reaching close()" a reachability
  question.
- ``except`` dispatch is approximated: an exception edge reaches a
  dispatch node that fans out to every handler; unless some handler is
  a bare ``except:`` / ``except (Base)Exception``, the dispatch also
  keeps an exception edge outward (the handlers may not match).
- ``async def`` (and anything else the builder does not model) is NOT
  analyzed: ``build_cfg`` returns ``None`` and CFG-hosted rules skip
  the function cleanly instead of guessing (tested in
  tests/test_mxlint.py).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

NORMAL = "normal"
EXC = "exception"

# node kinds (``stmt`` carries the AST anchor for line numbers)
ENTRY = "entry"
EXIT = "exit"            # normal return
RAISE_EXIT = "raise"     # uncaught exception leaves the function
STMT = "stmt"
BRANCH = "branch"        # if/match header
LOOP = "loop"            # while/for header (iter/test evaluation + bind)
WITH_ENTER = "with_enter"  # context managers entered (locks acquired)
WITH_EXIT = "with_exit"    # context managers exited (locks released)
DISPATCH = "except_dispatch"
BRIDGE = "bridge"        # re-raise hop after a duplicated finally body


class Node:
    __slots__ = ("stmt", "kind", "succ")

    def __init__(self, stmt=None, kind=STMT):
        self.stmt = stmt
        self.kind = kind
        self.succ: List[Tuple["Node", str]] = []

    def link(self, other: "Node", edge: str = NORMAL):
        if other is not None and (other, edge) not in self.succ:
            self.succ.append((other, edge))

    @property
    def lineno(self):
        return getattr(self.stmt, "lineno", 0)

    def __repr__(self):  # debugging aid only
        return f"<{self.kind}@{self.lineno}>"


class CFG:
    """entry/exit/raise_exit plus every reachable node of one function
    (or module) body."""

    def __init__(self, fn):
        self.fn = fn
        self.entry = Node(fn, ENTRY)
        self.exit = Node(fn, EXIT)
        self.raise_exit = Node(fn, RAISE_EXIT)

    def nodes(self) -> List[Node]:
        """Reachable nodes in a stable (BFS) order."""
        seen = {id(self.entry): self.entry}
        order = [self.entry]
        i = 0
        while i < len(order):
            for nxt, _ in order[i].succ:
                if id(nxt) not in seen:
                    seen[id(nxt)] = nxt
                    order.append(nxt)
            i += 1
        return order


_MAY_RAISE = (ast.Call, ast.Raise, ast.Assert, ast.Subscript, ast.Await)


def may_raise(stmt) -> bool:
    """Can this statement plausibly raise?  Calls, subscripts, asserts
    and explicit raises; attribute reads and arithmetic are treated as
    non-raising (the rules this feeds want actionable paths, not the
    truism that any bytecode can fault).  Nested function/lambda BODIES
    are skipped — defining a function never raises; for a ``def``
    statement itself only its decorators and default values (which run
    at definition time) count."""
    stack = []
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
        stack.extend(stmt.decorator_list)
        stack.extend(stmt.args.defaults + stmt.args.kw_defaults)
    else:
        stack.append(stmt)
    while stack:
        node = stack.pop()
        if node is None:
            continue
        if isinstance(node, _MAY_RAISE):
            return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            stack.extend(node.decorator_list
                         if not isinstance(node, ast.Lambda) else ())
            stack.extend(node.args.defaults + node.args.kw_defaults)
            continue
        stack.extend(ast.iter_child_nodes(node))
    return False


class _Ctx:
    """Continuation targets while building: where ``return`` / ``break``
    / ``continue`` / an uncaught exception go from here."""

    __slots__ = ("ret", "brk", "cont", "exc")

    def __init__(self, ret, brk, cont, exc):
        self.ret = ret
        self.brk = brk
        self.cont = cont
        self.exc = exc

    def replace(self, **kw):
        out = _Ctx(self.ret, self.brk, self.cont, self.exc)
        for k, v in kw.items():
            setattr(out, k, v)
        return out


def _catches_everything(handlers) -> bool:
    for h in handlers:
        if h.type is None:
            return True
        names = []
        if isinstance(h.type, ast.Tuple):
            names = [getattr(e, "attr", getattr(e, "id", None))
                     for e in h.type.elts]
        else:
            names = [getattr(h.type, "attr", getattr(h.type, "id", None))]
        if any(n in ("Exception", "BaseException") for n in names):
            return True
    return False


class _Builder:
    def __init__(self, cfg: CFG):
        self.cfg = cfg

    # -- sequencing ---------------------------------------------------------
    def seq(self, stmts, follow: Node, ctx: _Ctx) -> Node:
        cur = follow
        for stmt in reversed(stmts):
            cur = self.stmt(stmt, cur, ctx)
        return cur

    # -- finally duplication ------------------------------------------------
    def _wrap_finally(self, finalbody, cache: Dict, target: Node,
                      edge: str, ctx: _Ctx) -> Node:
        """Entry of a fresh copy of ``finalbody`` that continues to
        ``target`` via ``edge`` (memoized per (target, edge))."""
        if not finalbody or target is None:
            return target
        key = (id(target), edge)
        if key not in cache:
            bridge = Node(finalbody[0], BRIDGE)
            bridge.link(target, edge)
            # an exception INSIDE finally abandons the original
            # continuation and propagates outward
            cache[key] = self.seq(finalbody, bridge, ctx)
        return cache[key]

    # -- statements ---------------------------------------------------------
    def stmt(self, stmt, follow: Node, ctx: _Ctx) -> Node:
        cfg = self.cfg
        if isinstance(stmt, ast.If):
            node = Node(stmt, BRANCH)
            node.link(self.seq(stmt.body, follow, ctx))
            node.link(self.seq(stmt.orelse, follow, ctx)
                      if stmt.orelse else follow)
            if may_raise(stmt.test):
                node.link(ctx.exc, EXC)
            return node

        if isinstance(stmt, (ast.While, ast.For)):
            head = Node(stmt, LOOP)
            after = self.seq(stmt.orelse, follow, ctx) \
                if stmt.orelse else follow
            body_ctx = ctx.replace(brk=follow, cont=head)
            head.link(self.seq(stmt.body, head, body_ctx))
            head.link(after)
            test = stmt.test if isinstance(stmt, ast.While) else stmt.iter
            if may_raise(test) or isinstance(stmt, ast.For):
                head.link(ctx.exc, EXC)
            return head

        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            enter = Node(stmt, WITH_ENTER)
            cache: Dict = {}

            def wrap(target, edge=NORMAL):
                if target is None:
                    return None
                key = (id(target), edge)
                if key not in cache:
                    ex = Node(stmt, WITH_EXIT)
                    ex.link(target, edge)
                    cache[key] = ex
                return cache[key]

            body_ctx = _Ctx(ret=wrap(ctx.ret), brk=wrap(ctx.brk),
                            cont=wrap(ctx.cont), exc=wrap(ctx.exc, EXC))
            enter.link(self.seq(stmt.body, wrap(follow), body_ctx))
            # exception while entering: the manager is not held yet —
            # route through the WITH_EXIT copy anyway so a lock-set
            # transfer that optimistically added tokens at WITH_ENTER
            # retracts them before the edge leaves the with (Python
            # skips __exit__ when __enter__ raises; for set-valued
            # facts, removing a token that was never really held is
            # the identity)
            enter.link(wrap(ctx.exc, EXC), EXC)
            return enter

        if isinstance(stmt, ast.Try):
            fin = stmt.finalbody
            cache: Dict = {}

            def wrap(target, edge=NORMAL):
                if target is None or not fin:
                    return target
                return self._wrap_finally(fin, cache, target, edge, ctx)

            w_follow = wrap(follow)
            w_exc = wrap(ctx.exc, EXC)
            inner = _Ctx(ret=wrap(ctx.ret), brk=wrap(ctx.brk),
                         cont=wrap(ctx.cont), exc=w_exc)
            if stmt.handlers:
                dispatch = Node(stmt, DISPATCH)
                handler_ctx = inner
                for h in stmt.handlers:
                    dispatch.link(self.seq(h.body, w_follow, handler_ctx))
                if not _catches_everything(stmt.handlers):
                    dispatch.link(w_exc, EXC)
                body_exc = dispatch
            else:
                body_exc = w_exc
            body_ctx = inner.replace(exc=body_exc)
            after_body = self.seq(stmt.orelse, w_follow, inner) \
                if stmt.orelse else w_follow
            return self.seq(stmt.body, after_body, body_ctx)

        if isinstance(stmt, ast.Return):
            node = Node(stmt)
            node.link(ctx.ret)
            if stmt.value is not None and may_raise(stmt.value):
                node.link(ctx.exc, EXC)
            return node

        if isinstance(stmt, ast.Raise):
            node = Node(stmt)
            node.link(ctx.exc, EXC)
            return node

        if isinstance(stmt, ast.Break):
            node = Node(stmt)
            node.link(ctx.brk or follow)
            return node

        if isinstance(stmt, ast.Continue):
            node = Node(stmt)
            node.link(ctx.cont or follow)
            return node

        if isinstance(stmt, ast.Assert):
            node = Node(stmt)
            node.link(follow)
            node.link(ctx.exc, EXC)
            return node

        if isinstance(stmt, ast.Match):
            node = Node(stmt, BRANCH)
            exhausted = False
            for case in stmt.cases:
                node.link(self.seq(case.body, follow, ctx))
                if isinstance(case.pattern, ast.MatchAs) \
                        and case.pattern.pattern is None:
                    exhausted = True  # `case _:`
            if not exhausted:
                node.link(follow)
            if may_raise(stmt.subject):
                node.link(ctx.exc, EXC)
            return node

        # nested defs/classes, simple statements, everything else: one
        # node, fall through, exception edge when it can raise.  Nested
        # function BODIES are separate CFGs — not descended into here.
        node = Node(stmt)
        node.link(follow)
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)) and may_raise(stmt):
            node.link(ctx.exc, EXC)
        return node


def build_cfg(fn) -> Optional[CFG]:
    """CFG for one ``FunctionDef`` (or an ``ast.Module`` — the donation
    rule analyzes module scope too).  Returns ``None`` for constructs
    the builder does not model (``async def``): callers must treat that
    as "not analyzed", never as "clean and verified" — and never crash.
    """
    if isinstance(fn, ast.AsyncFunctionDef):
        return None
    if not isinstance(fn, (ast.FunctionDef, ast.Module, ast.Lambda)):
        return None
    if isinstance(fn, ast.Lambda):
        return None  # single expression: nothing path-sensitive to model
    cfg = CFG(fn)
    builder = _Builder(cfg)
    ctx = _Ctx(ret=cfg.exit, brk=None, cont=None, exc=cfg.raise_exit)
    cfg.entry.link(builder.seq(fn.body, cfg.exit, ctx))
    return cfg


def node_exprs(node: Node) -> tuple:
    """The AST subtrees a node actually *evaluates* — the ``if`` test
    but not its body (the body has its own nodes), the loop iterable,
    the ``with`` context expressions.  CFG-hosted rules scan these
    instead of ``node.stmt`` wholesale, or every expression in a
    compound statement would be visited once per enclosing header.
    """
    s = node.stmt
    if s is None or node.kind in (ENTRY, EXIT, RAISE_EXIT, BRIDGE,
                                  DISPATCH, WITH_EXIT):
        return ()
    if node.kind == BRANCH:
        if isinstance(s, ast.If):
            return (s.test,)
        if isinstance(s, ast.Match):
            return (s.subject,)
        return ()
    if node.kind == LOOP:
        if isinstance(s, ast.While):
            return (s.test,)
        return (s.target, s.iter)
    if node.kind == WITH_ENTER:
        out = []
        for item in s.items:
            out.append(item.context_expr)
            if item.optional_vars is not None:
                out.append(item.optional_vars)
        return tuple(out)
    if isinstance(s, (ast.If, ast.While, ast.For, ast.With, ast.AsyncWith,
                      ast.Try, ast.Match)):
        return ()   # defensive: headers are handled by kind above
    return (s,)


# --------------------------------------------------------------------------
# generic forward dataflow over a CFG
# --------------------------------------------------------------------------

def forward(cfg: CFG, entry_fact, transfer, join):
    """Classic worklist forward analysis.

    ``transfer(node, fact_in)`` returns either one fact for every out
    edge, or a ``(normal_fact, exception_fact)`` pair when the two edge
    kinds must differ — e.g. the statement that *acquires* a resource
    contributes it only on its normal edge (if ``open()`` raises there
    is no handle to leak).  Facts must be hashable (frozensets);
    ``join(a, b)`` merges at control-flow merges (union = may-analysis,
    intersection = must-analysis).  Returns ``{id(node): fact_in}`` for
    every reachable node.
    """
    facts: Dict[int, object] = {id(cfg.entry): entry_fact}
    work = [cfg.entry]
    iterations = 0
    limit = 40 * (len(cfg.nodes()) + 8)   # belt + suspenders: lattices
    while work:                           # here are finite, this bounds
        iterations += 1                   # a builder bug, not the math
        if iterations > limit:
            # NEVER return partial facts: rules hosted on this engine
            # feed a zero-findings CI gate, and silent under-reporting
            # is the one failure mode such a gate cannot tolerate —
            # fail loudly and fix the builder
            raise RuntimeError(
                f"mxlint dataflow did not converge within {limit} "
                f"iterations on '{getattr(cfg.fn, 'name', '<module>')}'"
                f" (line {getattr(cfg.fn, 'lineno', 0)}) — CFG builder "
                f"bug, please report")
        node = work.pop()
        out = transfer(node, facts[id(node)])
        if isinstance(out, tuple):
            normal_out, exc_out = out
        else:
            normal_out = exc_out = out
        for nxt, edge in node.succ:
            fact = exc_out if edge == EXC else normal_out
            prev = facts.get(id(nxt))
            merged = fact if prev is None else join(prev, fact)
            if merged != prev:
                facts[id(nxt)] = merged
                work.append(nxt)
    return facts
