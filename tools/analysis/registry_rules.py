"""Registry- and docs-consistency rules.

The op registry (``mxnet_tpu/ops/registry.py``) is string-keyed: a
second ``register_op("X")`` silently *shadows* the first (last writer
wins, like the reference's NNVM registry refusing duplicates — which we
don't, at runtime).  Similarly, a ``jax.custom_vjp`` wrapper whose
``defvjp`` call was dropped in a refactor imports fine and fails only
when ``jax.grad`` first touches it.  And ``docs/api.md`` rows rot as
symbols are renamed.  All three are cross-file facts no single-file
review sees — exactly what a project rule is for.

``registry-duplicate``   the same op name registered (or aliased) from
                         two distinct source sites
``registry-missing-grad`` a ``jax.custom_vjp`` function with no
                         ``.defvjp(...)`` installation in its module
``docs-stale-symbol``    a ``docs/api.md`` "Here" cell naming a file
                         that does not exist or a project symbol that is
                         defined nowhere in the tree
"""
from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Tuple

from .core import Finding, ProjectRule, Rule, last_component


# --------------------------------------------------------------------------
# registry registrations
# --------------------------------------------------------------------------

def _registrations(mod):
    """(name, lineno) pairs this module registers: register_op first
    args, their aliases= entries, and alias_op targets.  Only literal
    names count — f-string loops (broadcast_* generation) are runtime
    facts, not static ones."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = last_component(node.func)
        if callee == "register_op":
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                out.append((node.args[0].value, node.lineno))
            for k in node.keywords:
                if k.arg == "aliases" \
                        and isinstance(k.value, (ast.Tuple, ast.List)):
                    out.extend((e.value, node.lineno) for e in k.value.elts
                               if isinstance(e, ast.Constant)
                               and isinstance(e.value, str))
        elif callee == "alias_op":
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                out.append((node.args[0].value, node.lineno))
    return out


class DuplicateRegistrationRule(ProjectRule):
    id = "registry-duplicate"
    description = "op name registered/aliased from two distinct sites"

    def facts(self, mod):
        return [[name, line] for name, line in _registrations(mod)]

    def check_facts(self, facts, root, analyzed):
        sites: Dict[str, List[Tuple[str, int]]] = {}
        for relpath, regs in facts:
            for name, line in regs or ():
                sites.setdefault(name, []).append((relpath, line))
        for name, where in sorted(sites.items()):
            if len(where) < 2:
                continue
            where.sort()
            first = where[0]
            for path, line in where[1:]:
                if path not in analyzed:
                    continue
                yield Finding(
                    rule=self.id, path=path, line=line, col=1,
                    message=f"op '{name}' is registered here but already "
                            f"registered at {first[0]}:{first[1]} — the "
                            f"later registration silently shadows the "
                            f"earlier one (rename it or register an "
                            f"explicit alias of the same function)")


class MissingGradientRule(Rule):
    id = "registry-missing-grad"
    description = ("jax.custom_vjp function without a .defvjp "
                   "installation (declared gradient never provided)")

    def check_module(self, mod):
        declared: Dict[str, ast.AST] = {}
        installed = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for d in node.decorator_list:
                    base = d.func if isinstance(d, ast.Call) else d
                    # @jax.custom_vjp and @partial(jax.custom_vjp, ...)
                    if last_component(base) == "custom_vjp" or (
                            isinstance(d, ast.Call)
                            and last_component(d.func) == "partial"
                            and d.args
                            and last_component(d.args[0]) == "custom_vjp"):
                        declared[node.name] = node
            elif isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and last_component(node.value.func) == "custom_vjp":
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        declared[t.id] = node
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "defvjp" \
                    and isinstance(node.func.value, ast.Name):
                installed.add(node.func.value.id)
        for name, node in declared.items():
            if name not in installed:
                yield self.finding(
                    mod, node,
                    f"'{name}' is wrapped in jax.custom_vjp but no "
                    f"'{name}.defvjp(fwd, bwd)' call exists in this "
                    f"module: the declared custom gradient is never "
                    f"installed and jax.grad will raise at runtime")


# --------------------------------------------------------------------------
# docs/api.md staleness
# --------------------------------------------------------------------------

_TOKEN_RE = re.compile(r"`([^`]+)`")
_IDENT_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_.]*$")
_PATH_EXTS = (".py", ".cc", ".c", ".h", ".md", ".json", ".so")
# dotted tokens are resolved only under these project roots — `os.replace`
# or `jax.distributed` in prose are not ours to check
_PROJECT_PREFIXES = {
    "mx", "mxnet_tpu", "parallel", "fault", "callback", "gluon", "nd",
    "sym", "np", "npx", "contrib", "io", "profiler", "checkpoint",
    "optimizer", "image", "random", "symbol", "executor", "module", "nn",
    "rnn", "kvstore", "metric", "model", "viz", "mon", "amp", "onnx",
    "recordio", "config", "runtime", "util", "tools", "step", "serving",
    "telemetry",
}


def module_symbols(mod) -> list:
    """Every name one module defines: functions/classes/methods at any
    depth, assignments (including ``self.attr`` instance attributes),
    registered op names, fault-injection point names, and the module
    basename.  This is the per-file fact the docs rule caches; the
    whole-tree index is the union."""
    index = set()
    index.add(Path(mod.relpath).stem)
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            index.add(node.name)
        elif isinstance(node, (ast.Assign, ast.AnnAssign,
                               ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        index.add(n.id)
                    elif isinstance(n, ast.Attribute):
                        index.add(n.attr)
        elif isinstance(node, ast.Call) \
                and last_component(node.func) in ("fire", "_fire",
                                                  "inject") \
                and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            # fault-injection point names are a documented surface
            # (`io.producer` etc.) — docs referencing them are not
            # stale as long as the fire() site exists
            index.add(node.args[0].value)
    for name, _ in _registrations(mod):
        index.add(name)
    return sorted(index)


def build_symbol_index(modules) -> set:
    """Union of ``module_symbols`` over ModuleInfo objects (kept for
    tests/back-compat; the engine path goes through facts)."""
    index = set()
    for mod in modules:
        index.update(module_symbols(mod))
    return index


class StaleDocSymbolRule(ProjectRule):
    id = "docs-stale-symbol"
    description = ("docs/api.md names a file or project symbol that no "
                   "longer exists")
    doc_path = Path("docs/api.md")

    def facts(self, mod):
        return module_symbols(mod)

    def check_facts(self, facts, root, analyzed):
        # the docs contract is against the WHOLE tree, not whatever
        # subset this run analyzes — the engine hands project rules the
        # analyzed set PLUS the project scope (core.PROJECT_SCOPE), so
        # linting a single file does not make every doc row look stale
        doc = root / self.doc_path
        if not doc.exists():
            return
        index = set()
        for _relpath, symbols in facts:
            index.update(symbols or ())
        lines = doc.read_text(encoding="utf-8").splitlines()
        doc_mod = type("Doc", (), {"relpath": str(self.doc_path)})
        for lineno, line in enumerate(lines, start=1):
            for token in self._checkable_tokens(line):
                yield from self._check_token(doc_mod, lineno, token, index,
                                             root)

    @staticmethod
    def _checkable_tokens(line):
        """Backticked tokens from the line's project-side cells.  In
        tables the first cell is the *reference* column (MXNet 1.x
        symbols, which legitimately do not exist here) — skip it."""
        if line.strip().startswith("|"):
            cells = line.split("|")[2:]  # drop leading '' + reference cell
            text = "|".join(cells)
        else:
            text = line
        return _TOKEN_RE.findall(text)

    def _check_token(self, doc_mod, lineno, token, index, root):
        token = token.strip().rstrip(",.;:")
        if any(ch in token for ch in "*<>$= \""):
            # globs, placeholders, flags, and `key=value` snippets are
            # illustrative, not symbol references
            token = token.split(" ")[0]
            if any(ch in token for ch in "*<>$=\""):
                return
        # call-form: `fit(...)` / `mx.fault.inject(...)`
        base = token.split("(")[0] if "(" in token else token
        if "/" in base:
            last = base.rsplit("/", 1)[-1]
            if base.endswith("/") or last.endswith(_PATH_EXTS):
                for cand in (root / base, root / "mxnet_tpu" / base):
                    if cand.exists():
                        return
                yield Rule.finding(
                    self, doc_mod,
                    type("L", (), {"lineno": lineno, "col_offset": 0}),
                    f"docs/api.md references path `{base}` which does "
                    f"not exist in the tree")
            return
        if not _IDENT_RE.match(base):
            return
        if "." in base:
            if base in index:  # full dotted name known (fault points)
                return
            first, last = base.split(".", 1)[0], base.rsplit(".", 1)[-1]
            if first not in _PROJECT_PREFIXES:
                return
            if last not in index and first != last:
                yield Rule.finding(
                    self, doc_mod,
                    type("L", (), {"lineno": lineno, "col_offset": 0}),
                    f"docs/api.md names `{base}` but '{last}' is not "
                    f"defined anywhere in the tree (renamed or removed?)")
        elif "(" in token:
            # bare call like `maybe_save()` — the parens mark it as a
            # project callable claim
            if base not in index:
                yield Rule.finding(
                    self, doc_mod,
                    type("L", (), {"lineno": lineno, "col_offset": 0}),
                    f"docs/api.md names callable `{base}()` but it is "
                    f"not defined anywhere in the tree")
