"""Trace-safety rules.

A function that runs under ``jax.jit`` (directly, through a transform
like ``grad``/``vmap``/``shard_map``, or because TrainStep/EvalStep/the
symbolic executor compiles it) is *traced*: its array arguments are
abstract tracers, and any operation that needs a concrete value — a
host sync (``.item()``, ``float()``, ``np.asarray``, ``device_get``), a
Python ``if``/``while`` on an array, a ``print`` — either fails at
trace time or, worse, silently bakes trace-time state into the compiled
program (the Julia→TPU literature calls this the compile-boundary
discipline; it is the #1 hazard class of a whole-program-compile
stack).  These rules find such operations *statically*, with a
first-order taint walk: the traced function's parameters are tainted,
assignment propagates taint, and accesses that are static even under
trace (``.shape``/``.ndim``/``.dtype``, ``isinstance``/``len``,
``is None``) are exempt.

Rules:

``trace-host-sync``      host-sync call on a traced value inside a
                         traced function (also any ``print``: it runs
                         at trace time, once, not per step)
``trace-python-branch``  ``if``/``while``/ternary/``assert`` on a
                         traced value (needs ``jnp.where``/``lax.cond``)
``trace-mutable-global`` mutating module-level state from inside a
                         traced function (runs at trace time only; the
                         compiled steps never see it — and with the
                         producer threads of the async feed it is a
                         data race as well)
``trace-unhashable-static``  list/dict/set literal passed in a
                         ``static_argnums``/``static_argnames``
                         position of a jitted callable (or any arg of
                         an ``lru_cache``-ed one): unhashable statics
                         fail at call time
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import Rule, dotted_name, last_component, assigned_names

# transforms whose function arguments execute under trace
_JIT_WRAPPERS = {
    "jit", "pjit", "grad", "value_and_grad", "vjp", "jvp", "linearize",
    "eval_shape", "make_jaxpr", "vmap", "pmap", "checkpoint", "remat",
    "shard_map", "pallas_call", "scan", "while_loop", "fori_loop", "cond",
    "custom_vjp", "custom_jvp", "associative_scan",
}

# compile-path constructors of THIS framework: the named argument is
# traced by the fused step (parallel/step.py) / the symbolic executor
_COMPILE_SINKS = {"TrainStep": (1, "loss_fn"), "EvalStep": (None, None)}

# attribute reads that are static under trace (abstract-value metadata)
_STATIC_ATTRS = {
    "shape", "ndim", "dtype", "size", "nbytes", "aval", "sharding",
    "is_fully_addressable", "is_fully_replicated", "weak_type", "_fields",
}

# calls whose results are static under trace even on traced inputs
_STATIC_CALLS = {
    "isinstance", "issubclass", "len", "hasattr", "getattr", "callable",
    "type", "id", "repr", "str", "format",
}

_HOST_SYNC_METHODS = {"item", "tolist", "asnumpy", "block_until_ready"}
_HOST_SYNC_FUNCS = {
    "np.asarray", "numpy.asarray", "np.array", "numpy.array",
    "jax.device_get", "device_get", "np.copyto",
}
_CASTS = {"float", "int", "bool", "complex"}

_MUTATORS = {
    "append", "extend", "insert", "add", "update", "pop", "popitem",
    "remove", "discard", "clear", "setdefault", "popleft", "appendleft",
    "__setitem__",
}

_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.SetComp,
               ast.DictComp, ast.GeneratorExp)


# --------------------------------------------------------------------------
# traced-function discovery
# --------------------------------------------------------------------------

def _is_jit_wrapper(node) -> bool:
    """True for a decorator/callee that traces its function argument:
    ``jax.jit``, ``lax.scan``, ``functools.partial(jax.jit, ...)``..."""
    name = last_component(node)
    if name in _JIT_WRAPPERS:
        return True
    if isinstance(node, ast.Call) and last_component(node.func) == "partial" \
            and node.args and last_component(node.args[0]) in _JIT_WRAPPERS:
        return True
    return False


def _returned_defs(fn: ast.FunctionDef) -> Set[str]:
    """Names of nested defs this factory function returns."""
    nested = {n.name for n in fn.body if isinstance(n, ast.FunctionDef)}
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and isinstance(node.value, ast.Name) \
                and node.value.id in nested:
            out.add(node.value.id)
    return out


def _static_positions(call: Optional[ast.Call]):
    """(param indices, param names) a jit/custom_vjp call marks static —
    those arguments are concrete Python values, not tracers."""
    nums: Set[int] = set()
    names: Set[str] = set()
    if call is None:
        return nums, names
    for k in call.keywords:
        if k.arg in ("static_argnums", "nondiff_argnums"):
            if isinstance(k.value, (ast.Tuple, ast.List)):
                nums |= {e.value for e in k.value.elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, int)}
            elif isinstance(k.value, ast.Constant) \
                    and isinstance(k.value.value, int):
                nums.add(k.value.value)
        elif k.arg == "static_argnames":
            if isinstance(k.value, (ast.Tuple, ast.List)):
                names |= {e.value for e in k.value.elts
                          if isinstance(e, ast.Constant)
                          and isinstance(e.value, str)}
            elif isinstance(k.value, ast.Constant) \
                    and isinstance(k.value.value, str):
                names.add(k.value.value)
    return nums, names


def find_traced_functions(tree: ast.Module) -> List[tuple]:
    """(fn, static param indices, static param names) triples for the
    functions in this module whose bodies execute under trace."""
    defs: Dict[str, List[ast.FunctionDef]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)

    traced: List[tuple] = []
    seen = {}

    def mark(name_or_fn, nums=(), names=()):
        fns = [name_or_fn] if isinstance(name_or_fn, ast.AST) \
            else defs.get(name_or_fn or "", ())
        for fn in fns:
            if id(fn) not in seen:
                entry = [fn, set(nums), set(names)]
                seen[id(fn)] = entry
                traced.append(entry)
            else:  # merge static info from a second marking site
                seen[id(fn)][1] |= set(nums)
                seen[id(fn)][2] |= set(names)

    # decorated: @jax.jit / @partial(jax.jit, static_argnums=...) ...
    for fns in defs.values():
        for fn in fns:
            for d in fn.decorator_list:
                if _is_jit_wrapper(d):
                    nums, names = _static_positions(
                        d if isinstance(d, ast.Call) else None)
                    mark(fn, nums, names)

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _is_jit_wrapper(node.func):
            nums, names = _static_positions(node)
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, ast.Name):
                    mark(arg.id, nums, names)
                elif isinstance(arg, ast.Call) \
                        and last_component(arg.func) == "partial" \
                        and arg.args and isinstance(arg.args[0], ast.Name):
                    # pallas_call(partial(kernel, ...)) — the partial'd
                    # function is the one that traces; everything the
                    # partial binds (positionally or by keyword) is a
                    # concrete Python value, not a tracer
                    bound_nums = set(range(len(arg.args) - 1))
                    bound_names = {k.arg for k in arg.keywords if k.arg}
                    mark(arg.args[0].id, nums | bound_nums,
                         names | bound_names)
                elif isinstance(arg, ast.Call) and \
                        isinstance(arg.func, ast.Name):
                    # factory pattern: jax.jit(make_fn(...)) traces the
                    # nested def make_fn returns
                    for fn in defs.get(arg.func.id, ()):
                        for name in _returned_defs(fn):
                            mark(name, nums, names)
        sink = _COMPILE_SINKS.get(last_component(node.func) or "")
        if sink:
            pos, kw = sink
            cand = None
            if pos is not None and len(node.args) > pos:
                cand = node.args[pos]
            for k in node.keywords:
                if k.arg == kw:
                    cand = k.value
            if isinstance(cand, ast.Name):
                mark(cand.id)
    return [tuple(e) for e in traced]


# --------------------------------------------------------------------------
# taint
# --------------------------------------------------------------------------

def _tainted_params(fn, static_nums=(), static_names=()) -> Set[str]:
    args = fn.args
    pos = [a.arg for a in args.posonlyargs + args.args]
    names = [n for i, n in enumerate(pos) if i not in set(static_nums)]
    names += [a.arg for a in args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return {n for n in names if n != "self" and n not in set(static_names)}


def compute_taint(fn, static_nums=(), static_names=(),
                  seed=None) -> Set[str]:
    """Parameters of ``fn`` (and of its nested defs — they run under the
    same trace) plus everything assignment-reachable from them.  Params
    in static/nondiff positions are concrete, not traced, and metadata
    reads (``x.shape``) do not propagate taint.

    ``seed`` overrides the initial set: for a helper reached through a
    call boundary only the parameters the call site actually passed
    tainted values into are traced (dataflow.traced_closure computes
    those) — the helper's other parameters stay concrete."""
    if seed is not None:
        tainted = set(seed)
    else:
        tainted = set(_tainted_params(fn, static_nums, static_names))
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                tainted |= _tainted_params(node)
    for _ in range(3):  # small fixpoint: chains are short in practice
        before = len(tainted)
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                                 ast.NamedExpr)):
                value = node.value
                if value is None or not effective_taint(value, tainted):
                    continue
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    tainted |= assigned_names(t)
            elif isinstance(node, ast.For):
                if effective_taint(node.iter, tainted):
                    tainted |= assigned_names(node.target)
            elif isinstance(node, ast.comprehension):
                if effective_taint(node.iter, tainted):
                    tainted |= assigned_names(node.target)
        if len(tainted) == before:
            break
    return tainted


def effective_taint(expr, tainted: Set[str]) -> Set[str]:
    """Tainted names whose VALUE (not just metadata) feeds ``expr``.

    Skips subtrees that are static under trace: ``x.shape``-style
    metadata reads, ``isinstance``/``len``-style calls, and
    ``is (not) None`` comparisons.
    """
    out: Set[str] = set()

    def walk(n):
        if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
            return
        if isinstance(n, ast.Call):
            fname = last_component(n.func)
            if fname in _STATIC_CALLS:
                return
        if isinstance(n, ast.Compare) \
                and all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in n.ops):
            # identity compares are Python-object-level: always static
            # under trace, never concretize a tracer
            return
        if isinstance(n, ast.Compare) \
                and all(isinstance(op, (ast.In, ast.NotIn))
                        for op in n.ops) \
                and not (isinstance(n.left, ast.Constant)
                         and isinstance(n.left.value, (int, float,
                                                       complex))
                         and not isinstance(n.left.value, bool)):
            # `key in store` probes a container's KEYS — for the dict
            # stores this tree uses (param/aux dicts holding traced
            # VALUES) that is hashing, not a tracer comparison, so only
            # the left operand can concretize.  `tracer in xs` (left
            # tainted) still taints and still flags — and so does a
            # NUMERIC literal membership (`0 in x`): that shape is an
            # element test on a traced array, not a dict-key probe.
            walk(n.left)
            return
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                and n.id in tainted:
            out.add(n.id)
        for child in ast.iter_child_nodes(n):
            walk(child)

    walk(expr)
    return out


# --------------------------------------------------------------------------
# rules
# --------------------------------------------------------------------------

def _via(chain):
    """' (traced via a -> b)' suffix for findings inside helpers the
    taint reached through call boundaries."""
    return f" (traced via {' -> '.join(chain)})" if chain else ""

class _TracedRule(Rule):
    """Base: iterates (traced function, taint set) pairs per module.

    Since the CFG/dataflow engine (this PR), the pairs are the
    *interprocedural closure*: every traced function PLUS the
    same-module helpers its taint flows into through ``self._helper(x)``
    / ``helper(x)`` call boundaries (two levels deep — the single-hop
    blind spot of the PR 3 walk, closed).  Duplicate findings from a
    helper reached via several traced callers are deduped by the
    engine (core.analyze)."""

    def check_module(self, mod):
        from .dataflow import ModuleFunctions, traced_closure
        funcs = ModuleFunctions(mod.tree)
        emitted = set()
        for fn, static_nums, static_names in find_traced_functions(mod.tree):
            tainted = compute_taint(fn, static_nums, static_names)
            for target, taint, chain in traced_closure(
                    funcs, fn, tainted, compute_taint, effective_taint):
                key = (id(target), frozenset(taint))
                if key in emitted:
                    continue
                emitted.add(key)
                yield from self.check_traced(mod, target, taint,
                                             chain=chain)

    def check_traced(self, mod, fn, tainted, chain=()):
        return ()


class HostSyncRule(_TracedRule):
    id = "trace-host-sync"
    description = ("host-synchronizing call on a traced value inside a "
                   "jit-compiled function")

    def check_traced(self, mod, fn, tainted, chain=()):
        via = _via(chain)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute) \
                    and func.attr in _HOST_SYNC_METHODS \
                    and effective_taint(func.value, tainted):
                yield self.finding(
                    mod, node,
                    f".{func.attr}() on traced value inside traced function "
                    f"'{fn.name}'{via}: forces a host sync / fails under jit — "
                    f"keep the value on device or move the sync outside "
                    f"the compiled path")
            dname = dotted_name(func)
            if (dname in _HOST_SYNC_FUNCS
                    or (last_component(func) or "") == "device_get") \
                    and any(effective_taint(a, tainted) for a in node.args):
                yield self.finding(
                    mod, node,
                    f"{dname or last_component(func)}() on traced value "
                    f"inside traced function '{fn.name}'{via}: host sync under "
                    f"jit — use jnp/lax equivalents on device")
            if isinstance(func, ast.Name) and func.id in _CASTS \
                    and node.args \
                    and effective_taint(node.args[0], tainted):
                yield self.finding(
                    mod, node,
                    f"{func.id}() on traced value inside traced function "
                    f"'{fn.name}'{via}: concretizes the tracer (host sync / "
                    f"ConcretizationTypeError) — use .astype or jnp casts")
            if isinstance(func, ast.Name) and func.id == "print":
                yield self.finding(
                    mod, node,
                    f"print() inside traced function '{fn.name}'{via} runs at "
                    f"TRACE time (once), not per step — use "
                    f"jax.debug.print or log outside the compiled path")


class TracedBranchRule(_TracedRule):
    id = "trace-python-branch"
    description = ("Python control flow on a traced value inside a "
                   "jit-compiled function")

    def check_traced(self, mod, fn, tainted, chain=()):
        via = _via(chain)
        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While, ast.IfExp)):
                names = effective_taint(node.test, tainted)
                kind = {ast.If: "if", ast.While: "while",
                        ast.IfExp: "conditional expression"}[type(node)]
                if names:
                    yield self.finding(
                        mod, node,
                        f"Python {kind} on traced value(s) "
                        f"{sorted(names)} inside traced function "
                        f"'{fn.name}'{via}: branches are resolved at trace "
                        f"time — use jnp.where / lax.cond / lax.select")
            elif isinstance(node, ast.Assert):
                names = effective_taint(node.test, tainted)
                if names:
                    yield self.finding(
                        mod, node,
                        f"assert on traced value(s) {sorted(names)} inside "
                        f"traced function '{fn.name}'{via}: evaluated at trace "
                        f"time only — use checkify or a fused finite-guard")


class MutableGlobalRule(_TracedRule):
    id = "trace-mutable-global"
    description = ("module-level state mutated from inside a "
                   "jit-compiled function")

    def check_module(self, mod):
        module_names = set()
        for node in mod.tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    module_names |= assigned_names(t)
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                module_names |= assigned_names(node.target)
        self._module_names = module_names
        yield from super().check_module(mod)

    def _root_name(self, node):
        while isinstance(node, (ast.Attribute, ast.Subscript)):
            node = node.value
        return node.id if isinstance(node, ast.Name) else None

    def check_traced(self, mod, fn, tainted, chain=()):
        via = _via(chain)
        local = set(_tainted_params(fn))
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Name):
                        local.add(t.id)
        globals_hit = self._module_names - local

        def flag(node, root, how):
            return self.finding(
                mod, node,
                f"traced function '{fn.name}' {how} module-level "
                f"'{root}': runs at trace time only and races concurrent "
                f"tracers — thread state through the function instead")

        declared_global = {name for node in ast.walk(fn)
                           if isinstance(node, ast.Global)
                           for name in node.names}
        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                yield self.finding(
                    mod, node,
                    f"'global {', '.join(node.names)}' inside traced "
                    f"function '{fn.name}': writes happen at trace time "
                    f"only — return the value instead")
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, (ast.Subscript, ast.Attribute)):
                        root = self._root_name(t)
                        if root in globals_hit:
                            yield flag(node, root, "mutates")
                    elif isinstance(t, ast.Name) \
                            and t.id in declared_global:
                        yield flag(node, t.id, "rebinds")
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS:
                root = self._root_name(node.func.value)
                if root in globals_hit:
                    yield flag(node, root, "mutates")


class UnhashableStaticRule(Rule):
    id = "trace-unhashable-static"
    description = ("unhashable literal passed in a static_argnums/"
                   "static_argnames position (or to an lru_cache'd "
                   "function)")

    def check_module(self, mod):
        jitted: Dict[str, tuple] = {}   # name -> (static names, nums)
        cached: Set[str] = set()        # lru_cache'd defs: all args hashable
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and last_component(node.value.func) in ("jit", "pjit"):
                nums, names = _static_positions(node.value)
                if names or nums:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            jitted[t.id] = (names, nums)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for d in node.decorator_list:
                    base = d.func if isinstance(d, ast.Call) else d
                    if last_component(base) == "lru_cache":
                        cached.add(node.name)
                    if isinstance(d, ast.Call) \
                            and last_component(d.func) in ("jit", "pjit"):
                        nums, names = _static_positions(d)
                        if names or nums:
                            jitted[node.name] = (names, nums)

        if not jitted and not cached:
            return
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)):
                continue
            name = node.func.id
            if name in jitted:
                snames, snums = jitted[name]
                for i, a in enumerate(node.args):
                    if i in snums and isinstance(a, _UNHASHABLE):
                        yield self.finding(
                            mod, a,
                            f"unhashable literal in static position {i} of "
                            f"jitted '{name}': static args key the compile "
                            f"cache and must be hashable — use a tuple")
                for k in node.keywords:
                    if k.arg in snames and isinstance(k.value, _UNHASHABLE):
                        yield self.finding(
                            mod, k.value,
                            f"unhashable literal for static arg "
                            f"'{k.arg}' of jitted '{name}' — use a tuple")
            elif name in cached:
                for a in list(node.args) + [k.value for k in node.keywords]:
                    if isinstance(a, _UNHASHABLE):
                        yield self.finding(
                            mod, a,
                            f"unhashable literal passed to lru_cache'd "
                            f"'{name}': every argument is a cache key — "
                            f"use a tuple")
