"""Thread-safety rule.

The async input feed (PrefetchingIter / DevicePrefetcher producer
threads, DataLoader pools) made instance attributes shared state: an
attribute a producer thread writes and a public method reads without
the instance's lock is a torn read waiting for a scheduler change.  The
rule is mechanical (the TensorFlow lesson — invariants, not review):

``thread-unlocked-attr``
    For every class that starts a ``threading.Thread`` on one of its
    own methods (or subclasses ``Thread`` with a ``run``), every
    attribute that producer-side code writes must be accessed from
    public methods either under a ``with self.<lock>:`` block (any
    attribute holding a ``Lock``/``RLock``/``Condition``) or through an
    inherently thread-safe channel (``queue.Queue``/``Event``/
    ``Semaphore`` attributes are exempt).

Producer-side code is the transitive closure of ``self.X()`` calls from
the thread target — a helper the producer calls runs on the producer
thread too.
"""
from __future__ import annotations

import ast
from typing import Dict, Set

from .core import Rule, last_component

_LOCK_TYPES = {"Lock", "RLock", "Condition"}
_SAFE_TYPES = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue",
               "Event", "Semaphore", "BoundedSemaphore", "Barrier",
               "deque", "Counter"}
_MUTATORS = {"append", "extend", "insert", "add", "update", "pop",
             "popitem", "remove", "discard", "clear", "setdefault",
             "popleft", "appendleft", "__setitem__"}
# dunders that are part of the public protocol surface (__init__ is not:
# it runs before any thread exists)
_PUBLIC_DUNDERS = {"__iter__", "__next__", "__enter__", "__exit__",
                   "__len__", "__call__", "__contains__", "__getitem__"}


def _self_attr(node) -> str | None:
    """'X' for an ``self.X`` attribute node, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class UnlockedAttrRule(Rule):
    id = "thread-unlocked-attr"
    description = ("producer-thread-written attribute accessed from a "
                   "public method without the instance lock")

    def check_module(self, mod):
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(mod, node)

    # ---- per-class analysis ----
    def _check_class(self, mod, cls: ast.ClassDef):
        methods: Dict[str, ast.FunctionDef] = {
            n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}

        producers = self._producer_methods(cls, methods)
        if not producers:
            return

        locks, safe = self._attr_types(methods)
        written = self._producer_writes(producers, methods, safe)
        if not written:
            return

        for name, fn in methods.items():
            if name in producers or name == "__init__":
                continue
            if name.startswith("_") and name not in _PUBLIC_DUNDERS:
                continue
            yield from self._check_public(mod, cls, name, fn, written,
                                          locks, producers)

    def _producer_methods(self, cls, methods) -> Set[str]:
        """Thread targets + run() + the self-methods they call."""
        producers: Set[str] = set()
        if any(last_component(b) == "Thread" for b in cls.bases) \
                and "run" in methods:
            producers.add("run")
        for node in ast.walk(cls):
            if isinstance(node, ast.Call) \
                    and last_component(node.func) == "Thread":
                for k in node.keywords:
                    if k.arg == "target":
                        attr = _self_attr(k.value)
                        if attr in methods:
                            producers.add(attr)
        # transitive: helpers invoked as self.X() from producer code run
        # on the producer thread as well
        while True:
            grew = False
            for p in list(producers):
                for node in ast.walk(methods[p]):
                    if isinstance(node, ast.Call):
                        attr = _self_attr(node.func)
                        if attr in methods and attr not in producers:
                            producers.add(attr)
                            grew = True
            if not grew:
                break
        return producers

    def _attr_types(self, methods):
        """(lock attrs, thread-safe-channel attrs) by constructor name."""
        locks, safe = set(), set()
        for fn in methods.values():
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call):
                    ctor = last_component(node.value.func)
                    for t in node.targets:
                        attr = _self_attr(t)
                        if attr is None:
                            continue
                        if ctor in _LOCK_TYPES:
                            locks.add(attr)
                        elif ctor in _SAFE_TYPES:
                            safe.add(attr)
        return locks, safe

    def _producer_writes(self, producers, methods, safe) -> Dict[str, str]:
        """attr -> producer method that writes it (plain rebinds of the
        whole attribute and in-place mutation of its contents both
        count; safe-channel attrs are exempt)."""
        written: Dict[str, str] = {}

        def note(attr, pname):
            if attr is not None and attr not in safe:
                written.setdefault(attr, pname)

        for pname in producers:
            for node in ast.walk(methods[pname]):
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in targets:
                        note(_self_attr(t), pname)
                        if isinstance(t, ast.Subscript):
                            note(_self_attr(t.value), pname)
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _MUTATORS:
                    note(_self_attr(node.func.value), pname)
        return written

    def _check_public(self, mod, cls, name, fn, written, locks, producers):
        """Flag accesses of producer-written attrs on CFG nodes where no
        instance lock is *guaranteed* held (must-analysis: a path that
        reaches the access unlocked is a torn read on that path).

        Hosted on the CFG engine (this PR): ``with self._lock:`` blocks
        release on every exit path by construction, and intersection
        merge means an access reachable both locked and unlocked is
        still flagged — the lexical walk of PR 3 got the same answer
        only for straight-line code."""
        from .cfg import WITH_ENTER, WITH_EXIT, build_cfg, forward, \
            node_exprs
        from .dataflow import acquire_tokens, release_tokens

        cfg = build_cfg(fn)
        if cfg is None:
            return   # async def: not analyzed (clean skip, not a guess)

        def with_locks(stmt):
            held = set()
            for item in stmt.items:
                expr = item.context_expr
                # `with self._lock:` (Lock attrs used as ctx managers)
                if _self_attr(expr) in locks:
                    held.add(_self_attr(expr))
                # `with self._lock.acquire_timeout(...)`-style helpers
                elif isinstance(expr, ast.Call) \
                        and isinstance(expr.func, ast.Attribute) \
                        and _self_attr(expr.func.value) in locks:
                    held.add(_self_attr(expr.func.value))
            return frozenset(held)

        def transfer(node, fact):
            # leveled (token, depth) facts: a reentrant RLock's inner
            # exit must not release the outer hold
            if node.kind == WITH_ENTER:
                return acquire_tokens(fact, with_locks(node.stmt))
            if node.kind == WITH_EXIT:
                return release_tokens(fact, with_locks(node.stmt))
            return fact

        facts = forward(cfg, frozenset(), transfer, lambda a, b: a & b)
        for node in cfg.nodes():
            fact = facts.get(id(node))
            if fact is None or fact:
                continue     # unreachable, or under some instance lock
            for expr in node_exprs(node):
                for sub in ast.walk(expr):
                    attr = _self_attr(sub)
                    if attr in written:
                        yield self.finding(
                            mod, sub,
                            f"{cls.name}.{name} accesses self.{attr} "
                            f"without holding the instance lock, but "
                            f"'{written[attr]}' writes it from the "
                            f"producer thread — wrap the access in "
                            f"`with self."
                            f"{sorted(locks)[0] if locks else '<lock>'}:`"
                            f" or route it through a Queue/Event")
