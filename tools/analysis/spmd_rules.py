"""SPMD/sharding rules: the partitioning discipline mxlint was blind to.

The hand-written ``shard_map``/collective surface (``parallel/step.py``
grad reduction, ``quantize.py``'s int8 exchange, ``pipeline.py``,
``sequence.py``) is about to multiply under tensor-parallel serving
(ROADMAP item 1) — and SPMD bugs compile *fine* and fail only as silent
numerics or byte blowups at scale: a typo'd axis name surfaces as a deep
JAX error (or worse, a different reduction), an unsound
``out_specs=PartitionSpec()`` replication claim silently serves one
shard's values as "the" result, and a collective issued per Python loop
iteration unrolls into per-layer latency the compiler cannot fuse —
exactly the cost class *EQuARX* (arXiv:2506.17615) shows dominates
sharded decode.  These rules make that discipline mechanical:

``spmd-axis-unknown``       an axis-consuming primitive
                            (``lax.psum``/``pmean``/``all_gather``/
                            ``all_to_all``/``ppermute``/``axis_index``)
                            whose LITERAL axis name is not bound by the
                            enclosing ``shard_map``'s statically-known
                            mesh/spec axes — or is used with no
                            enclosing ``shard_map``/``pmap`` at all
``spmd-spec-arity``         ``in_specs``/``out_specs`` tuple length vs
                            the wrapped callable's positional arity, and
                            a literal ``PartitionSpec`` with more
                            entries than a statically-known argument
                            rank
``spmd-replication-claim``  an ``out_specs`` entry of
                            ``PartitionSpec()`` (replicated claim) on an
                            output with no ``psum``/``pmean``/
                            ``all_gather`` producer on its dataflow path
                            — the statically checkable core of
                            ``check_rep``
``spmd-collective-in-loop`` collectives issued inside Python
                            ``for``/``while`` bodies (one collective per
                            unrolled iteration instead of one fused /
                            scanned reduction)

Soundness stance (matches the rest of the engine): the rules only claim
an axis is *unbound* or a claim *unsound* when they can resolve every
relevant literal — a spec built by ``tree_map``, a mesh arriving through
``self.mesh``, or an axis passed as a parameter makes the binding OPEN
and the site is skipped, never guessed.  The runtime twin
(``parallel.mesh.shard_map``'s call-time axis validation) covers what
static resolution cannot.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import Rule, dotted_name, last_component, assigned_names
from .dataflow import (INLINE_DEPTH, ModuleFunctions, bind_args, iter_calls,
                       iter_scope_nodes, resolve_mesh_axes,
                       resolve_spec_axes, scope_assignments)

#: axis-consuming primitive -> positional slot of its axis_name argument
_AXIS_ARG = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "all_gather": 1,
    "all_to_all": 1, "ppermute": 1, "pshuffle": 1, "psum_scatter": 1,
    "pbroadcast": 1, "axis_index": 0, "axis_size": 0,
}

#: the subset that moves bytes over the interconnect (axis_index /
#: axis_size read a register — axis-consuming but free)
_COMM = frozenset(_AXIS_ARG) - {"axis_index", "axis_size"}

#: reducers whose result is identical on every participant — the
#: producers that make a ``PartitionSpec()`` replication claim honest
_REPLICATING = frozenset({"psum", "pmean", "pmax", "pmin", "all_gather"})

#: dotted roots TRANSPARENT to the replication walk: ``jnp.sum(x)``
#: transforms a device-varying value, it never launders it
_TRANSPARENT_ROOTS = frozenset({"jnp", "jax", "lax", "np", "numpy",
                                "math", "functools"})

#: builtins that are transparent the same way (``sum(leaves)`` varies
#: when its argument does); any OTHER unresolved bare-name call is an
#: import whose replication behavior is unknown
_TRANSPARENT_BUILTINS = frozenset({
    "sum", "min", "max", "abs", "float", "int", "bool", "list", "tuple",
    "zip", "enumerate", "sorted", "reversed", "map", "len", "range",
})

#: transforms that bind an ``axis_name=`` themselves (a psum under pmap
#: is bound by the pmap, not a shard_map)
_AXIS_BINDERS = {"pmap", "vmap", "xmap"}


def _collective_callee(call: ast.Call) -> Optional[str]:
    """The axis-consuming primitive a call invokes, or None.  Dotted
    receivers must be jax/lax-rooted (``self.all_gather(...)`` on a comm
    class is not ``lax.all_gather``); bare names are accepted (``from
    jax.lax import psum``)."""
    name = last_component(call.func)
    if name not in _AXIS_ARG:
        return None
    if isinstance(call.func, ast.Attribute):
        dn = dotted_name(call.func)
        root = dn.split(".")[0] if dn else None
        if root not in ("jax", "lax"):
            return None
    return name


def _axis_expr(call: ast.Call, name: str):
    """The axis_name argument expression of an axis-consuming call."""
    for k in call.keywords:
        if k.arg == "axis_name":
            return k.value
    pos = _AXIS_ARG[name]
    if len(call.args) > pos:
        return call.args[pos]
    return None


def _literal_axes(expr, bindings: Dict[str, str]) -> Optional[Set[str]]:
    """Axis names when the expression is a string literal, a tuple of
    them, or a parameter bound to a literal at an inlined call site;
    None when not statically known."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return {expr.value}
    if isinstance(expr, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for el in expr.elts:
            sub = _literal_axes(el, bindings)
            if sub is None:
                return None
            out |= sub
        return out
    if isinstance(expr, ast.Name) and expr.id in bindings:
        return {bindings[expr.id]}
    return None


# --------------------------------------------------------------------------
# shard_map region discovery
# --------------------------------------------------------------------------

@dataclasses.dataclass
class Region:
    """One ``shard_map``-wrapped body (or ``pmap(..., axis_name=...)``):
    the statically-known axis binding the body's collectives run
    under."""
    fn: Optional[ast.FunctionDef]    # wrapped body, when resolvable
    anchor: ast.AST                  # the wrapping call (finding anchor)
    axes: Set[str]                   # known bound axis names
    closed: bool                     # True = `axes` is the FULL set
    mesh_axes: Optional[Set[str]]    # mesh axes when the mesh is literal
    in_specs: Optional[ast.AST] = None
    out_specs: Optional[ast.AST] = None
    apply_call: Optional[ast.Call] = None   # shard_map(f, ...)(a, b)
    assigns: Dict[str, ast.AST] = dataclasses.field(default_factory=dict)


def _shard_map_aliases(tree: ast.Module) -> Set[str]:
    out = {"shard_map"}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == "shard_map":
                    out.add(a.asname or a.name)
    return out


def _wrapper_call(node, aliases: Set[str]) -> Optional[ast.Call]:
    """The config-carrying Call of a shard_map wrapper: ``shard_map(...)``
    itself or ``functools.partial(shard_map, ...)``."""
    if not isinstance(node, ast.Call):
        return None
    if last_component(node.func) in aliases:
        return node
    if last_component(node.func) == "partial" and node.args \
            and last_component(node.args[0]) in aliases:
        return node
    return None


def _axis_binder_call(node) -> Optional[Tuple[ast.Call, Optional[str]]]:
    """``(call, axis_name literal | None)`` for pmap/vmap/xmap wrappers
    carrying an ``axis_name=`` binding."""
    if not isinstance(node, ast.Call):
        return None
    target = node
    if last_component(node.func) == "partial" and node.args \
            and last_component(node.args[0]) in _AXIS_BINDERS:
        pass
    elif last_component(node.func) not in _AXIS_BINDERS:
        return None
    for k in target.keywords:
        if k.arg == "axis_name":
            if isinstance(k.value, ast.Constant) \
                    and isinstance(k.value.value, str):
                return node, k.value.value
            return node, None
    return node, None


def _sm_kwargs(call: ast.Call):
    kw = {k.arg: k.value for k in call.keywords if k.arg}
    return kw.get("mesh"), kw.get("in_specs"), kw.get("out_specs")


def _parent_functions(tree: ast.Module) -> Dict[int, ast.AST]:
    """id(FunctionDef) -> innermost enclosing FunctionDef | module."""
    out: Dict[int, ast.AST] = {}

    def walk(node, owner):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out[id(child)] = owner
                walk(child, child)
            else:
                walk(child, owner)

    walk(tree, tree)
    return out


def _region_axes(mesh_expr, in_specs, out_specs, assigns):
    """``(axes, closed, mesh_axes)``: ONLY a literal mesh closes the
    axis set — a mesh axis may legitimately be reduced over without
    appearing in any spec (the mixed-axis TP-over-dp shape), so spec
    literals must never close the binding on their own.  With a
    non-literal mesh the binding is OPEN: collectives inside are not
    judged, and the runtime ``validate_specs`` covers the spec-typo
    class at call time."""
    if mesh_expr is not None:
        axes, closed = resolve_mesh_axes(mesh_expr, assigns)
        if closed:
            return set(axes), True, set(axes)
    return set(), False, None


#: per-tree region memo: three of the four rules need the regions of
#: the same module, and discovery walks the whole AST — compute once.
#: Keyed by id() with a strong reference to the tree held in the value
#: (so the id cannot be reused while the entry lives); bounded.
_REGION_MEMO: Dict[int, Tuple[ast.Module, List["Region"]]] = {}


def find_regions(tree: ast.Module) -> List[Region]:
    hit = _REGION_MEMO.get(id(tree))
    if hit is not None and hit[0] is tree:
        return hit[1]
    regions = _find_regions(tree)
    if len(_REGION_MEMO) > 64:
        _REGION_MEMO.clear()
    _REGION_MEMO[id(tree)] = (tree, regions)
    return regions


def _find_regions(tree: ast.Module) -> List[Region]:
    aliases = _shard_map_aliases(tree)
    defs: Dict[str, List[ast.FunctionDef]] = {}
    for n in ast.walk(tree):
        if isinstance(n, ast.FunctionDef):
            defs.setdefault(n.name, []).append(n)
    parents = _parent_functions(tree)
    regions: List[Region] = []
    seen_calls: Dict[int, Region] = {}

    def resolve_fn(name: Optional[str]) -> Optional[ast.FunctionDef]:
        cands = defs.get(name or "", [])
        return cands[0] if len(cands) == 1 else None

    def make_region(call, fn, scope, apply_call=None):
        assigns = scope_assignments(
            scope if isinstance(scope, ast.FunctionDef) else None, tree)
        mesh_expr, in_specs, out_specs = _sm_kwargs(call)
        axes, closed, mesh_axes = _region_axes(mesh_expr, in_specs,
                                               out_specs, assigns)
        reg = Region(fn=fn, anchor=call, axes=axes, closed=closed,
                     mesh_axes=mesh_axes, in_specs=in_specs,
                     out_specs=out_specs, apply_call=apply_call,
                     assigns=assigns)
        regions.append(reg)
        seen_calls[id(call)] = reg
        return reg

    # decorator form: @shard_map(...) / @functools.partial(shard_map, ...)
    # (the pipeline.py idiom) — and pmap-style axis binders
    for fns in defs.values():
        for fn in fns:
            scope = parents.get(id(fn), tree)
            for d in fn.decorator_list:
                call = _wrapper_call(d, aliases)
                if call is not None:
                    make_region(call, fn, scope)
                    continue
                binder = _axis_binder_call(d)
                if binder is not None:
                    call, axis = binder
                    regions.append(Region(
                        fn=fn, anchor=call,
                        axes={axis} if axis else set(),
                        closed=axis is not None, mesh_axes=None))

    # call form: shard_map(body, mesh=..., ...) — possibly applied
    # immediately — scanned scope by scope so spec names resolve where
    # the call is written
    scopes = [tree] + [n for n in ast.walk(tree)
                       if isinstance(n, ast.FunctionDef)]
    for scope in scopes:
        scope_binds = scope_assignments(
            scope if isinstance(scope, ast.FunctionDef) else None, tree)
        for node in iter_scope_nodes(scope):
            if not isinstance(node, ast.Call):
                continue
            inner = node.func if isinstance(node.func, ast.Call) else None
            if inner is not None and _wrapper_call(inner, aliases) \
                    is not None and id(inner) not in seen_calls:
                # immediate application: shard_map(f, ...)(a, b)
                fn = None
                if inner.args and isinstance(inner.args[0], ast.Name) \
                        and last_component(inner.args[0]) not in aliases:
                    fn = resolve_fn(inner.args[0].id)
                make_region(inner, fn, scope, apply_call=node)
            elif _wrapper_call(node, aliases) is not None \
                    and id(node) not in seen_calls:
                fn = None
                first = node.args[0] if node.args else None
                if isinstance(first, ast.Name) \
                        and first.id not in aliases:
                    fn = resolve_fn(first.id)
                if fn is not None or node.keywords:
                    make_region(node, fn, scope)
            elif isinstance(node.func, ast.Name) and _wrapper_call(
                    scope_binds.get(node.func.id), aliases) is not None:
                # stored-curried form (the serving builder idiom):
                #   wrap = functools.partial(shard_map, mesh=..., ...)
                #   ...
                #   wrap(body, in_specs=..., out_specs=...)
                # The application names the body and carries the specs;
                # the stored partial carries the mesh.  Same-scope
                # single-assignment only (scope_assignments) — a wrap
                # that crosses a function boundary stays an OPEN-mesh
                # anchor region, judged by runtime validate_specs.
                curried = _wrapper_call(scope_binds[node.func.id], aliases)
                if last_component(curried.func) == "partial":
                    fn = None
                    first = node.args[0] if node.args else None
                    if isinstance(first, ast.Name) \
                            and first.id not in aliases:
                        fn = resolve_fn(first.id)
                    if fn is not None or node.keywords:
                        p_mesh, p_in, p_out = _sm_kwargs(curried)
                        a_mesh, a_in, a_out = _sm_kwargs(node)
                        mesh_expr = a_mesh if a_mesh is not None else p_mesh
                        in_specs = a_in if a_in is not None else p_in
                        out_specs = a_out if a_out is not None else p_out
                        axes, closed, mesh_axes = _region_axes(
                            mesh_expr, in_specs, out_specs, scope_binds)
                        regions.append(Region(
                            fn=fn, anchor=node, axes=axes, closed=closed,
                            mesh_axes=mesh_axes, in_specs=in_specs,
                            out_specs=out_specs, assigns=scope_binds))
            elif isinstance(node.func, ast.Name) or \
                    isinstance(node.func, ast.Attribute):
                binder = _axis_binder_call(node)
                if binder is not None and node.args \
                        and isinstance(node.args[0], ast.Name):
                    call, axis = binder
                    fn = resolve_fn(node.args[0].id)
                    if fn is not None:
                        regions.append(Region(
                            fn=fn, anchor=call,
                            axes={axis} if axis else set(),
                            closed=axis is not None, mesh_axes=None))
    return regions


def _own_and_nested(fn) -> List[ast.AST]:
    """``fn`` plus every def/lambda lexically nested in it — a
    ``lax.scan`` body (or inline lambda) defined inside a shard_map
    body runs under the same axis binding."""
    out = [fn]
    for n in ast.walk(fn):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)) and n is not fn:
            out.append(n)
    return out


# --------------------------------------------------------------------------
# spmd-axis-unknown
# --------------------------------------------------------------------------

class SpmdAxisUnknownRule(Rule):
    id = "spmd-axis-unknown"
    default_severity = "error"
    description = ("collective/axis_index over an axis name not bound by "
                   "the enclosing shard_map's mesh or specs")

    def check_module(self, mod):
        funcs = ModuleFunctions(mod.tree)
        regions = find_regions(mod.tree)
        region_fns = {id(r.fn) for r in regions if r.fn is not None}
        covered: Set[int] = set()
        findings: List = []
        seen_visits: Set[tuple] = set()
        # bodies a wrapper NAMES but the module cannot uniquely resolve
        # (two same-named defs) are still covered — never guessed at
        aliases = _shard_map_aliases(mod.tree)
        defs: Dict[str, List[ast.FunctionDef]] = {}
        for n in ast.walk(mod.tree):
            if isinstance(n, ast.FunctionDef):
                defs.setdefault(n.name, []).append(n)
        for call in ast.walk(mod.tree):
            if not isinstance(call, ast.Call):
                continue
            if _wrapper_call(call, aliases) is None \
                    and _axis_binder_call(call) is None:
                continue
            first = call.args[0] if call.args else None
            if isinstance(first, ast.Name) and first.id not in aliases:
                for fn in defs.get(first.id, ()):
                    for sub in _own_and_nested(fn):
                        covered.add(id(sub))
            elif isinstance(first, ast.Lambda):
                # a shard_map-wrapped lambda body: inside a binder, but
                # its axis set is not judged (a lambda has no name to
                # resolve) — covered, never swept as unbound
                for sub in _own_and_nested(first):
                    covered.add(id(sub))
        for reg in regions:
            # a spec literal naming an axis outside a LITERAL mesh is
            # the same typo class, caught at the wrapper itself
            if reg.mesh_axes is not None:
                for spec in (reg.in_specs, reg.out_specs):
                    if spec is None:
                        continue
                    axes, closed = resolve_spec_axes(spec, reg.assigns)
                    for a in sorted(axes - reg.mesh_axes):
                        findings.append(self.finding(
                            mod, spec,
                            f"spec names axis '{a}' but the shard_map "
                            f"mesh only defines "
                            f"{sorted(reg.mesh_axes)} — a typo'd spec "
                            f"axis fails deep inside jax (or silently "
                            f"changes the partitioning)"))
            if reg.fn is None:
                continue
            self._visit(mod, funcs, reg.fn, reg, {}, (), covered,
                        seen_visits, findings, INLINE_DEPTH, region_fns)
        # the outside sweep: literal-axis primitives with NO enclosing
        # binder at all (lambda bodies included — a collective hidden
        # in a lambda escapes no contract)
        for fn in (n for n in ast.walk(mod.tree)
                   if isinstance(n, (ast.FunctionDef, ast.Lambda))):
            if id(fn) in covered:
                continue
            for call in iter_calls(fn):
                name = _collective_callee(call)
                if name is None:
                    continue
                axes = _literal_axes(_axis_expr(call, name), {})
                if not axes:
                    continue
                findings.append(self.finding(
                    mod, call,
                    f"lax.{name} over axis {sorted(axes)} inside "
                    f"'{getattr(fn, 'name', '<lambda>')}': no "
                    f"enclosing shard_map/pmap binds this axis — the "
                    f"call only compiles (and only means anything) "
                    f"under a mesh context that defines it; wrap the "
                    f"body in mesh.shard_map / jax.shard_map with the "
                    f"axis in its mesh"))
        return findings

    def _visit(self, mod, funcs, fn, reg, bindings, chain, covered,
               seen, findings, depth, region_fns=frozenset()):
        key = (id(fn), id(reg.anchor), frozenset(bindings.items()))
        if key in seen:
            return
        seen.add(key)
        for sub in _own_and_nested(fn):
            covered.add(id(sub))
        via = f" (reached via {' -> '.join(chain)})" if chain else ""
        for sub in _own_and_nested(fn):
            if sub is not fn and id(sub) in region_fns:
                # a NESTED shard_map body carries its own axis binding
                # (the TP-inside-dp shape): judged by its own region's
                # visit, never against this one's axes
                continue
            for call in iter_calls(sub):
                name = _collective_callee(call)
                if name is not None and reg.closed:
                    axes = _literal_axes(_axis_expr(call, name), bindings)
                    if axes:
                        for a in sorted(axes - reg.axes):
                            findings.append(self.finding(
                                mod, call,
                                f"lax.{name} over axis '{a}' inside "
                                f"shard_map body "
                                f"'{getattr(sub, 'name', '<lambda>')}'"
                                f"{via}, but "
                                f"the enclosing shard_map only binds "
                                f"axes {sorted(reg.axes)} — an unbound "
                                f"axis name fails deep inside jax (or, "
                                f"if it exists on an OUTER transform, "
                                f"reduces over the wrong devices)"))
                if depth > 0 and name is None:
                    callee = funcs.resolve_call(sub, call)
                    if callee is None or id(callee) in region_fns:
                        continue
                    new_bind = {}
                    params = [a.arg for a in callee.args.posonlyargs
                              + callee.args.args]
                    offset = 1 if params[:1] == ["self"] \
                        and isinstance(call.func, ast.Attribute) else 0
                    for i, a in enumerate(call.args):
                        idx = i + offset
                        if isinstance(a, ast.Constant) \
                                and isinstance(a.value, str) \
                                and idx < len(params):
                            new_bind[params[idx]] = a.value
                    for k in call.keywords:
                        if k.arg and isinstance(k.value, ast.Constant) \
                                and isinstance(k.value.value, str):
                            new_bind[k.arg] = k.value.value
                    self._visit(mod, funcs, callee, reg, new_bind,
                                chain + (getattr(sub, "name",
                                                 "<lambda>"),),
                                covered, seen, findings, depth - 1,
                                region_fns)


# --------------------------------------------------------------------------
# spmd-spec-arity
# --------------------------------------------------------------------------

class SpmdSpecArityRule(Rule):
    id = "spmd-spec-arity"
    default_severity = "error"
    description = ("in_specs/out_specs arity vs the wrapped callable, "
                   "and PartitionSpec rank vs statically-known argument "
                   "rank")

    def check_module(self, mod):
        for reg in find_regions(mod.tree):
            if reg.in_specs is None and reg.out_specs is None:
                continue
            yield from self._check_in_arity(mod, reg)
            yield from self._check_out_arity(mod, reg)
            yield from self._check_ranks(mod, reg)

    # -- in_specs length vs positional arity ---------------------------
    def _check_in_arity(self, mod, reg):
        if reg.fn is None or not isinstance(reg.in_specs, ast.Tuple):
            return
        n = len(reg.in_specs.elts)
        if any(isinstance(e, ast.Starred) for e in reg.in_specs.elts):
            return
        args = reg.fn.args
        params = [a.arg for a in args.posonlyargs + args.args]
        required = len(params) - len(args.defaults)
        if args.vararg is None and n > len(params):
            yield self.finding(
                mod, reg.in_specs,
                f"in_specs has {n} entries but '{reg.fn.name}' takes at "
                f"most {len(params)} positional argument(s) — shard_map "
                f"zips specs with arguments one-to-one, so the extra "
                f"spec(s) raise (or shift every later binding by one)")
        elif n < required:
            yield self.finding(
                mod, reg.in_specs,
                f"in_specs has {n} entries but '{reg.fn.name}' requires "
                f"at least {required} positional argument(s) — each "
                f"argument needs its own spec")

    # -- out_specs length vs returned-tuple length ----------------------
    def _check_out_arity(self, mod, reg):
        if reg.fn is None or not isinstance(reg.out_specs, ast.Tuple):
            return
        lengths = set()
        for node in iter_scope_nodes(reg.fn):
            if isinstance(node, ast.Return) and node.value is not None:
                if not isinstance(node.value, ast.Tuple):
                    return          # tuple-valued name: cannot align
                if any(isinstance(e, ast.Starred)
                       for e in node.value.elts):
                    return
                lengths.add(len(node.value.elts))
        if len(lengths) != 1:
            return
        r = lengths.pop()
        s = len(reg.out_specs.elts)
        if s != r:
            yield self.finding(
                mod, reg.out_specs,
                f"out_specs has {s} entries but '{reg.fn.name}' returns "
                f"{r} value(s) — the output pytree and its specs must "
                f"match element-for-element")

    # -- PartitionSpec entry count vs known argument rank ----------------
    def _check_ranks(self, mod, reg):
        if reg.apply_call is None or not isinstance(reg.in_specs,
                                                    ast.Tuple):
            return
        ranks = self._static_ranks(reg)
        for i, arg in enumerate(reg.apply_call.args):
            if isinstance(arg, ast.Starred):
                break   # the star expands to an unknown count: every
                        # later AST index is misaligned with its spec
            if i >= len(reg.in_specs.elts):
                break
            if not isinstance(arg, ast.Name) or arg.id not in ranks:
                continue
            spec = reg.in_specs.elts[i]
            if isinstance(spec, ast.Name):
                spec = reg.assigns.get(spec.id, spec)
            if not (isinstance(spec, ast.Call)
                    and last_component(spec.func) in ("PartitionSpec",
                                                      "P")):
                continue
            entries = len(spec.args)
            rank = ranks[arg.id]
            if entries > rank:
                yield self.finding(
                    mod, reg.apply_call.args[i],
                    f"in_specs[{i}] is a PartitionSpec with {entries} "
                    f"entries but '{arg.id}' has rank {rank} — a spec "
                    f"longer than the array rank raises at trace time")

    @staticmethod
    def _static_ranks(reg) -> Dict[str, int]:
        """Names whose array rank is statically evident from their
        single assignment (``x = jnp.zeros((4, 8))`` and friends)."""
        ranks: Dict[str, int] = {}
        for name, value in reg.assigns.items():
            if not isinstance(value, ast.Call):
                continue
            lc = last_component(value.func)
            if lc in ("zeros", "ones", "empty", "full") and value.args \
                    and isinstance(value.args[0], ast.Tuple):
                ranks[name] = len(value.args[0].elts)
            elif lc == "arange":
                ranks[name] = 1
            elif lc == "reshape":
                if len(value.args) == 1 \
                        and isinstance(value.args[0], ast.Tuple):
                    ranks[name] = len(value.args[0].elts)
                elif value.args and all(
                        isinstance(a, (ast.Constant, ast.Name,
                                       ast.UnaryOp))
                        for a in value.args) and len(value.args) > 1:
                    ranks[name] = len(value.args)
        return ranks


# --------------------------------------------------------------------------
# spmd-replication-claim
# --------------------------------------------------------------------------

_CLEAN, _UNKNOWN, _DIRTY = "clean", "unknown", "dirty"


class SpmdReplicationClaimRule(Rule):
    id = "spmd-replication-claim"
    default_severity = "error"
    description = ("out_specs replication claim (PartitionSpec()) with "
                   "no psum/pmean/all_gather on the output's dataflow "
                   "path")

    def check_module(self, mod):
        funcs = ModuleFunctions(mod.tree)
        self._fn_memo: Dict[tuple, str] = {}
        for reg in find_regions(mod.tree):
            if reg.fn is None or reg.out_specs is None:
                continue
            claims = self._claims(reg)
            if claims is None:
                continue
            varying = self._varying_params(reg)
            closure = self._closure(reg.fn, varying, funcs,
                                    INLINE_DEPTH)
            for ret in iter_scope_nodes(reg.fn):
                if not isinstance(ret, ast.Return) or ret.value is None:
                    continue
                yield from self._check_return(mod, funcs, reg, claims,
                                              closure, ret)

    # ------------------------------------------------------------------
    def _claims(self, reg):
        """``"all"`` | set of claimed output positions | None (no
        literal replication claim to judge)."""
        spec = reg.out_specs
        if isinstance(spec, ast.Name):
            spec = reg.assigns.get(spec.id, spec)
        if self._is_empty_pspec(spec, reg):
            return "all"
        if isinstance(spec, ast.Tuple):
            claimed = {i for i, el in enumerate(spec.elts)
                       if self._is_empty_pspec(el, reg)}
            return claimed or None
        return None

    @staticmethod
    def _is_empty_pspec(expr, reg) -> bool:
        if isinstance(expr, ast.Name):
            expr = reg.assigns.get(expr.id, expr)
        return (isinstance(expr, ast.Call)
                and last_component(expr.func) in ("PartitionSpec", "P")
                and not expr.args and not expr.keywords)

    def _varying_params(self, reg) -> Set[str]:
        """Parameters whose per-device values can differ: sharded (spec
        with axes) or unresolvable specs.  ``in_specs=PartitionSpec()``
        (jax's pytree-prefix "everything replicated" form) makes NO
        parameter varying; with no alignable literal in_specs at all,
        EVERY parameter is assumed varying — the rule then only passes
        outputs that carry a reducer (or launder through an
        unresolvable call)."""
        args = reg.fn.args
        params = [a.arg for a in args.posonlyargs + args.args
                  if a.arg != "self"]
        if args.vararg is not None:
            params.append(args.vararg.arg)
        spec = reg.in_specs
        if isinstance(spec, ast.Name):
            spec = reg.assigns.get(spec.id, spec)
        if self._is_empty_pspec(spec, reg):
            return set()
        if not isinstance(spec, ast.Tuple):
            return set(params)
        varying = set()
        elts = spec.elts
        for i, p in enumerate(params):
            if i >= len(elts):
                varying.add(p)       # *leaves tail: sharded batch data
                continue
            axes, closed = resolve_spec_axes(elts[i], reg.assigns)
            if axes or not closed:
                varying.add(p)
        return varying

    # ------------------------------------------------------------------
    def _check_return(self, mod, funcs, reg, claims, closure, ret):
        if claims == "all":
            targets = [(None, ret.value)]
        else:
            if not isinstance(ret.value, ast.Tuple) \
                    or len(ret.value.elts) != len(reg.out_specs.elts):
                return
            targets = [(i, ret.value.elts[i]) for i in sorted(claims)]
        for pos, expr in targets:
            verdict = self._verdict(expr, closure, funcs, reg.fn,
                                    INLINE_DEPTH)
            if verdict == _DIRTY:
                where = "the output" if pos is None \
                    else f"output {pos}"
                yield self.finding(
                    mod, expr,
                    f"out_specs claims {where} of '{reg.fn.name}' is "
                    f"replicated (PartitionSpec()), but its value "
                    f"derives from per-device inputs with no psum/"
                    f"pmean/all_gather on the dataflow path — the "
                    f"claim is unsound: devices hold DIFFERENT values "
                    f"and jax will either reject it (check_rep) or "
                    f"silently serve one shard's answer; reduce before "
                    f"claiming replication, or shard the output spec")

    def _verdict(self, expr, varying, funcs, owner, depth) -> str:
        flags: Set[str] = set()
        self._scan(expr, varying, funcs, owner, depth, flags)
        if _CLEAN in flags:
            return _CLEAN
        if _UNKNOWN in flags:
            return _UNKNOWN
        if _DIRTY in flags:
            return _DIRTY
        return _CLEAN       # constants / replicated-only: identical

    @staticmethod
    def _ifexp_callees(func) -> Set[str]:
        """Possible callee names of a conditionally-dispatched call —
        ``(lax.pmean if mean else lax.psum)(x, "dp")``, the step.py
        loss-reduction idiom."""
        if isinstance(func, ast.IfExp):
            return (SpmdReplicationClaimRule._ifexp_callees(func.body)
                    | SpmdReplicationClaimRule._ifexp_callees(
                        func.orelse))
        name = last_component(func)
        return {name} if name else {"<unknown>"}

    def _scan(self, expr, varying, funcs, owner, depth, flags):
        if isinstance(expr, ast.Call):
            if isinstance(expr.func, ast.IfExp):
                names = self._ifexp_callees(expr.func)
                if names <= _REPLICATING:
                    flags.add(_CLEAN)     # every branch reduces
                else:
                    # mixed or unknown dispatch: never claim unsound
                    flags.add(_UNKNOWN)
                return
            name = _collective_callee(expr)
            if name in _REPLICATING:
                flags.add(_CLEAN)
                return
            if name in ("axis_index",):
                flags.add(_DIRTY)
                return
            callee = funcs.resolve_call(owner, expr) \
                if isinstance(owner, ast.FunctionDef) else None
            if callee is not None and depth > 0:
                seed = bind_args(
                    callee, expr,
                    lambda e: self._verdict(e, varying, funcs, owner,
                                            depth) == _DIRTY)
                flags.add(self._fn_verdict(callee, frozenset(seed),
                                           funcs, depth - 1))
                return
            if callee is None and isinstance(expr.func, ast.Attribute):
                dn = dotted_name(expr.func)
                root = dn.split(".")[0] if dn else None
                if root not in _TRANSPARENT_ROOTS:
                    # method call: transparent when the receiver itself
                    # is a device-varying array expression
                    # (``(x / s).astype(...)`` chains deviceness) or a
                    # reduced one (``psum(x).reshape(...)`` stays
                    # identical); anything else — a foreign object, a
                    # cross-module helper like
                    # ``_quantize.reduce_gradients`` — has unknown
                    # replication behavior and must never be claimed
                    # unsound
                    rflags: Set[str] = set()
                    self._scan(expr.func.value, varying, funcs, owner,
                               depth, rflags)
                    if _CLEAN in rflags:
                        flags.add(_CLEAN)
                        return
                    if _DIRTY in rflags and _UNKNOWN not in rflags:
                        flags.add(_DIRTY)
                        for a in list(expr.args) \
                                + [k.value for k in expr.keywords]:
                            self._scan(a, varying, funcs, owner, depth,
                                       flags)
                        return
                    flags.add(_UNKNOWN)
                    return
            if callee is None and isinstance(expr.func, ast.Name) \
                    and expr.func.id not in _TRANSPARENT_BUILTINS:
                # unresolved bare-name call (an import from another
                # module): it may itself reduce — unknown, not dirty
                flags.add(_UNKNOWN)
                return
            for a in list(expr.args) + [k.value for k in expr.keywords]:
                self._scan(a, varying, funcs, owner, depth, flags)
            return
        if isinstance(expr, ast.Name) and isinstance(expr.ctx, ast.Load) \
                and expr.id in varying:
            flags.add(_DIRTY)
        for child in ast.iter_child_nodes(expr):
            self._scan(child, varying, funcs, owner, depth, flags)

    def _fn_verdict(self, fn, seed: frozenset, funcs, depth) -> str:
        key = (id(fn), seed, depth)
        if key in self._fn_memo:
            return self._fn_memo[key]
        self._fn_memo[key] = _UNKNOWN      # cycle guard
        closure = self._closure(fn, set(seed), funcs, depth)
        flags: Set[str] = set()
        for node in iter_scope_nodes(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                flags.add(self._verdict(node.value, closure, funcs, fn,
                                        depth))
        out = (_DIRTY if _DIRTY in flags else
               _UNKNOWN if _UNKNOWN in flags else _CLEAN)
        self._fn_memo[key] = out
        return out

    def _closure(self, fn, seed: Set[str], funcs, depth) -> Set[str]:
        """Names whose values can differ per device, closed over the
        function's assignments (a ``psum`` on the right-hand side stops
        the propagation — its result is identical everywhere)."""
        varying = set(seed)
        for _ in range(3):
            before = len(varying)
            for node in iter_scope_nodes(fn):
                if isinstance(node, (ast.Assign, ast.AnnAssign,
                                     ast.AugAssign, ast.NamedExpr)):
                    value = node.value
                    if value is None:
                        continue
                    if self._verdict(value, varying, funcs, fn,
                                     depth) == _DIRTY:
                        targets = node.targets \
                            if isinstance(node, ast.Assign) \
                            else [node.target]
                        for t in targets:
                            varying |= assigned_names(t)
                elif isinstance(node, (ast.For, ast.comprehension)):
                    if self._verdict(node.iter, varying, funcs, fn,
                                     depth) == _DIRTY:
                        varying |= assigned_names(node.target)
            if len(varying) == before:
                break
        return varying


# --------------------------------------------------------------------------
# spmd-collective-in-loop
# --------------------------------------------------------------------------

class SpmdCollectiveInLoopRule(Rule):
    id = "spmd-collective-in-loop"
    default_severity = "error"
    description = ("collective issued inside a Python for/while body — "
                   "one collective per unrolled iteration instead of a "
                   "fused/scanned reduction")

    def check_module(self, mod):
        fns = [n for n in ast.walk(mod.tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for fn in fns:
            for node in iter_scope_nodes(fn):
                if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                    roots = list(node.body)
                    if isinstance(node, ast.While):
                        roots.append(node.test)
                    yield from self._flag(mod, roots, "a Python loop")
                elif isinstance(node, (ast.ListComp, ast.SetComp,
                                       ast.DictComp, ast.GeneratorExp)):
                    roots = [node.key, node.value] \
                        if isinstance(node, ast.DictComp) else [node.elt]
                    for gen in node.generators:
                        roots.extend(gen.ifs)
                    yield from self._flag(mod, roots, "a comprehension")

    def _flag(self, mod, roots, where):
        for root in roots:
            for call in iter_calls(root):
                name = _collective_callee(call)
                if name is None or name not in _COMM:
                    continue
                # one-argument lookalikes (mx.distributed.all_gather)
                # never carry an axis_name
                if len(call.args) + len(call.keywords) < 2 \
                        and not any(k.arg == "axis_name"
                                    for k in call.keywords):
                    continue
                yield self.finding(
                    mod, call,
                    f"lax.{name} inside {where}: the trace unrolls one "
                    f"collective per iteration — per-layer collective "
                    f"latency XLA cannot fuse, the byte pattern the "
                    f"sharded cost budgets exist to catch.  Stack/"
                    f"concatenate the operands and issue ONE collective, "
                    f"or move the loop into lax.scan so the compiler "
                    f"can pipeline it")
