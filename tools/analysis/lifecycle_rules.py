"""Resource-lifecycle rule: leaks on exceptional paths.

``resource-leak-on-error``
    A resource with teardown obligations — an ``open()`` file handle, a
    *started* ``threading.Thread``, a ``PrefetchingIter``/
    ``DevicePrefetcher``/``DataLoader`` feed, a ``ThreadPoolExecutor``/
    ``Pool`` — is acquired into a local variable, and some CFG path can
    exit the function via an exception without reaching its release
    (``close``/``join``/``shutdown``/...).  This is the exact bug class
    PRs 2 and 4 fixed by hand in review (producer threads leaked when a
    wrapped iterator raised; prefetchers left running when predict's
    loop died) — now it is mechanical.

    The rule is deliberately conservative about ownership: tracking
    *ends* (no finding) the moment the resource escapes the function —
    returned, yielded, stored on ``self``/an object/a container,
    aliased to another name, or passed to another call (ownership
    transfer).  A ``with`` block is the canonical fix and never
    tracked.  Only the exceptional exit is checked: returning an open
    resource on the normal path is how constructors work.
"""
from __future__ import annotations

import ast
from typing import Dict, Optional, Set, Tuple

from .cfg import STMT, WITH_ENTER, build_cfg, forward, node_exprs
from .core import Rule, last_component
from .dataflow import _calls_of_stmt, iter_scope_nodes

# ctor name -> (kind, release verbs). Thread is special-cased: it only
# becomes a leak candidate once .start() runs (an unstarted Thread
# object is garbage-collected like any object).
_RESOURCE_CTORS = {
    "open": ("file handle", ("close",)),
    "PrefetchingIter": ("prefetcher", ("close",)),
    "DevicePrefetcher": ("prefetcher", ("close",)),
    "DataLoader": ("data loader", ("close",)),
    "ThreadPoolExecutor": ("thread pool", ("shutdown",)),
    "ProcessPoolExecutor": ("process pool", ("shutdown",)),
    "Pool": ("worker pool", ("close", "terminate", "join")),
    "socket": ("socket", ("close",)),
    "TemporaryFile": ("temp file", ("close",)),
    "NamedTemporaryFile": ("temp file", ("close",)),
}
_THREAD_CTORS = {"Thread"}
_RELEASE_VERBS = {"close", "join", "shutdown", "terminate", "stop",
                  "release", "__exit__"}


def _resource_ctor(value) -> Optional[Tuple[str, Tuple[str, ...]]]:
    if isinstance(value, ast.Call):
        spec = _RESOURCE_CTORS.get(last_component(value.func) or "")
        if spec:
            return spec
    return None


class ResourceLeakRule(Rule):
    id = "resource-leak-on-error"
    description = ("locally-acquired Thread/file/prefetcher/pool can "
                   "leak: an exception path exits the function before "
                   "its close()/join()")

    def check_module(self, mod):
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.FunctionDef):
                yield from self._check_function(mod, node)
            # async defs: build_cfg declines them; nothing to report —
            # "not analyzed" must never decay into findings or crashes

    def _check_function(self, mod, fn):
        cfg = build_cfg(fn)
        if cfg is None:
            return
        # pass 1 (lexical): candidate locals + thread locals + names
        # that ever escape.  A name that escapes anywhere is dropped
        # entirely — path-sensitive ownership is not worth the FPs.
        acquires: Dict[int, Tuple[str, str, Tuple[str, ...]]] = {}
        thread_locals: Set[str] = set()
        for node in self._own_nodes(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                spec = _resource_ctor(node.value)
                if spec:
                    acquires[id(node)] = (name,) + spec
                elif isinstance(node.value, ast.Call) \
                        and last_component(node.value.func) \
                        in _THREAD_CTORS:
                    thread_locals.add(name)
        escaped = self._escaped_names(
            fn, {a[0] for a in acquires.values()} | thread_locals)
        tracked = ({a[0] for a in acquires.values()} | thread_locals) \
            - escaped
        if not tracked:
            return

        # pass 2 (paths): forward "held resources" facts.  A fact is a
        # frozenset of (name, acquire line, kind, release verbs).
        def transfer(cnode, fact):
            s = cnode.stmt
            if s is None:
                return fact
            if cnode.kind == WITH_ENTER:
                # `with open(...) as f` manages release itself; a bare
                # `with x:` also releases x — clear anything rebound or
                # context-managed here
                names = {v.optional_vars.id for v in s.items
                         if isinstance(v.optional_vars, ast.Name)}
                names |= {v.context_expr.id for v in s.items
                          if isinstance(v.context_expr, ast.Name)}
                return frozenset(h for h in fact if h[0] not in names)
            out = set(fact)
            added = set()
            if cnode.kind == STMT and isinstance(s, ast.Assign):
                if id(s) in acquires:
                    name, kind, verbs = acquires[id(s)]
                    if name in tracked:
                        out = {h for h in out if h[0] != name}
                        added.add((name, s.lineno, kind, verbs))
                        out.add((name, s.lineno, kind, verbs))
                else:
                    for t in s.targets:
                        if isinstance(t, ast.Name):
                            out = {h for h in out if h[0] != t.id}
            # node_exprs keeps loop/branch headers from re-counting
            # their bodies' calls (the bodies have their own nodes)
            for expr in node_exprs(cnode):
                for call in self._calls_in(expr):
                    f = call.func
                    if isinstance(f, ast.Attribute) \
                            and isinstance(f.value, ast.Name):
                        name = f.value.id
                        if f.attr == "start" and name in thread_locals \
                                and name in tracked:
                            out = {h for h in out if h[0] != name}
                            added.add((name, call.lineno,
                                       "started thread", ("join",)))
                            out.add((name, call.lineno,
                                     "started thread", ("join",)))
                        elif f.attr in _RELEASE_VERBS:
                            out = {h for h in out if h[0] != name}
            # the acquiring statement's own exception edge carries the
            # PRE-STATEMENT state: if open()/start() itself raises, the
            # new handle does not exist — and the store never ran, so a
            # REBOUND name (f = open(y) over an earlier f = open(x))
            # still holds the old handle, which therefore still leaks
            return frozenset(out), (fact if added else frozenset(out))

        facts = forward(cfg, frozenset(), transfer,
                        lambda a, b: a | b)
        leaked = facts.get(id(cfg.raise_exit))
        if not leaked:
            return
        for name, line, kind, verbs in sorted(leaked):
            anchor = type("L", (), {"lineno": line, "col_offset": 0})
            yield self.finding(
                mod, anchor,
                f"{kind} '{name}' acquired here can leak: an exception "
                f"path exits '{fn.name}' before "
                f"{' / '.join(f'{name}.{v}()' for v in verbs)} — "
                f"release it in a try/finally (or a with block), the "
                f"way the async-feed teardown does")

    # ---- lexical helpers (canonical pruned walks from dataflow) ----
    @staticmethod
    def _own_nodes(fn):
        return iter_scope_nodes(fn)

    @staticmethod
    def _calls_in(stmt):
        return _calls_of_stmt(stmt)

    @staticmethod
    def _bare_loads(expr, candidates: Set[str]) -> Set[str]:
        """Candidate names loaded *as values* in ``expr``.  A name used
        only as an attribute receiver (``f.read()``, ``t.is_alive()``)
        is a *use*, not an ownership transfer, and is exempt."""
        out: Set[str] = set()

        def walk(n):
            if isinstance(n, ast.Attribute):
                base = n.value
                while isinstance(base, ast.Attribute):
                    base = base.value
                if isinstance(base, ast.Name):
                    return          # pure receiver chain: exempt
                walk(n.value)
                return
            if isinstance(n, ast.Name) \
                    and isinstance(n.ctx, ast.Load) \
                    and n.id in candidates:
                out.add(n.id)
            for child in ast.iter_child_nodes(n):
                walk(child)

        walk(expr)
        return out

    @classmethod
    def _escaped_names(cls, fn, candidates: Set[str]) -> Set[str]:
        """Names whose ownership leaves the function (returned, yielded,
        stored on self/containers, aliased, or passed to another call):
        no leak verdict is ever issued for these."""
        escaped: Set[str] = set()
        for node in cls._own_nodes(fn):
            if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
                if node.value is not None and isinstance(
                        node.value, (ast.Name, ast.Tuple, ast.List)):
                    # `return f` hands the handle out; `return f.read()`
                    # does not (and its raise-path leak stays checkable)
                    escaped |= cls._bare_loads(node.value, candidates)
            elif isinstance(node, ast.Assign):
                escaped |= cls._bare_loads(node.value, candidates)
            elif isinstance(node, ast.Call):
                for a in list(node.args) + [k.value for k in
                                            node.keywords]:
                    escaped |= cls._bare_loads(a, candidates)
        return escaped
