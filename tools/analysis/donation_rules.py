"""Donation-safety rule.

``TrainStep(donate_batch=True)`` and ``jax.jit(..., donate_argnums=...)``
hand the input buffers to XLA: after the call the donated arrays are
DELETED, and touching them again raises (CPU backend) or reads freed
HBM semantics (the reason the async-feed docs say "safe only when each
batch is consumed exactly once").  The hazard is invisible locally —
the donation happens at the call site, the crash at the later use.

``donated-batch-reuse``
    Within one function, flags any read of a variable after it was
    passed in a donated position of a call to (a) a local name bound to
    ``jax.jit(fn, donate_argnums=...)`` or (b) a local name bound to
    ``TrainStep(..., donate_batch=True)`` (every batch argument of a
    donate_batch step call is donated).  Rebinding the variable clears
    the hazard.  Statement order is textual: a use *before* the donating
    call inside the same loop body is not flagged (the rule is a
    first-order linter, not a dataflow engine — see docs/analysis.md).
"""
from __future__ import annotations

import ast
from typing import Dict, List

from .core import Rule, last_component


def _walk_scope(scope):
    """Yield nodes of one function/module scope WITHOUT descending into
    nested function/class bodies (those are separate scopes, analyzed on
    their own)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class DonatedReuseRule(Rule):
    id = "donated-batch-reuse"
    description = "variable used after its buffer was donated to XLA"

    def check_module(self, mod):
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Module)):
                yield from self._check_scope(mod, node)

    @staticmethod
    def _donators(scope) -> Dict[str, object]:
        """name -> 'all' (donate_batch step) or set of donated positions."""
        out: Dict[str, object] = {}
        for node in _walk_scope(scope):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            call = node.value
            callee = last_component(call.func)
            spec = None
            if callee in ("jit", "pjit"):
                for k in call.keywords:
                    if k.arg == "donate_argnums" \
                            and isinstance(k.value, (ast.Tuple, ast.List)):
                        spec = {e.value for e in k.value.elts
                                if isinstance(e, ast.Constant)
                                and isinstance(e.value, int)}
                    elif k.arg == "donate_argnums" \
                            and isinstance(k.value, ast.Constant) \
                            and isinstance(k.value.value, int):
                        spec = {k.value.value}
            elif callee in ("TrainStep", "EvalStep"):
                for k in call.keywords:
                    if k.arg == "donate_batch" \
                            and isinstance(k.value, ast.Constant) \
                            and k.value.value is True:
                        spec = "all"
            if spec:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = spec
        return out

    def _check_scope(self, mod, scope):
        donators = self._donators(scope)
        if not donators:
            return

        # events in evaluation order: loads fire where the name is read;
        # donations fire at the END of their call; stores fire at the END
        # of their whole statement (Python evaluates the RHS first, so
        # `x = g(x)` donates x, then the store re-binds it clean).  For
        # loop targets the binding point is the header (iter end), not
        # the body end.
        events: List[tuple] = []  # (line, col, prio, kind, name, node)

        def store_events(target, anchor):
            end = (anchor.end_lineno or anchor.lineno,
                   anchor.end_col_offset or anchor.col_offset)
            for n in ast.walk(target):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                    events.append((end[0], end[1], 2, "store", n.id, n))

        for node in _walk_scope(scope):
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Load):
                    events.append((node.lineno, node.col_offset, 0,
                                   "load", node.id, node))
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                for t in (node.targets if isinstance(node, ast.Assign)
                          else [node.target]):
                    store_events(t, node)
            elif isinstance(node, ast.AugAssign):
                if isinstance(node.target, ast.Name):
                    # x += v reads x too
                    events.append((node.target.lineno,
                                   node.target.col_offset, 0, "load",
                                   node.target.id, node.target))
                store_events(node.target, node)
            elif isinstance(node, ast.NamedExpr):
                store_events(node.target, node)
            elif isinstance(node, ast.For):
                store_events(node.target, node.iter)
            elif isinstance(node, ast.withitem) \
                    and node.optional_vars is not None:
                store_events(node.optional_vars, node.context_expr)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id in donators:
                spec = donators[node.func.id]
                for i, a in enumerate(node.args):
                    if not isinstance(a, ast.Name):
                        continue
                    if spec == "all" or i in spec:
                        events.append((node.end_lineno or node.lineno,
                                       node.end_col_offset or
                                       node.col_offset, 1,
                                       "donate", a.id, node))
        events.sort(key=lambda e: (e[0], e[1], e[2]))

        donated: Dict[str, int] = {}
        for line, _col, _p, kind, name, node in events:
            if kind == "load" and name in donated:
                yield self.finding(
                    mod, node,
                    f"'{name}' is read after being donated on line "
                    f"{donated[name]}: the buffer belongs to XLA now "
                    f"(deleted array) — copy it first, re-bind the name, "
                    f"or drop donate_batch/donate_argnums for this path")
                del donated[name]  # one finding per donation
            elif kind == "donate":
                donated[name] = line
            elif kind == "store":
                donated.pop(name, None)
