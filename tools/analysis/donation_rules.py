"""Donation-safety rule.

``TrainStep(donate_batch=True)`` and ``jax.jit(..., donate_argnums=...)``
hand the input buffers to XLA: after the call the donated arrays are
DELETED, and touching them again raises (CPU backend) or reads freed
HBM semantics (the reason the async-feed docs say "safe only when each
batch is consumed exactly once").  The hazard is invisible locally —
the donation happens at the call site, the crash at the later use.

``donated-batch-reuse``
    Within one function, flags any read of a variable after it was
    passed in a donated position of a call to (a) a local name bound to
    ``jax.jit(fn, donate_argnums=...)`` or (b) a local name bound to
    ``TrainStep(..., donate_batch=True)`` (every batch argument of a
    donate_batch step call is donated).  Rebinding the variable clears
    the hazard.  Statement order is textual: a use *before* the donating
    call inside the same loop body is not flagged (the rule is a
    first-order linter, not a dataflow engine — see docs/analysis.md).
"""
from __future__ import annotations

import ast
from typing import Dict, List

from .core import Rule, last_component


def _walk_scope(scope):
    """Yield nodes of one function/module scope WITHOUT descending into
    nested function/class bodies (those are separate scopes, analyzed on
    their own)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class DonatedReuseRule(Rule):
    id = "donated-batch-reuse"
    description = "variable used after its buffer was donated to XLA"

    def check_module(self, mod):
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Module)):
                yield from self._check_scope(mod, node)

    @staticmethod
    def _donators(scope) -> Dict[str, object]:
        """name -> 'all' (donate_batch step) or set of donated positions."""
        out: Dict[str, object] = {}
        for node in _walk_scope(scope):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            call = node.value
            callee = last_component(call.func)
            spec = None
            if callee in ("jit", "pjit"):
                for k in call.keywords:
                    if k.arg == "donate_argnums" \
                            and isinstance(k.value, (ast.Tuple, ast.List)):
                        spec = {e.value for e in k.value.elts
                                if isinstance(e, ast.Constant)
                                and isinstance(e.value, int)}
                    elif k.arg == "donate_argnums" \
                            and isinstance(k.value, ast.Constant) \
                            and isinstance(k.value.value, int):
                        spec = {k.value.value}
            elif callee in ("TrainStep", "EvalStep"):
                for k in call.keywords:
                    if k.arg == "donate_batch" \
                            and isinstance(k.value, ast.Constant) \
                            and k.value.value is True:
                        spec = "all"
            if spec:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = spec
        return out

    @staticmethod
    def _node_events(cnode, donators):
        """Ordered ``(line, col, prio, kind, name, node)`` events of one
        CFG node — the PR 3 textual evaluation model, applied WITHIN a
        node (cross-node ordering is the CFG's job): loads fire at the
        name's position, donations at the END of their call, stores at
        the END of their whole statement (Python evaluates the RHS
        first, so ``x = g(x)`` donates x, then the store re-binds it
        clean; but ``out = (g(x), x.sum())`` reads x AFTER the donating
        call and is flagged).  For loop headers the target's binding
        point is the iterable's end; nested def/lambda bodies are
        separate scopes and contribute nothing here."""
        from .cfg import LOOP, WITH_ENTER, node_exprs
        from .dataflow import iter_scope_nodes
        events: List[tuple] = []

        def store_events(target, anchor):
            end = (anchor.end_lineno or anchor.lineno,
                   anchor.end_col_offset or anchor.col_offset)
            for n in ast.walk(target):
                if isinstance(n, ast.Name) and isinstance(n.ctx,
                                                          ast.Store):
                    events.append((end[0], end[1], 2, "store", n.id, n))

        s = cnode.stmt
        if cnode.kind == LOOP and isinstance(s, ast.For):
            walk_roots = [s.iter]
            store_events(s.target, s.iter)
        elif cnode.kind == WITH_ENTER:
            walk_roots = [i.context_expr for i in s.items]
            for i in s.items:
                if i.optional_vars is not None:
                    store_events(i.optional_vars, i.context_expr)
        else:
            walk_roots = [e for e in node_exprs(cnode)
                          if not isinstance(e, (ast.FunctionDef,
                                                ast.AsyncFunctionDef,
                                                ast.ClassDef))]
        for root in walk_roots:
            for n in iter_scope_nodes(root):
                if isinstance(n, ast.Name) and isinstance(n.ctx,
                                                          ast.Load):
                    events.append((n.lineno, n.col_offset, 0, "load",
                                   n.id, n))
                elif isinstance(n, (ast.Assign, ast.AnnAssign)):
                    for t in (n.targets if isinstance(n, ast.Assign)
                              else [n.target]):
                        store_events(t, n)
                elif isinstance(n, ast.AugAssign):
                    if isinstance(n.target, ast.Name):   # x += v reads x
                        events.append((n.target.lineno,
                                       n.target.col_offset, 0, "load",
                                       n.target.id, n.target))
                    store_events(n.target, n)
                elif isinstance(n, ast.NamedExpr):
                    store_events(n.target, n)
                elif isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Name) \
                        and n.func.id in donators:
                    spec = donators[n.func.id]
                    for i, a in enumerate(n.args):
                        if isinstance(a, ast.Name) \
                                and (spec == "all" or i in spec):
                            events.append((n.end_lineno or n.lineno,
                                           n.end_col_offset
                                           or n.col_offset, 1,
                                           "donate", a.id, n))
        events.sort(key=lambda e: (e[0], e[1], e[2]))
        return events

    def _node_pass(self, cnode, donators, entry):
        """Replay one node's events over the entry fact.  Returns
        ``(hits, out_fact)`` — hits are ``(name, donated line, load
        node)`` triples; the fact is a frozenset of ``(name, line)``."""
        donated: Dict[str, int] = {}
        for name, line in sorted(entry):
            donated.setdefault(name, line)
        hits = []
        for _l, _c, _p, kind, name, node in self._node_events(cnode,
                                                              donators):
            if kind == "load" and name in donated:
                hits.append((name, donated[name], node))
                del donated[name]    # one finding per donation
            elif kind == "donate":
                donated[name] = _l
            elif kind == "store":
                donated.pop(name, None)
        return hits, frozenset(donated.items())

    def _check_scope(self, mod, scope):
        """CFG-hosted (this PR): donated-ness is a forward dataflow fact
        of ``(name, donation line)`` pairs, so the hazard now survives
        control flow the PR 3 textual-order walk could not represent —
        branches that donate on one arm, and loop back edges (the loop
        header's re-bind is what makes per-iteration donation clean) —
        while WITHIN a statement the original evaluation-order model
        still applies (a read in the same statement as the donating
        call, after it, is still a use-after-donate)."""
        from .cfg import build_cfg, forward
        donators = self._donators(scope)
        if not donators:
            return
        cfg = build_cfg(scope)
        if cfg is None:
            return   # async scope: not analyzed

        def transfer(cnode, fact):
            return self._node_pass(cnode, donators, fact)[1]

        facts = forward(cfg, frozenset(), transfer, lambda a, b: a | b)
        reported = set()
        for cnode in cfg.nodes():
            fact = facts.get(id(cnode))
            if fact is None:
                continue
            for name, line, node in self._node_pass(cnode, donators,
                                                    fact)[0]:
                if id(node) in reported:
                    continue
                reported.add(id(node))
                yield self.finding(
                    mod, node,
                    f"'{name}' is read after being donated on line "
                    f"{line}: the buffer belongs to XLA now (deleted "
                    f"array) — copy it first, re-bind the name, or "
                    f"drop donate_batch/donate_argnums for this path")
