"""mxlint CLI: ``python -m tools.analysis mxnet_tpu/``.

Exit code 0 = no unsuppressed error-severity findings (the tier-1 gate
in tests/test_mxlint.py asserts exactly this), 1 = findings, 2 = usage.

Incremental mode is the default: per-file records are cached under
``<root>/.mxlint_cache/`` keyed by content hash, so a re-run after a
small edit re-analyzes only the edited files (``--no-cache`` opts out;
``--changed`` additionally restricts the analyzed set to what
``git diff --name-only`` reports).  ``--format sarif`` emits a SARIF
2.1.0 log for CI annotation tooling.
"""
from __future__ import annotations

import argparse
import sys
from pathlib import Path

from .core import (Config, analyze, default_rules, exit_code, summarize,
                   to_json)
from .sarif import to_sarif


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.analysis",
        description="mxlint: trace-safety / thread-safety / donation / "
                    "concurrency / lifecycle / registry static analysis "
                    "(docs/analysis.md)")
    parser.add_argument("paths", nargs="*", default=[],
                        help="files or directories to analyze "
                             "(default: mxnet_tpu; with --changed, the "
                             "whole gated surface — mxnet_tpu, tools, "
                             "examples, bench.py — so an edit anywhere "
                             "the gate covers is seen)")
    parser.add_argument("--format", choices=("human", "json", "sarif"),
                        default="human", dest="fmt",
                        help="output format (sarif = SARIF 2.1.0 for CI "
                             "annotation ingestion)")
    parser.add_argument("--json", action="store_true",
                        help="shorthand for --format json (suppressed "
                             "findings included, marked)")
    parser.add_argument("--disable", action="append", default=[],
                        metavar="RULE", help="disable a rule id")
    parser.add_argument("--severity", action="append", default=[],
                        metavar="RULE=LEVEL",
                        help="override a rule's severity "
                             "(error|warning|info)")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="also print suppressed findings")
    parser.add_argument("--root", default=None,
                        help="repo root for relative paths + docs "
                             "(default: cwd)")
    parser.add_argument("--changed", action="store_true",
                        help="lint only files git reports as changed "
                             "(diff vs HEAD + untracked); no-op when "
                             "git is unavailable")
    parser.add_argument("--no-cache", action="store_true",
                        help="disable the .mxlint_cache/ incremental "
                             "cache (always re-analyze)")
    parser.add_argument("--cache-dir", default=None,
                        help="cache directory (default: "
                             "<root>/.mxlint_cache)")
    parser.add_argument("--list-rules", action="store_true",
                        help="list rule ids and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in default_rules():
            print(f"{rule.id:26s} {rule.description}")
        return 0

    severities = {}
    for spec in args.severity:
        if "=" not in spec:
            parser.error(f"--severity expects RULE=LEVEL, got {spec!r}")
        rid, sev = spec.split("=", 1)
        severities[rid] = sev
    config = Config(disabled=args.disable, severities=severities)

    root = Path(args.root) if args.root else Path.cwd()
    paths = args.paths
    if not paths:
        # defaults are anchored at --root (explicit paths stay
        # cwd-relative, normal CLI semantics).  With --changed the
        # default set is the whole gated surface: "lint what I
        # changed" silently skipping a changed tools/ or examples/
        # file would be a false all-clear
        defaults = ("mxnet_tpu", "tools", "examples", "bench.py") \
            if args.changed else ("mxnet_tpu",)
        paths = [root / p for p in defaults if (root / p).exists()]
    findings = analyze(paths, config=config, root=root,
                       use_cache=not args.no_cache,
                       cache_dir=args.cache_dir,
                       changed_only=args.changed)

    fmt = "json" if args.json else args.fmt
    if fmt == "json":
        print(to_json(findings))
    elif fmt == "sarif":
        print(to_sarif(findings))
    else:
        for f in findings:
            if f.suppressed and not args.show_suppressed:
                continue
            print(f.render())
        print(summarize(findings))
    return exit_code(findings)


if __name__ == "__main__":
    sys.exit(main())
