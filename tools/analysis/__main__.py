"""mxlint CLI: ``python -m tools.analysis mxnet_tpu/``.

Exit code 0 = no unsuppressed error-severity findings (the tier-1 gate
in tests/test_mxlint.py asserts exactly this), 1 = findings, 2 = usage.
"""
from __future__ import annotations

import argparse
import sys
from pathlib import Path

from .core import (Config, analyze, default_rules, exit_code, summarize,
                   to_json)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.analysis",
        description="mxlint: trace-safety / thread-safety / donation / "
                    "registry static analysis (docs/analysis.md)")
    parser.add_argument("paths", nargs="*", default=["mxnet_tpu"],
                        help="files or directories to analyze "
                             "(default: mxnet_tpu)")
    parser.add_argument("--json", action="store_true",
                        help="emit findings as JSON (suppressed ones "
                             "included, marked)")
    parser.add_argument("--disable", action="append", default=[],
                        metavar="RULE", help="disable a rule id")
    parser.add_argument("--severity", action="append", default=[],
                        metavar="RULE=LEVEL",
                        help="override a rule's severity "
                             "(error|warning|info)")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="also print suppressed findings")
    parser.add_argument("--root", default=None,
                        help="repo root for relative paths + docs "
                             "(default: cwd)")
    parser.add_argument("--list-rules", action="store_true",
                        help="list rule ids and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in default_rules():
            print(f"{rule.id:26s} {rule.description}")
        return 0

    severities = {}
    for spec in args.severity:
        if "=" not in spec:
            parser.error(f"--severity expects RULE=LEVEL, got {spec!r}")
        rid, sev = spec.split("=", 1)
        severities[rid] = sev
    config = Config(disabled=args.disable, severities=severities)

    root = Path(args.root) if args.root else Path.cwd()
    findings = analyze(args.paths, config=config, root=root)

    if args.json:
        print(to_json(findings))
    else:
        for f in findings:
            if f.suppressed and not args.show_suppressed:
                continue
            print(f.render())
        print(summarize(findings))
    return exit_code(findings)


if __name__ == "__main__":
    sys.exit(main())
